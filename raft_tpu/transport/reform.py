"""Process-group re-formation: the elastic-recovery loop for multihost.

``transport/multihost.py`` states the recovery contract for a mirrored
multi-process cluster: detection is a progress watchdog (a fixed JAX mesh
gives no failure notification), re-formation is a restart into a fresh
runtime over the processes that remain, and state comes from stable
storage. Round 4 proved the 2-process->1 half of that contract
(tests/test_multiprocess.py). This module supplies the piece an N>=3
cluster additionally needs: **agreement on who survived, who coordinates
the next runtime, and which checkpoint the new epoch restores from** —
plus the rejoin path for a process that comes back from the dead.

The agreement medium is a shared **rendezvous directory** on common
storage — the stand-in for the deployment's supervisor or config service
(k8s, etcd, a cluster manager); the reference has no analogue (its whole
"cluster" is goroutines in one process, main.go:12). The protocol:

- Every process writes a *heartbeat* file each committed round:
  ``hb-{pid}.json`` = {time, epoch, round, wm, ckpt}. Heartbeats double
  as the failure detector's evidence and the checkpoint directory.
- Epochs are numbered runtime generations. ``epoch-{n}.json`` (written
  atomically, write-once) fixes the new generation: its member set, the
  JAX coordinator address, the checkpoint to restore from, and the
  replica rows considered dead. Processes poll for epochs that include
  them and re-exec into the new runtime.
- **Coordinator derivation**: the survivor with the lowest pid among
  fresh heartbeats proposes the next epoch — a deterministic rule every
  survivor evaluates identically, so losing the ORIGINAL coordinator
  (process 0, the jax.distributed rendezvous host) just promotes the
  next-lowest survivor. Write-once epoch files make a racing duplicate
  proposal harmless (first rename wins; the loser re-reads).
- **Checkpoint election**: the proposer restores the epoch from the
  fresh checkpoint with the HIGHEST watermark. Every process only acks
  entries after its own checkpoint covers them, and mirrored processes
  commit identical prefixes, so the max-watermark checkpoint covers
  every acked entry of every survivor — the durability fence holds
  across re-formation.
- **Rejoin**: a restarted process writes ``join-{pid}`` and waits.
  Members see the pending join on their next round; the current
  coordinator proposes an epoch with the joiner added back and its
  replica row no longer marked dead. The joiner's engine state comes
  entirely from the elected checkpoint (the snapshot-install of the
  mirrored model); its device row then heals forward through the
  engine's repair window / snapshot heal like any lapped replica.
  A *survivor* that finds itself excluded from a newly published epoch
  (its heartbeat went stale past the detector window while it was
  wedged — GC pause, NFS stall, clock skew) takes the same path:
  ``reform`` falls through to ``request_join`` instead of proposing
  epochs the members will never join.

**Death certificates (round 17)**: staleness is a GUESS — the detector
cannot distinguish a dead process from a slow one, which is why the
reform loop pays a settle window before proposing. A supervisor that
reaped the process (``waitpid`` after ``kill -9``) has POSITIVE
evidence, and the cluster tier's :class:`ClusterSupervisor` owns
exactly that evidence. ``declare_dead`` publishes it as a write-once
``dead-{pid}.json`` certificate stamped with the victim's last
published ``beat``; ``fresh_peers`` excludes certified pids
immediately (no staleness wait), and ``reform`` skips the settle
window entirely when every missing member is certified — reformation
driven by real process death converges in one poll instead of
``stall_s + settle``. The certificate self-heals: a heartbeat whose
``beat`` PROGRESSES past the certified beat proves the declaration
stale (false positive, pid reuse) and retires the file.

**Failure detector (single-clock-domain)**: heartbeat freshness is
derived from per-writer stamp *progression*, observed entirely on the
OBSERVER's monotonic clock (ADVICE r5 #1). Each heartbeat carries a
``beat`` counter (plus the wall stamp, kept for humans); ``fresh_peers``
remembers, per writer, the last distinct (beat, stamp) pair it saw and
WHEN it saw it on ``time.monotonic()``. A peer is fresh iff its pair
changed within the last ``stale_s`` of observation. No cross-host clock
comparison exists anywhere in the protocol: wall-clock skew between
processes — any amount, in either direction, including NTP steps
mid-run — cannot mis-detect a live peer as dead or hold a dead peer
fresh. The price is one bounded latency term: a peer seen for the FIRST
time by a given observer (fresh process, or a restart that lost its
observation state) counts as fresh until ``stale_s`` of observation
passes without progression, so detecting an already-dead peer takes up
to one staleness window from first sight instead of zero. For an
observer that was already watching when the peer died, detection
latency is the same as before. Deadline loops (``reform``,
``await_epoch_including_me``) run on ``time.monotonic()`` for the same
reason: an NTP step must not expire — or immortalize — a re-formation
budget.
"""

from __future__ import annotations

import json
import os
import socket
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from raft_tpu.obs import blackbox


def _atomic_write(path: str, payload: dict) -> bool:
    """Write-once atomic JSON publish: False if ``path`` already exists
    (or appears concurrently — os.link semantics make the publish
    exclusive even when two proposers race)."""
    if os.path.exists(path):
        return False
    # unique tmp per attempt: pid alone collides for two writers in one
    # process (threads) or across pid reuse after a kill
    import uuid

    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    try:
        os.link(tmp, path)          # fails if a racer published first
        return True
    except FileExistsError:
        return False
    finally:
        os.unlink(tmp)


@dataclass
class Epoch:
    n: int
    members: List[int]              # original process ids, sorted
    coord: str                      # jax.distributed coordinator address
    ckpt: Optional[str]             # checkpoint to restore (None: fresh)
    dead_rows: List[int] = field(default_factory=list)

    @property
    def num_processes(self) -> int:
        return len(self.members)

    def process_id(self, pid: int) -> int:
        return self.members.index(pid)


class Rendezvous:
    """One process's handle on the shared re-formation directory."""

    def __init__(self, root: str, pid: int):
        self.root = root
        self.pid = pid
        os.makedirs(root, exist_ok=True)
        self._beats = 0
        self._seen: Dict[int, tuple] = {}
        #   pid -> ((beat, stamp), monotonic time this observer first saw
        #   that exact pair) — the progression detector's whole state
        #   (see fresh_peers / the module-doc failure-detector note)

    # ---- heartbeats ----------------------------------------------------
    def heartbeat(self, epoch: int, round_no: int, wm: int,
                  ckpt: Optional[str]) -> None:
        path = os.path.join(self.root, f"hb-{self.pid}.json")
        tmp = path + ".tmp"
        self._beats += 1
        with open(tmp, "w") as f:
            # ``beat`` is the progression counter freshness derives from
            # (it advances even if the wall clock is frozen or stepped
            # backward); ``time`` is kept for humans reading the files
            json.dump({"time": time.time(), "beat": self._beats,
                       "epoch": epoch, "round": round_no, "wm": wm,
                       "ckpt": ckpt}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def my_heartbeat(self) -> Optional[dict]:
        """This process's last published heartbeat (stale or not) — the
        restart path reads it to learn which epoch it last participated
        in and which checkpoint it last fenced acks behind."""
        path = os.path.join(self.root, f"hb-{self.pid}.json")
        try:
            return json.load(open(path))
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def fresh_peers(self, stale_s: float) -> Dict[int, dict]:
        """pids (self included) whose heartbeat PROGRESSED within the
        last ``stale_s`` seconds of this observer's ``time.monotonic()``
        — the failure detector's survivor estimate.

        Progression, not wall-clock age: the observer remembers each
        writer's last distinct (beat, stamp) pair and when it saw it on
        its OWN monotonic clock; a peer is fresh iff the pair changed
        within the window. No cross-host clock comparison — skew of any
        magnitude cannot mis-detect (module-doc failure-detector note).
        A writer seen for the first time counts as fresh from that
        sighting: detection of an already-dead peer costs at most one
        staleness window of observation, which is the bounded price of
        skew immunity."""
        now = time.monotonic()
        out: Dict[int, dict] = {}
        for f in os.listdir(self.root):
            # exact-shape match: a concurrent writer's hb-N.json.tmp must
            # not be parsed (os.replace makes the .json itself atomic)
            if not (f.startswith("hb-") and f.endswith(".json")):
                continue
            try:
                hb = json.load(open(os.path.join(self.root, f)))
            except (json.JSONDecodeError, OSError):
                continue                      # torn concurrent write
            pid = int(f[3:-5])
            mark = (hb.get("beat"), hb["time"])
            seen = self._seen.get(pid)
            if seen is None or seen[0] != mark:
                self._seen[pid] = (mark, now)     # progressed: stamp NOW
                out[pid] = hb
            elif now - seen[1] <= stale_s:
                out[pid] = hb                     # unchanged but recent
        # positive evidence overrides recency: a certified-dead peer is
        # out NOW (no staleness wait) — unless its beat progressed past
        # the certificate, which proves the declaration stale
        for pid, cert in self.declared_dead().items():
            hb = out.get(pid)
            if (hb is not None and cert.get("beat") is not None
                    and (hb.get("beat") or 0) > cert["beat"]):
                self.clear_dead(pid)              # false positive: retire
            else:
                out.pop(pid, None)
        return out

    # ---- death certificates (positive evidence) ------------------------
    def declare_dead(self, pid: int, evidence: str = "waitpid") -> None:
        """Publish positive death evidence for member ``pid`` (module
        doc, death certificates): the caller REAPED the process or
        otherwise knows it is gone — not a staleness guess. Stamped
        with the victim's last published ``beat`` so a later heartbeat
        that progresses past it can prove the certificate stale."""
        hb = None
        try:
            hb = json.load(open(os.path.join(self.root,
                                             f"hb-{pid}.json")))
        except (OSError, json.JSONDecodeError):
            pass
        path = os.path.join(self.root, f"dead-{pid}.json")
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"time": time.time(), "evidence": evidence,
                       "beat": None if hb is None else hb.get("beat"),
                       "by": self.pid}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        blackbox.mark("declare_dead", rv_pid=self.pid, dead=pid,
                      evidence=evidence)

    def declared_dead(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        for f in os.listdir(self.root):
            if f.startswith("dead-") and f.endswith(".json"):
                try:
                    out[int(f[5:-5])] = json.load(
                        open(os.path.join(self.root, f)))
                except (OSError, ValueError):
                    continue
        return out

    def clear_dead(self, pid: int) -> None:
        try:
            os.unlink(os.path.join(self.root, f"dead-{pid}.json"))
        except FileNotFoundError:
            pass

    # ---- epochs --------------------------------------------------------
    def latest_epoch(self) -> Optional[Epoch]:
        best = None
        for f in os.listdir(self.root):
            if f.startswith("epoch-") and f.endswith(".json"):
                n = int(f[6:-5])
                if best is None or n > best:
                    best = n
        if best is None:
            return None
        d = json.load(open(os.path.join(self.root, f"epoch-{best}.json")))
        return Epoch(n=best, members=sorted(d["members"]),
                     coord=d["coord"], ckpt=d.get("ckpt"),
                     dead_rows=d.get("dead_rows", []))

    def publish_epoch(self, n: int, members: List[int],
                      ckpt: Optional[str],
                      dead_rows: List[int]) -> Optional[Epoch]:
        """Publish epoch ``n`` (write-once). The coordinator address is a
        freshly bound localhost port; jax.distributed requires the
        process with process_id 0 — i.e. ``sorted(members)[0]`` — to
        host the service there, so on a real fabric the address host
        must be that member's hostname (the localhost CI cluster makes
        every choice valid). The probe-then-close port pick is TOCTOU:
        another process can take the port before the coordinator binds
        it. That failure is SELF-HEALING, not permanent — the epoch's
        members fail ``initialize`` (bounded timeout), their supervisors
        restart them into the reform path (each entry attempt first
        heartbeats its target epoch, so a re-entry loop cannot form),
        and the next proposal mints a fresh port in epoch ``n+1``.
        Returns None if a racer published first (caller re-reads)."""
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        coord = f"127.0.0.1:{port}"
        ep = {"members": sorted(members), "coord": coord, "ckpt": ckpt,
              "dead_rows": sorted(dead_rows)}
        if _atomic_write(os.path.join(self.root, f"epoch-{n}.json"), ep):
            return Epoch(n=n, members=sorted(members), coord=coord,
                         ckpt=ckpt, dead_rows=sorted(dead_rows))
        return None

    def propose_next_epoch(self, prev: Epoch, survivors: Dict[int, dict],
                           joiners: List[int]) -> Optional[Epoch]:
        """Coordinator-side epoch bump: members = fresh survivors of the
        previous epoch plus any joiners; dead rows = rows of members that
        did NOT survive (row == original pid, the initial placement
        convention) minus rows coming back; checkpoint = the survivor
        checkpoint with the highest watermark (see module doc)."""
        alive = sorted(set(survivors) & set(prev.members))
        members = sorted(set(alive) | set(joiners))
        dead = sorted(
            (set(prev.members) | set(prev.dead_rows)) - set(members)
        )
        best_ckpt, best_wm = None, -1
        for p in alive:
            hb = survivors[p]
            if hb.get("ckpt") and hb.get("wm", -1) > best_wm:
                best_ckpt, best_wm = hb["ckpt"], hb["wm"]
        return self.publish_epoch(prev.n + 1, members, best_ckpt, dead)

    def is_coordinator(self, survivors: Dict[int, dict],
                       members: Optional[List[int]] = None) -> bool:
        """Deterministic coordinator derivation: lowest fresh pid —
        restricted to the current epoch's ``members`` when given, so a
        waiting joiner (fresh but not a member) can never self-elect."""
        pool = set(survivors)
        if members is not None:
            pool &= set(members)
        return bool(pool) and min(pool) == self.pid

    # ---- joins ---------------------------------------------------------
    def request_join(self) -> None:
        _atomic_write(
            os.path.join(self.root, f"join-{self.pid}.json"),
            {"time": time.time()},
        )

    def pending_joins(self, members: List[int],
                      stale_s: Optional[float] = None) -> List[int]:
        """Join requests from non-members. With ``stale_s``, only joiners
        with a FRESH heartbeat count (a waiting joiner heartbeats in
        ``await_epoch_including_me``) — a leftover join file from a
        process that died again must not be folded into an epoch it can
        never connect to."""
        fresh = None if stale_s is None else self.fresh_peers(stale_s)
        out = []
        for f in os.listdir(self.root):
            if f.startswith("join-") and f.endswith(".json"):
                p = int(f[5:-5])
                if p in members:
                    self.clear_join(p)      # folded in: retire the file
                elif fresh is None or p in fresh:
                    out.append(p)
        return sorted(out)

    def clear_join(self, pid: int) -> None:
        try:
            os.unlink(os.path.join(self.root, f"join-{pid}.json"))
        except FileNotFoundError:
            pass

    def await_epoch_including_me(self, after: int = 0,
                                 timeout_s: float = 600.0,
                                 poll_s: float = 0.3,
                                 hb: Optional[dict] = None) -> Epoch:
        """Block until an epoch newer than ``after`` lists this pid as a
        member, heartbeating meanwhile so the failure detector keeps
        counting this process as alive. ``hb`` carries the last known
        {round, wm, ckpt} so the re-published heartbeat stays a valid
        candidate in the checkpoint election (clobbering it with
        placeholders could silently drop the max-watermark checkpoint
        from the next epoch's restore choice)."""
        hb = hb or {}
        # write-before-block (obs.blackbox): this wait can legitimately
        # run to its full timeout — the journal says which epoch the
        # process was waiting past when an external kill arrives
        blackbox.mark("await_epoch", rv_pid=self.pid, after=after,
                      timeout_s=timeout_s)
        # monotonic deadline (ADVICE r5 #1): a wall-clock step must not
        # expire the wait early or extend it indefinitely
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            ep = self.latest_epoch()
            if ep is not None and ep.n > after and self.pid in ep.members:
                self.clear_join(self.pid)
                blackbox.mark("await_epoch_done", rv_pid=self.pid, epoch=ep.n)
                return ep
            self.heartbeat(after, hb.get("round", -1), hb.get("wm", -1),
                           hb.get("ckpt"))
            time.sleep(poll_s)
        raise TimeoutError(
            f"pid {self.pid}: no epoch after {after} included me"
        )

    def reform(self, cur: Epoch, stall_s: float, joiners: List[int] = (),
               timeout_s: float = 600.0, hb: Optional[dict] = None) -> Epoch:
        """Drive one re-formation to completion: wait out heartbeat
        staleness, derive the coordinator from the fresh set, propose the
        next epoch if that is this process, and return the first epoch
        newer than ``cur`` that includes this pid. Safe for every
        survivor to call concurrently — non-coordinators just wait, a
        lost proposal race falls through to the published epoch, and the
        coordinator re-derivation loop covers the case where the
        would-be coordinator is itself dead (its heartbeat goes stale
        and the next-lowest survivor takes over)."""
        hb = hb or {}
        blackbox.mark("reform_enter", rv_pid=self.pid, epoch=cur.n,
                      stall_s=stall_s, timeout_s=timeout_s)
        deadline = time.monotonic() + timeout_s
        seen, seen_at = None, time.monotonic()
        settle_s = 6.0
        while time.monotonic() < deadline:
            ep = self.latest_epoch()
            if ep is not None and ep.n > cur.n:
                if self.pid in ep.members:
                    blackbox.mark("reform_done", rv_pid=self.pid, epoch=ep.n)
                    return ep
                # A newer epoch EXCLUDED this survivor: its heartbeat went
                # stale past the detector window while it was wedged (GC
                # pause, storage stall, clock skew — module doc). Spinning
                # here on proposals derived from ``cur`` can never
                # succeed — ``cur.n + 1`` is already taken, and the new
                # epoch's members owe a silent non-member nothing. Take
                # the rejoin path instead: announce the join and wait to
                # be folded into a following epoch (the coordinator sees
                # the fresh join on its next round).
                blackbox.mark("reform_rejoin", rv_pid=self.pid,
                              excluded_by=ep.n)
                self.request_join()
                return self.await_epoch_including_me(
                    after=ep.n,
                    timeout_s=max(deadline - time.monotonic(), 1.0),
                    hb=hb,
                )
            self.heartbeat(cur.n, hb.get("round", -1), hb.get("wm", -1),
                           hb.get("ckpt"))
            fresh = self.fresh_peers(stall_s)
            # settle window: the fresh set must hold still before the
            # derived coordinator proposes, so two survivors re-exec'ing
            # a second apart converge on the SAME survivor set instead of
            # the faster one forming a smaller epoch without the other
            key = tuple(sorted(fresh))
            if key != seen:
                seen, seen_at = key, time.monotonic()
            # death-driven short-circuit: when every missing member is
            # covered by a death certificate, the survivor set is not a
            # guess that needs to hold still — it is reaped fact, and
            # the settle window would only delay recovery
            missing = set(cur.members) - set(fresh)
            certified = missing and missing <= set(self.declared_dead())
            settle = 0.0 if certified else settle_s
            if (
                self.is_coordinator(fresh, cur.members)
                and time.monotonic() - seen_at >= settle
            ):
                blackbox.mark("reform_propose", rv_pid=self.pid,
                              next_epoch=cur.n + 1,
                              survivors=sorted(fresh),
                              death_driven=bool(certified))
                self.propose_next_epoch(cur, fresh, list(joiners))
            time.sleep(0.5)
        raise TimeoutError(f"pid {self.pid}: re-formation stalled")
