"""Mesh transport: one replica row per device over a ``replica`` mesh axis.

The TPU-native recast of the reference's "network" (a global map of Go
channels, main.go:12, 32-38): replica state machines are rows of the same
replica-major arrays, sharded one per chip over a ``jax.sharding.Mesh``
axis. AppendEntries becomes the leader-window all_gather/scatter inside the
step kernel, and ack/vote aggregation becomes gather+reduce — all XLA
collectives riding ICI (SURVEY.md §5 "distributed communication backend").

The program body is byte-identical to the single-device transport
(``core.step``); only ``Comm`` and placement change — which is exactly the
property the differential tests rely on.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.config import RaftConfig
from raft_tpu.core.comm import MeshComm
from raft_tpu.core.state import ReplicaState, init_state
from raft_tpu.core.step import (
    RepInfo,
    VoteInfo,
    replicate_step,
    scan_replicate,
    vote_step,
)

AXIS = "replica"


class TpuMeshTransport:
    def __init__(self, cfg: RaftConfig, devices: Sequence[jax.Device] | None = None):
        self.cfg = cfg
        devices = list(devices) if devices is not None else jax.devices()
        if len(devices) < cfg.n_replicas:
            raise ValueError(
                f"need {cfg.n_replicas} devices for one replica row each, "
                f"got {len(devices)}"
            )
        self.mesh = Mesh(np.array(devices[: cfg.n_replicas]), (AXIS,))
        self._row = NamedSharding(self.mesh, P(AXIS))
        self._rep = NamedSharding(self.mesh, P())
        comm = MeshComm(cfg.n_replicas, AXIS)

        state_specs = ReplicaState(
            term=P(AXIS), voted_for=P(AXIS), last_index=P(AXIS),
            commit_index=P(AXIS), match_index=P(AXIS), match_term=P(AXIS),
            log_term=P(AXIS), log_payload=P(AXIS),
        )
        info_specs = RepInfo(
            commit_index=P(), match=P(), max_term=P(),
            repair_start=P(), frontier_len=P(),
        )
        vote_specs = VoteInfo(votes=P(), max_term=P(), grants=P())

        self._replicate = jax.jit(
            jax.shard_map(
                partial(replicate_step, comm, ec=cfg.ec_enabled),
                mesh=self.mesh,
                in_specs=(state_specs, P(AXIS), P(), P(), P(), P(), P()),
                out_specs=(state_specs, info_specs),
                check_vma=False,
            )
        )
        self._vote = jax.jit(
            jax.shard_map(
                partial(vote_step, comm),
                mesh=self.mesh,
                in_specs=(state_specs, P(), P(), P()),
                out_specs=(state_specs, vote_specs),
                check_vma=False,
            )
        )
        self._replicate_many = jax.jit(
            jax.shard_map(
                partial(scan_replicate, comm, cfg.ec_enabled),
                mesh=self.mesh,
                in_specs=(state_specs, P(None, AXIS), P(), P(), P(), P(), P()),
                out_specs=(state_specs, info_specs),
                check_vma=False,
            )
        )

    def init(self) -> ReplicaState:
        state = init_state(self.cfg)
        return jax.device_put(state, self._row)

    def shard_rows(self, payload):
        """Place a u8[R, B, S] per-replica payload one row per device (the
        'scatter' of the north star when rows are RS shards)."""
        return jax.device_put(payload, self._row)

    def replicate(
        self, state, client_payload, client_count, leader, leader_term, alive, slow
    ) -> Tuple[ReplicaState, RepInfo]:
        return self._replicate(
            state, client_payload, jnp.int32(client_count), jnp.int32(leader),
            jnp.int32(leader_term), alive, slow,
        )

    def replicate_many(
        self, state, payloads, counts, leader, leader_term, alive, slow
    ) -> Tuple[ReplicaState, RepInfo]:
        """u8[T, R, B, S] payloads → T steps in one compiled scan."""
        return self._replicate_many(
            state, payloads, counts, jnp.int32(leader), jnp.int32(leader_term),
            alive, slow,
        )

    def request_votes(
        self, state, candidate, cand_term, alive
    ) -> Tuple[ReplicaState, VoteInfo]:
        return self._vote(state, jnp.int32(candidate), jnp.int32(cand_term), alive)
