"""Mesh transport: one replica row per device over a ``replica`` mesh axis.

The TPU-native recast of the reference's "network" (a global map of Go
channels, main.go:12, 32-38): replica state machines are rows of the same
replica-major arrays, sharded one per chip over a ``jax.sharding.Mesh``
axis. AppendEntries becomes the leader-window all_gather/scatter inside the
step kernel, and ack/vote aggregation becomes gather+reduce — all XLA
collectives riding ICI (SURVEY.md §5 "distributed communication backend").

The program body is byte-identical to the single-device transport
(``core.step``); only ``Comm`` and placement change — which is exactly the
property the differential tests rely on.

A second, optional mesh axis (``pshard``) shards the payload *byte*
dimension, the framework's long-dimension/sequence-parallel analogue: every
log slot's bytes are split across ``payload_shards`` devices, so per-device
HBM for the log shrinks by that factor and the replication windows move
byte-slices in parallel. The protocol kernels never reduce over the byte
axis, so they run unchanged on the 2-D mesh — replica collectives ride one
axis, the byte axis stays local.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.config import RaftConfig
from raft_tpu.core.comm import MeshComm, shard_map
from raft_tpu.obs import blackbox
from raft_tpu.obs.compile import labeled
from raft_tpu.core.state import ReplicaState, init_state
from raft_tpu.core.step import (
    RepInfo,
    VoteInfo,
    fused_steady_scan,
    replicate_step,
    scan_replicate,
    vote_step,
)

AXIS = "replica"
PAYLOAD_AXIS = "pshard"

#: Process-wide mesh + program caches (the group_mesh pattern, extended
#: to the replica mesh this round): a fresh TpuMeshTransport over the
#: same device grid used to rebuild every shard_map program — a silent
#: per-instance retrace of the whole family, which the RetraceSentinel
#: now counts as a hot-path violation. Instances over the same (device
#: ids, rows, payload shards, program-shaping config) share ONE Mesh
#: object and ONE labeled jitted program per entry point.
_MESHES: dict = {}
_PROGRAMS: dict = {}


class TpuMeshTransport:
    def __init__(
        self,
        cfg: RaftConfig,
        devices: Sequence[jax.Device] | None = None,
        payload_shards: int | None = None,
    ):
        self.cfg = cfg
        if payload_shards is None:
            payload_shards = cfg.payload_shards
        devices = list(devices) if devices is not None else jax.devices()
        # membership headroom allocates (and shards) cfg.rows replica
        # rows; spare rows idle behind the member mask until add_server
        need = cfg.rows * payload_shards
        if len(devices) < need:
            raise ValueError(
                f"need {need} devices ({cfg.rows} replica rows x "
                f"{payload_shards} payload shards), got {len(devices)}"
            )
        if cfg.shard_words % payload_shards:
            raise ValueError(
                f"per-entry stored words ({cfg.shard_words}) must divide "
                f"evenly over {payload_shards} payload shards"
            )
        self.payload_shards = payload_shards
        # write-before-block (obs.blackbox): mesh construction and the
        # shard_map program builds below are where a wedged backend or
        # an incompatible JAX stalls/dies — the journal names this phase
        blackbox.mark(
            "mesh_build", rows=cfg.rows, payload_shards=payload_shards,
            devices=len(devices),
        )
        grid = np.array(devices[:need]).reshape(cfg.rows, payload_shards)
        mesh_key = (tuple(d.id for d in grid.flat), cfg.rows,
                    payload_shards)
        if mesh_key not in _MESHES:
            _MESHES[mesh_key] = Mesh(grid, (AXIS, PAYLOAD_AXIS))
        self.mesh = _MESHES[mesh_key]
        # everything that shapes a program's CLOSURE (specs, comm,
        # partial params) — operand shapes re-key inside jit itself
        self._key = mesh_key + (
            cfg.ec_enabled, cfg.commit_quorum,
            cfg.max_replicas is not None,
            cfg.log_capacity, cfg.shard_words,
        )
        # The folded payload's lane axis is [R x P x W_local] flattened in
        # that (major-to-minor) order, which is exactly how PartitionSpec
        # splits one dimension over a tuple of mesh axes.
        lanes = (AXIS, PAYLOAD_AXIS) if payload_shards > 1 else AXIS
        self._row = NamedSharding(self.mesh, P(AXIS))
        self._payload2 = NamedSharding(self.mesh, P(None, lanes))
        comm = MeshComm(cfg.rows, AXIS)

        state_specs = ReplicaState(
            term=P(AXIS), voted_for=P(AXIS), last_index=P(AXIS),
            commit_index=P(AXIS), match_index=P(AXIS), match_term=P(AXIS),
            log_term=P(AXIS), log_payload=P(None, lanes),
        )
        info_specs = RepInfo(
            commit_index=P(), match=P(), max_term=P(),
            repair_start=P(), frontier_len=P(),
        )
        vote_specs = VoteInfo(votes=P(), max_term=P(), grants=P())

        # repair-capable and steady-state (repair compiled out) variants of
        # each entry point; the engine dispatches on whether anyone lags.
        # EC has no repair window: both keys alias one program.
        reps = (True,) if cfg.ec_enabled else (True, False)
        self._member_mode = cfg.max_replicas is not None
        mem_spec = (P(),) if self._member_mode else ()
        self._replicate = {
            rep: self._cached(
                "tpu_mesh.replicate", ("replicate", rep),
                lambda rep=rep: jax.jit(
                    shard_map(
                        partial(
                            replicate_step, comm,
                            ec=cfg.ec_enabled,
                            commit_quorum=cfg.commit_quorum,
                            repair=rep,
                        ),
                        mesh=self.mesh,
                        in_specs=(
                            state_specs, P(None, lanes), P(), P(), P(),
                            P(), P(), P(), P(),
                        ) + mem_spec,
                        out_specs=(state_specs, info_specs),
                        check_vma=False,
                    )
                ),
            )
            for rep in reps
        }
        self._vote = self._cached(
            "tpu_mesh.vote", ("vote",),
            lambda: jax.jit(
                shard_map(
                    partial(vote_step, comm),
                    mesh=self.mesh,
                    in_specs=(state_specs, P(), P(), P()),
                    out_specs=(state_specs, vote_specs),
                    check_vma=False,
                )
            ),
        )
        self._replicate_many = {
            rep: self._cached(
                "tpu_mesh.replicate_many", ("replicate_many", rep),
                lambda rep=rep: jax.jit(
                    shard_map(
                        partial(
                            scan_replicate, comm, cfg.ec_enabled,
                            cfg.commit_quorum, rep,
                        ),
                        mesh=self.mesh,
                        in_specs=(
                            state_specs, P(None, None, lanes),
                            P(), P(), P(), P(), P(), P(), P(),
                        ) + mem_spec,
                        out_specs=(state_specs, info_specs),
                        check_vma=False,
                    )
                ),
            )
            for rep in reps
        }
        if cfg.ec_enabled:
            self._replicate[False] = self._replicate[True]
            self._replicate_many[False] = self._replicate_many[True]
        # fused-dispatch program family (built lazily): same protocol
        # functions with the engine's term_floor threaded through, which
        # lets core.step route to the per-device fused kernels
        # (core.step_mesh) when the shape allows — VERDICT r4 #1: the
        # deployment shape and the fast shape are the same program now.
        self._comm = comm
        self._state_specs = state_specs
        self._info_specs = info_specs
        self._lanes = lanes
        self._mem_spec = mem_spec
        #   the recorded (obs.device) variants thread the replicated
        #   EventRing through the shard_map body (every device computes
        #   the identical ring from gathered values, so P() specs are
        #   exact); they ride the same process-wide _PROGRAMS cache
        self._fetch_seq = 0
        #   allgather id for blackbox marks: every cross-process fetch is
        #   a collective that can stall; the journal carries which one
        blackbox.mark("mesh_ready", rows=cfg.rows)

    def _cached(self, label: str, key: tuple, build):
        """Process-wide program lookup (module docstring): build once
        per (transport key, program key), wrapped ``obs.compile.labeled``
        at cache-store time so the compile plane attributes the family."""
        k = self._key + key
        if k not in _PROGRAMS:
            _PROGRAMS[k] = labeled(label, build())
        return _PROGRAMS[k]

    def init(self) -> ReplicaState:
        state = init_state(self.cfg)
        shardings = ReplicaState(
            term=self._row, voted_for=self._row, last_index=self._row,
            commit_index=self._row, match_index=self._row, match_term=self._row,
            log_term=NamedSharding(self.mesh, P(AXIS, None)),
            log_payload=self._payload2,
        )
        return jax.tree.map(jax.device_put, state, shardings)

    def fetch(self, x):
        """Host view of a (possibly cross-process sharded) device value.

        Single process: plain ``np.asarray``. Multi-process: a jit
        identity resharded to fully-replicated — a collective, so EVERY
        process must call it at the same point, which the engine's
        mirrored deterministic event loops guarantee (each process runs
        the identical control plane and issues identical launches)."""
        if jax.process_count() == 1:
            return np.asarray(x)
        if not hasattr(self, "_fetch_jit"):
            rep = NamedSharding(self.mesh, P())
            self._fetch_jit = jax.jit(lambda a: a, out_shardings=rep)
        # write-before-block: a cross-process fetch is a collective every
        # process must reach in lockstep; a mirrored-loop divergence or a
        # dead peer stalls exactly here, and the journal's allgather id
        # tells WHICH fetch each process was in when it wedged
        self._fetch_seq += 1
        blackbox.mark("allgather", id=self._fetch_seq, op="fetch")
        return np.asarray(self._fetch_jit(x))

    def shard_rows(self, payload):
        """Place a folded i32[B, R*W] batch with each replica's lane block
        on its own device (the 'scatter' of the north star when blocks are
        RS shards)."""
        return jax.device_put(payload, self._payload2)

    def _member_or_ones(self, member):
        return jnp.ones(self.cfg.rows, bool) if member is None else member

    def _fused_program(self, kind: str, rep: bool, allow_turnover=True):
        """shard_map programs that thread ``term_floor`` through, so the
        per-step dispatch inside core.step (one source of truth) can
        route to the per-device fused kernels. Built lazily per
        (kind, repair[, turnover]) and process-cached."""
        cfg = self.cfg
        comm = self._comm
        lanes = self._lanes
        mm = self._member_mode

        if kind == "replicate":
            def fn(state, payload, cnt, leader, lterm, alive, slow, fpt,
                   rf, *rest):
                member = rest[0] if mm else None
                tf = rest[-1]
                return replicate_step(
                    comm, state, payload, cnt, leader, lterm, alive,
                    slow, fpt, rf, member, ec=cfg.ec_enabled,
                    commit_quorum=cfg.commit_quorum, repair=rep,
                    term_floor=tf,
                )
            win_spec = P(None, lanes)
        elif kind == "replicate_many":
            def fn(state, payloads, counts, leader, lterm, alive, slow,
                   fpt, rf, *rest):
                member = rest[0] if mm else None
                tf = rest[-1]
                return scan_replicate(
                    comm, cfg.ec_enabled, cfg.commit_quorum, rep, state,
                    payloads, counts, leader, lterm, alive, slow, fpt,
                    rf, member, term_floor=tf,
                )
            win_spec = P(None, None, lanes)
        else:                                    # "pipeline"
            from raft_tpu.core.ring import pallas_interpret
            from raft_tpu.core.step_mesh import mesh_pipeline

            def fn(state, wins, counts, leader, lterm, alive, slow, fpt,
                   rf, *rest):
                member = rest[0] if mm else None
                tf = rest[-1]
                return mesh_pipeline(
                    AXIS, state, wins, counts, leader, lterm, alive,
                    slow, fpt, rf, member, tf,
                    commit_quorum=cfg.commit_quorum, ec=cfg.ec_enabled,
                    interpret=pallas_interpret(),
                    allow_turnover=allow_turnover,
                )
            win_spec = P(None, None, lanes)

        return self._cached(
            f"tpu_mesh.{kind}",
            ("fused_dispatch", kind, rep, allow_turnover),
            lambda: jax.jit(
                shard_map(
                    fn,
                    mesh=self.mesh,
                    in_specs=(
                        self._state_specs, win_spec,
                        P(), P(), P(), P(), P(), P(), P(),
                    ) + self._mem_spec + (P(),),
                    out_specs=(self._state_specs, self._info_specs),
                    check_vma=False,
                )
            ),
        )

    def _recorded_program(self, kind: str, rep: bool, has_tf: bool):
        """Device-observability variants (obs.device): the same protocol
        programs with record=True and the EventRing threaded through as
        a fully-replicated operand — recording derives from gathered
        (hence replicated) values, so every device writes the identical
        ring. Built lazily per (kind, repair, term_floor?) and cached."""
        if kind == "replicate" and self.cfg.ec_enabled:
            rep = True   # EC has no repair window: both keys are one
            #   program — alias like the unrecorded caches do
        from raft_tpu.obs.device import EventRing

        cfg = self.cfg
        comm = self._comm
        mm = self._member_mode
        ring_specs = EventRing(buf=P(), count=P(), tick=P(), counters=P())

        if kind == "replicate":
            def fn(state, payload, cnt, leader, lterm, alive, slow, fpt,
                   rf, *rest):
                member = rest[0] if mm else None
                tf = rest[-2] if has_tf else None
                return replicate_step(
                    comm, state, payload, cnt, leader, lterm, alive,
                    slow, fpt, rf, member, ec=cfg.ec_enabled,
                    commit_quorum=cfg.commit_quorum, repair=rep,
                    term_floor=tf, ring=rest[-1], record=True,
                )

            in_specs = (
                self._state_specs, P(None, self._lanes),
                P(), P(), P(), P(), P(), P(), P(),
            ) + self._mem_spec + ((P(),) if has_tf else ()) + (ring_specs,)
            out_specs = (self._state_specs, self._info_specs, ring_specs)
        else:                                    # "vote"
            vote_specs = VoteInfo(votes=P(), max_term=P(), grants=P())

            def fn(state, candidate, cand_term, alive, quorum, ring):
                return vote_step(
                    comm, state, candidate, cand_term, alive, ring=ring,
                    record=True, quorum=quorum,
                )

            in_specs = (self._state_specs, P(), P(), P(), P(), ring_specs)
            out_specs = (self._state_specs, vote_specs, ring_specs)

        return self._cached(
            f"tpu_mesh.{kind}", ("recorded", kind, rep, has_tf),
            lambda: jax.jit(
                shard_map(
                    fn, mesh=self.mesh, in_specs=in_specs,
                    out_specs=out_specs, check_vma=False,
                )
            ),
        )

    def replicate(
        self, state, client_payload, client_count, leader, leader_term,
        alive, slow, repair=True, member=None, repair_floor=0,
        floor_prev_term=0, term_floor=None, ring=None,
    ) -> Tuple[ReplicaState, RepInfo]:
        extra = (self._member_or_ones(member),) if self._member_mode else ()
        if ring is not None:
            has_tf = term_floor is not None
            tf = (jnp.int32(term_floor),) if has_tf else ()
            return self._recorded_program("replicate", bool(repair), has_tf)(
                state, client_payload, jnp.int32(client_count),
                jnp.int32(leader), jnp.int32(leader_term), alive, slow,
                jnp.int32(floor_prev_term), jnp.int32(repair_floor),
                *extra, *tf, ring,
            )
        if term_floor is not None:
            return self._fused_program("replicate", bool(repair))(
                state, client_payload, jnp.int32(client_count),
                jnp.int32(leader), jnp.int32(leader_term), alive, slow,
                jnp.int32(floor_prev_term), jnp.int32(repair_floor),
                *extra, jnp.int32(term_floor),
            )
        return self._replicate[bool(repair)](
            state, client_payload, jnp.int32(client_count), jnp.int32(leader),
            jnp.int32(leader_term), alive, slow,
            jnp.int32(floor_prev_term), jnp.int32(repair_floor), *extra,
        )

    def replicate_many(
        self, state, payloads, counts, leader, leader_term, alive, slow,
        repair=True, member=None, repair_floor=0, floor_prev_term=0,
        term_floor=None,
    ) -> Tuple[ReplicaState, RepInfo]:
        """i32[T, B, R*W] folded payloads → T steps in one compiled scan."""
        extra = (self._member_or_ones(member),) if self._member_mode else ()
        if term_floor is not None:
            return self._fused_program("replicate_many", bool(repair))(
                state, payloads, counts, jnp.int32(leader),
                jnp.int32(leader_term), alive, slow,
                jnp.int32(floor_prev_term), jnp.int32(repair_floor),
                *extra, jnp.int32(term_floor),
            )
        return self._replicate_many[bool(repair)](
            state, payloads, counts, jnp.int32(leader), jnp.int32(leader_term),
            alive, slow, jnp.int32(floor_prev_term), jnp.int32(repair_floor),
            *extra,
        )

    def _fused_scan_program(self, record: bool):
        """The K-tick fused steady-state scan over the mesh
        (core.step.fused_steady_scan with MeshComm): the staging ring's
        per-replica payload WORDS are exactly each device's local lane
        block on a full-copy cluster, so the ring rides in replicated
        over the replica axis (split over the payload axis when byte
        sharding is on) and the per-device scan body consumes it with
        no tile at all. Built lazily per record flag and process-cached
        with the other fused-dispatch programs."""
        cfg = self.cfg
        comm = self._comm
        mm = self._member_mode

        def fn(state, staging, start_slot, counts, n_run, halted0,
               leader, lterm, alive, slow, fpt, rf, *rest):
            member = rest[0] if mm else None
            ring = rest[-1] if record else None
            return fused_steady_scan(
                comm, cfg.commit_quorum, state, staging, start_slot,
                counts, n_run, halted0, leader, lterm, alive, slow,
                fpt, rf, member, ring=ring, record=record,
            )

        stag_spec = (
            P(None, None, PAYLOAD_AXIS) if self.payload_shards > 1
            else P()
        )
        flag_specs = (P(), P(), P())        # escaped, ran, halted
        extra_in = self._mem_spec
        extra_out = ()
        if record:
            from raft_tpu.obs.device import EventRing

            ring_specs = EventRing(buf=P(), count=P(), tick=P(),
                                   counters=P())
            extra_in = extra_in + (ring_specs,)
            extra_out = (ring_specs,)
        return self._cached(
            "tpu_mesh.fused", ("fused_scan", record),
            lambda: jax.jit(
                shard_map(
                    fn,
                    mesh=self.mesh,
                    in_specs=(
                        self._state_specs, stag_spec,
                        P(), P(), P(), P(), P(), P(), P(), P(), P(), P(),
                    ) + extra_in,
                    out_specs=(
                        self._state_specs, self._info_specs,
                    ) + flag_specs + extra_out,
                    check_vma=False,
                ),
                donate_argnums=(0,),
            ),
        )

    def replicate_fused(
        self, state, staging, start_slot, counts, n_run, halted0,
        leader, leader_term, alive, slow, member=None, repair_floor=0,
        floor_prev_term=0, ring=None,
    ):
        """Same contract as ``SingleDeviceTransport.replicate_fused``
        (state donated; returns ``(state, infos, escaped, ran,
        halted[, ring])``), over the mesh."""
        extra = (self._member_or_ones(member),) if self._member_mode else ()
        if ring is not None:
            extra = extra + (ring,)
        return self._fused_scan_program(ring is not None)(
            state, staging, jnp.int32(start_slot), counts,
            jnp.int32(n_run), jnp.asarray(halted0, bool),
            jnp.int32(leader), jnp.int32(leader_term), alive, slow,
            jnp.int32(floor_prev_term), jnp.int32(repair_floor), *extra,
        )

    def replicate_pipeline(
        self, state, payloads, counts, leader, leader_term, alive, slow,
        member=None, repair_floor=0, floor_prev_term=0, term_floor=1,
        allow_turnover=True,
    ) -> Tuple[ReplicaState, RepInfo]:
        """T saturated steps as ONE per-device kernel launch over the
        mesh (core.step_mesh.mesh_pipeline): two launch collectives,
        then a communication-free flight on every chip. Same contract
        as the single-device ``replicate_pipeline`` — the engine's host
        gate implies the (shared) launch-feasibility predicate and
        verifies commit progress covers the chunk."""
        extra = (self._member_or_ones(member),) if self._member_mode else ()
        return self._fused_program(
            "pipeline", True, bool(allow_turnover)
        )(
            state, payloads, counts, jnp.int32(leader),
            jnp.int32(leader_term), alive, slow,
            jnp.int32(floor_prev_term), jnp.int32(repair_floor),
            *extra, jnp.int32(term_floor),
        )

    def request_votes(
        self, state, candidate, cand_term, alive, ring=None, quorum=0,
    ) -> Tuple[ReplicaState, VoteInfo]:
        if ring is not None:
            return self._recorded_program("vote", True, False)(
                state, jnp.int32(candidate), jnp.int32(cand_term), alive,
                jnp.int32(quorum), ring,
            )
        return self._vote(state, jnp.int32(candidate), jnp.int32(cand_term), alive)
