"""Group-axis mesh transport: G Raft groups laid out ``(group, replica)``
over a device mesh.

``MultiEngine``'s resident layout vmaps all G groups onto ONE device —
the batched launch amortizes beautifully at small G and saturates once
the groups outgrow the chip (docs/PERF.md G-sweep: amortizing at G=4,
linear again by G=16). The production shape is hundreds-to-thousands of
groups, which is a SHARDING problem, not a batching problem: split the
group axis over a ``gshard`` mesh axis so each device runs the same
vmapped group program over its own block of groups, and ONE launch
drives every shard.

Layout (``core.state.group_partition_rules`` — the partition-rule
table): every group-state leaf splits its leading group axis over
``gshard``; ring slots, payload lanes and replica rows stay shard-local
(each shard holds ALL R replica rows of its groups, so the per-group
step bodies — ``core.step.group_replicate_step`` et al. — run unchanged
inside ``core.comm.shard_map``; a second ``replica`` mesh axis is
declared for the future replica-row spread and is size 1 here). Groups
are block-placed: physical slot ``s`` lives on shard
``s // (G / n_shards)``. The ENGINE owns the logical→physical slot
mapping (its placement table), which is what makes group migration a
device-side slot permutation (``swap_slots``) instead of a state
hand-off protocol.

Byte-identity by construction: ``shard_map(vmap(step))`` over a
block-split group axis computes, per group, exactly what the global
``vmap(step)`` computes — groups never communicate, so the split
introduces no collective into the step and no reordering into any
reduction. The pins in ``tests/test_group_shard.py`` hold this to
bit-exactness (state fields, committed logs, commit stamps, chaos
fingerprints).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.config import RaftConfig
from raft_tpu.core.comm import shard_map
from raft_tpu.core.state import (
    GROUP_AXIS,
    REPLICA_AXIS,
    ReplicaState,
    group_state_specs,
    make_shard_and_gather_fns,
)
from raft_tpu.core.step import (
    RepInfo,
    VoteInfo,
    fused_group_scan,
    group_replicate_step,
    group_vote_step,
)
from raft_tpu.obs import blackbox
from raft_tpu.obs.compile import labeled


def n_shards_for(n_groups: int, n_devices: int) -> int:
    """Largest shard count that divides G and fits the device set (block
    placement needs equal-sized shards; XLA needs the split exact)."""
    for d in range(min(n_groups, max(n_devices, 1)), 0, -1):
        if n_groups % d == 0:
            return d
    return 1


#: Process-wide program cache: one compiled program family per
#: (mesh devices, R, G-per-shard shape) — chaos runners build a fresh
#: MultiEngine per seed/crash cycle, and a shard_map rebuild per engine
#: would recompile the whole family every run.
_PROGRAMS: Dict[tuple, object] = {}
_MESHES: Dict[tuple, Mesh] = {}


class GroupMeshTransport:
    """The ``transport="mesh_groups"`` backend (module docstring).

    Accepts an existing 2-axis ``Mesh`` (axes ``('gshard', 'replica')``)
    or builds one from ``devices``/``jax.devices()``. All programs are
    ``shard_map`` wraps of the SAME vmapped group-step callables the
    resident engine jits, with state (and event rings) donated, so the
    sharded and resident paths cannot drift: there is one step body.
    """

    def __init__(
        self,
        cfg: RaftConfig,
        n_groups: int,
        mesh: Optional[Mesh] = None,
        devices: Optional[Sequence[jax.Device]] = None,
    ):
        self.cfg = cfg
        self.G = n_groups
        R = cfg.n_replicas
        if mesh is not None:
            if GROUP_AXIS not in mesh.axis_names:
                raise ValueError(
                    f"mesh must carry a {GROUP_AXIS!r} axis "
                    f"(got {mesh.axis_names})"
                )
            self.n_shards = mesh.shape[GROUP_AXIS]
            if n_groups % self.n_shards:
                raise ValueError(
                    f"n_groups ({n_groups}) must divide evenly over the "
                    f"{self.n_shards}-way {GROUP_AXIS!r} axis"
                )
            self.mesh = mesh
        else:
            devices = (
                list(devices) if devices is not None else jax.devices()
            )
            self.n_shards = n_shards_for(n_groups, len(devices))
            key = tuple(d.id for d in devices[: self.n_shards])
            if key not in _MESHES:
                _MESHES[key] = Mesh(
                    np.array(devices[: self.n_shards]).reshape(
                        self.n_shards, 1
                    ),
                    (GROUP_AXIS, REPLICA_AXIS),
                )
            self.mesh = _MESHES[key]
        # write-before-block (obs.blackbox): the shard_map program builds
        # below are where an incompatible backend wedges — same contract
        # as TpuMeshTransport's mesh_build mark
        blackbox.mark(
            "group_mesh_build", groups=n_groups, shards=self.n_shards,
            rows=R,
        )
        self.groups_per_shard = n_groups // self.n_shards
        self._state_specs = group_state_specs(cfg, n_groups)
        self._shard_fns, self._gather_fns = make_shard_and_gather_fns(
            self.mesh, self._state_specs
        )
        self._key = (
            tuple(d.id for d in np.asarray(self.mesh.devices).flat),
            R, n_groups, cfg.log_capacity, cfg.batch_size,
            cfg.shard_words,
        )
        blackbox.mark("group_mesh_ready", shards=self.n_shards)

    # ------------------------------------------------------------ placement
    def shard_of_slot(self, slot: int) -> int:
        """Physical shard of physical group slot ``slot`` (block layout)."""
        return slot // self.groups_per_shard

    def shard_state(self, state: ReplicaState) -> ReplicaState:
        """Place a (host or resident) group state onto the mesh with the
        rule-table layout."""
        return jax.tree.map(
            lambda fn, x: fn(x), self._shard_fns, state
        )

    def shard_payloads(self, payloads):
        """Place a group-leading payload batch (``[G, ...]`` or
        ``[K, G, ...]``) with its group axis split over ``gshard``."""
        spec = (
            P(GROUP_AXIS) if payloads.ndim == 3
            else P(None, GROUP_AXIS)
        )
        return jax.device_put(payloads, NamedSharding(self.mesh, spec))

    def shard_rings(self, rings):
        """Place the per-group event-ring pytree (leading group axis on
        every leaf) with its group axis split over ``gshard``."""
        sh = NamedSharding(self.mesh, P(GROUP_AXIS))
        return jax.tree.map(lambda a: jax.device_put(a, sh), rings)

    def _gspec(self, *trailing) -> P:
        return P(GROUP_AXIS, *trailing)

    def _cached(self, kind: str, record: bool, build):
        key = self._key + (kind, record)
        if key not in _PROGRAMS:
            # labeled at cache-store time: the compile plane attributes
            # every trace/compile of the family to "group_mesh.<kind>"
            _PROGRAMS[key] = labeled(f"group_mesh.{kind}", build())
        return _PROGRAMS[key]

    def _ring_specs(self):
        from raft_tpu.obs.device import EventRing

        g = self._gspec()
        return EventRing(buf=g, count=g, tick=g, counters=g)

    # ------------------------------------------------------------- programs
    def _replicate_program(self, record: bool):
        def build():
            body = group_replicate_step(
                self.cfg.n_replicas, record=record
            )
            g = self._gspec()
            info_specs = RepInfo(
                commit_index=g, match=g, max_term=g, repair_start=g,
                frontier_len=g,
            )
            in_specs = (
                self._state_specs, g, g, g, g, g, g, g,
            )
            out_specs = (self._state_specs, info_specs)
            if record:
                in_specs = in_specs + (self._ring_specs(), g)
                out_specs = out_specs + (self._ring_specs(),)
            return jax.jit(
                shard_map(
                    body, mesh=self.mesh, in_specs=in_specs,
                    out_specs=out_specs, check_vma=False,
                ),
                donate_argnums=(0, 8) if record else (0,),
            )

        return self._cached("replicate", record, build)

    def _vote_program(self, record: bool):
        def build():
            body = group_vote_step(self.cfg.n_replicas, record=record)
            g = self._gspec()
            vote_specs = VoteInfo(votes=g, max_term=g, grants=g)
            in_specs = (self._state_specs, g, g, g)
            out_specs = (self._state_specs, vote_specs)
            if record:
                in_specs = in_specs + (self._ring_specs(), g)
                out_specs = out_specs + (self._ring_specs(),)
            return jax.jit(
                shard_map(
                    body, mesh=self.mesh, in_specs=in_specs,
                    out_specs=out_specs, check_vma=False,
                ),
                donate_argnums=(0, 4) if record else (0,),
            )

        return self._cached("vote", record, build)

    def _fused_program(self, record: bool):
        def build():
            body = fused_group_scan(self.cfg.n_replicas, record=record)
            g = self._gspec()
            kg = P(None, GROUP_AXIS)
            info_specs = RepInfo(
                commit_index=kg, match=kg, max_term=kg, repair_start=kg,
                frontier_len=kg,
            )
            in_specs = (
                self._state_specs, kg, kg, P(), g, g, g, g, g, g,
            )
            out_specs = (self._state_specs, info_specs, kg, kg, g)
            if record:
                in_specs = in_specs + (self._ring_specs(), g)
                out_specs = out_specs + (self._ring_specs(),)
            return jax.jit(
                shard_map(
                    body, mesh=self.mesh, in_specs=in_specs,
                    out_specs=out_specs, check_vma=False,
                ),
                donate_argnums=(0, 10) if record else (0,),
            )

        return self._cached("fused", record, build)

    def _swap_program(self):
        def build():
            shardings = jax.tree.map(
                lambda spec: NamedSharding(self.mesh, spec),
                self._state_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            return jax.jit(
                lambda st, perm: jax.tree.map(lambda a: a[perm], st),
                donate_argnums=(0,),
                out_shardings=shardings,
            )

        return self._cached("swap", False, build)

    def _ring_swap_program(self):
        def build():
            ring_sh = jax.tree.map(
                lambda spec: NamedSharding(self.mesh, spec),
                self._ring_specs(),
                is_leaf=lambda x: isinstance(x, P),
            )
            return jax.jit(
                lambda rg, perm: jax.tree.map(lambda a: a[perm], rg),
                donate_argnums=(0,),
                out_shardings=ring_sh,
            )

        return self._cached("ring_swap", False, build)

    # ------------------------------------------------------------ entry API
    def replicate(self, state, payloads, counts, leaders, lterms, eff,
                  slow, member, rings=None, gids=None):
        """One sharded batched replicate launch — the exact operand
        contract of the resident engine's jitted
        ``group_replicate_step`` (all leading axes G, physical slot
        order)."""
        if rings is not None:
            return self._replicate_program(True)(
                state, payloads, counts, leaders, lterms, eff, slow,
                member, rings, gids,
            )
        return self._replicate_program(False)(
            state, payloads, counts, leaders, lterms, eff, slow, member,
        )

    def request_votes(self, state, candidates, cterms, eff, rings=None,
                      gids=None):
        if rings is not None:
            return self._vote_program(True)(
                state, candidates, cterms, eff, rings, gids,
            )
        return self._vote_program(False)(state, candidates, cterms, eff)

    def replicate_fused(self, state, payloads, counts, n_run, halted0,
                        leaders, terms, alive, slow, member, rings=None,
                        gids=None):
        """The K-tick fused group window over the mesh: per-shard
        ``halted`` flags (a P('gshard') slice of the per-group flags),
        state and rings donated, one launch for every shard's K ticks."""
        if rings is not None:
            return self._fused_program(True)(
                state, payloads, counts, n_run, halted0, leaders, terms,
                alive, slow, member, rings, gids,
            )
        return self._fused_program(False)(
            state, payloads, counts, n_run, halted0, leaders, terms,
            alive, slow, member,
        )

    def swap_slots(self, state, perm) -> ReplicaState:
        """Permute the group axis by ``perm`` (i32[G], physical order) —
        the device side of a group migration. GSPMD emits the cross-
        shard moves; the caller (engine placement table) guarantees the
        permutation is a pairwise swap, so the traffic is two groups'
        state, not a reshuffle."""
        return self._swap_program()(state, jnp.asarray(perm, jnp.int32))

    def swap_ring_slots(self, rings, perm):
        """The event rings ride the same slot permutation (recorded
        events stay with their logical group)."""
        return self._ring_swap_program()(rings, jnp.asarray(perm, jnp.int32))
