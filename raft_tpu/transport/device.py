"""Single-device transport: the replica axis as a resident batch axis.

All R replica state machines live on one chip; collectives degenerate to
reductions/indexing over the leading axis (``core.comm.SingleDeviceComm``).
This is how the benchmark runs on one TPU chip and the fastest CI path —
and it is the same compiled program as the mesh layout, only placement
differs (SURVEY.md §7 "minimum end-to-end slice").
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.config import RaftConfig
from raft_tpu.core.comm import SingleDeviceComm
from raft_tpu.core.state import ReplicaState, init_state
from raft_tpu.core.step import (
    RepInfo,
    VoteInfo,
    fused_steady_scan,
    replicate_step,
    scan_replicate,
    vote_step,
)
from raft_tpu.obs.compile import labeled

#: process-wide protocol-program cache: every transport instance over
#: the same cluster shape shares ONE jitted program per entry point
#: (jit caches per input shape), so chaos crash-restore cycles — which
#: build a fresh transport per restart — never recompile the fused
#: scan, the per-tick replicate/vote programs, or the batched drain
#: scan. (Before the compile plane existed, only the FUSED program was
#: process-cached; the per-tick programs were per-instance jits whose
#: crash-restore retraces nothing measured — the RetraceSentinel's
#: per-seed-rebuild pin is what keeps this cache honest now.) Programs
#: are wrapped ``obs.compile.labeled`` at cache-store time, so the
#: compile plane attributes every trace/compile to its program label.
#: Donation: the state pytree (and the event ring on the recorded
#: variants) updates in place instead of round-tripping HBM.
_PROGRAMS: dict = {}
_COMMS: dict = {}


def _comm_for(rows: int) -> SingleDeviceComm:
    # one stateless comm per cluster size, shared by every cached
    # program (a fresh comm per program would split jit caches)
    if rows not in _COMMS:
        _COMMS[rows] = SingleDeviceComm(rows)
    return _COMMS[rows]


def _fused_program(rows: int, commit_quorum, member_mode: bool,
                   record: bool):
    key = ("fused", rows, commit_quorum, member_mode, record)
    if key not in _PROGRAMS:
        comm = _comm_for(rows)

        def fn(state, staging, start_slot, counts, n_run, halted0,
               leader, leader_term, alive, slow, fpt, rf, *rest):
            member = rest[0] if member_mode else None
            ring = rest[-1] if record else None
            return fused_steady_scan(
                comm, commit_quorum, state, staging, start_slot, counts,
                n_run, halted0, leader, leader_term, alive, slow, fpt,
                rf, member, ring=ring, record=record,
            )

        ring_arg = 12 + (1 if member_mode else 0)
        _PROGRAMS[key] = labeled("single.fused", jax.jit(
            fn, donate_argnums=(0,) + ((ring_arg,) if record else ()),
        ))
    return _PROGRAMS[key]


def _replicate_program(rows: int, ec: bool, commit_quorum, rep: bool,
                       record: bool = False):
    key = ("replicate", rows, ec, commit_quorum, rep, record)
    if key not in _PROGRAMS:
        kw = {"record": True} if record else {}
        _PROGRAMS[key] = labeled("single.replicate", jax.jit(
            partial(
                replicate_step, _comm_for(rows),
                ec=ec, commit_quorum=commit_quorum, repair=rep, **kw,
            )
        ))
    return _PROGRAMS[key]


def _vote_program(rows: int, record: bool = False):
    key = ("vote", rows, record)
    if key not in _PROGRAMS:
        kw = {"record": True} if record else {}
        _PROGRAMS[key] = labeled("single.vote", jax.jit(
            partial(vote_step, _comm_for(rows), **kw)
        ))
    return _PROGRAMS[key]


def _replicate_many_program(rows: int, ec: bool, commit_quorum,
                            rep: bool):
    key = ("replicate_many", rows, ec, commit_quorum, rep)
    if key not in _PROGRAMS:
        _PROGRAMS[key] = labeled("single.replicate_many", jax.jit(
            partial(scan_replicate, _comm_for(rows), ec, commit_quorum,
                    rep)
        ))
    return _PROGRAMS[key]


class SingleDeviceTransport:
    def __init__(self, cfg: RaftConfig):
        self.cfg = cfg
        self._member_mode = cfg.max_replicas is not None
        # two compiled variants per entry point: repair-capable, and the
        # steady-state program with the repair window compiled out (~10%
        # faster; the engine dispatches on whether anyone lags). EC has no
        # repair window, so both keys alias one program (no dead wrapper,
        # no recompile on dispatch toggles).
        reps = (True,) if cfg.ec_enabled else (True, False)
        self._replicate = {
            rep: _replicate_program(
                cfg.rows, cfg.ec_enabled, cfg.commit_quorum, rep
            )
            for rep in reps
        }
        self._vote = _vote_program(cfg.rows)
        # device-observability (obs.device) variants, built lazily on
        # first recorded call: same protocol programs wrapped with the
        # in-kernel event ring (record=True). Keyed like _replicate.
        self._comm = _comm_for(cfg.rows)
        self._replicate_rec: dict = {}
        self._vote_rec = None
        self._replicate_many = {
            rep: _replicate_many_program(
                cfg.rows, cfg.ec_enabled, cfg.commit_quorum, rep
            )
            for rep in reps
        }
        if cfg.ec_enabled:
            self._replicate[False] = self._replicate[True]
            self._replicate_many[False] = self._replicate_many[True]

    def init(self) -> ReplicaState:
        return init_state(self.cfg)

    def fetch(self, x):
        """Host view of a device value (everything is addressable on a
        single device)."""
        import numpy as np

        return np.asarray(x)

    def replicate(
        self, state, client_payload, client_count, leader, leader_term,
        alive, slow, repair=True, member=None, repair_floor=0,
        floor_prev_term=0, term_floor=None, ring=None,
    ) -> Tuple[ReplicaState, RepInfo]:
        """``ring`` (obs.device.EventRing) selects the recorded program
        and makes the return a ``(state, info, ring)`` triple; ``None``
        (the default) runs the exact pre-instrumentation program."""
        fpt = jnp.int32(floor_prev_term)
        rf = jnp.int32(repair_floor)
        tf = None if term_floor is None else jnp.int32(term_floor)
        if member is None and self._member_mode:
            member = jnp.ones(self.cfg.rows, bool)
        if ring is not None:
            # EC has no repair window: both dispatch keys are one
            # program — alias like the unrecorded caches do
            key = True if self.cfg.ec_enabled else bool(repair)
            if key not in self._replicate_rec:
                self._replicate_rec[key] = _replicate_program(
                    self.cfg.rows, self.cfg.ec_enabled,
                    self.cfg.commit_quorum, key, record=True,
                )
            args = (
                state, client_payload, jnp.int32(client_count),
                jnp.int32(leader), jnp.int32(leader_term), alive, slow,
                fpt, rf,
            )
            if self._member_mode:
                args = args + (member,)
            return self._replicate_rec[key](
                *args, term_floor=tf, ring=ring,
            )
        if self._member_mode:
            return self._replicate[bool(repair)](
                state, client_payload, jnp.int32(client_count),
                jnp.int32(leader), jnp.int32(leader_term), alive, slow,
                fpt, rf, member, term_floor=tf,
            )
        return self._replicate[bool(repair)](
            state, client_payload, jnp.int32(client_count), jnp.int32(leader),
            jnp.int32(leader_term), alive, slow, fpt, rf, term_floor=tf,
        )

    def replicate_many(
        self, state, payloads, counts, leader, leader_term, alive, slow,
        repair=True, member=None, repair_floor=0, floor_prev_term=0,
        term_floor=None,
    ) -> Tuple[ReplicaState, RepInfo]:
        """T replication steps as one compiled ``lax.scan`` — no host
        round-trip per batch (SURVEY.md §7 hard part 1). ``payloads`` is
        i32[T, B, R*W] folded batches (core.state.fold_batch); ``counts``
        i32[T]."""
        fpt = jnp.int32(floor_prev_term)
        rf = jnp.int32(repair_floor)
        tf = None if term_floor is None else jnp.int32(term_floor)
        if self._member_mode:
            if member is None:
                member = jnp.ones(self.cfg.rows, bool)
            return self._replicate_many[bool(repair)](
                state, payloads, counts, jnp.int32(leader),
                jnp.int32(leader_term), alive, slow, fpt, rf, member,
                term_floor=tf,
            )
        return self._replicate_many[bool(repair)](
            state, payloads, counts, jnp.int32(leader), jnp.int32(leader_term),
            alive, slow, fpt, rf, term_floor=tf,
        )

    def replicate_fused(
        self, state, staging, start_slot, counts, n_run, halted0,
        leader, leader_term, alive, slow, member=None, repair_floor=0,
        floor_prev_term=0, ring=None,
    ):
        """One K-tick fused steady-state launch (core.step.
        fused_steady_scan): ``staging`` is the device staging ring
        i32[S, B, W] of untiled payload words, ``start_slot``/``counts``
        /``n_run`` select the window. The state pytree is DONATED (and
        the event ring on the recorded variant) — the scan updates in
        place; callers must treat the passed-in state as consumed.
        Returns ``(state, infos, escaped, ran, halted[, ring])``."""
        member_mode = self._member_mode
        if member_mode and member is None:
            member = jnp.ones(self.cfg.rows, bool)
        prog = _fused_program(
            self.cfg.rows, self.cfg.commit_quorum, member_mode,
            ring is not None,
        )
        extra = (member,) if member_mode else ()
        if ring is not None:
            extra = extra + (ring,)
        return prog(
            state, staging, jnp.int32(start_slot), counts,
            jnp.int32(n_run), jnp.asarray(halted0, bool),
            jnp.int32(leader), jnp.int32(leader_term), alive, slow,
            jnp.int32(floor_prev_term), jnp.int32(repair_floor), *extra,
        )

    def request_votes(
        self, state, candidate, cand_term, alive, ring=None, quorum=0,
    ) -> Tuple[ReplicaState, VoteInfo]:
        """``ring`` selects the recorded vote program (returns a triple);
        ``quorum`` is the engine's win threshold (members // 2) the
        recorded election-win condition uses."""
        if ring is not None:
            if self._vote_rec is None:
                self._vote_rec = _vote_program(
                    self.cfg.rows, record=True
                )
            return self._vote_rec(
                state, jnp.int32(candidate), jnp.int32(cand_term), alive,
                ring=ring, quorum=jnp.int32(quorum),
            )
        return self._vote(state, jnp.int32(candidate), jnp.int32(cand_term), alive)

    def replicate_pipeline(
        self, state, payloads, counts, leader, leader_term, alive, slow,
        member=None, repair_floor=0, floor_prev_term=0, term_floor=1,
        allow_turnover=True,
    ) -> Tuple[ReplicaState, RepInfo]:
        """T saturated steps as ONE kernel launch
        (core.step_pallas.steady_pipeline_tpu) — the engine dispatches
        this for full-batch chunks on a verified-steady cluster; the
        launch-feasibility cond inside falls back to the per-step fused
        scan. Returns the FINAL step's info only (the caller must verify
        commit progress covers the whole chunk)."""
        from functools import partial as _partial

        from raft_tpu.core.ring import pallas_interpret
        from raft_tpu.core.step_pallas import steady_pipeline_tpu

        if not hasattr(self, "_pipeline_jit"):
            self._pipeline_jit = labeled("single.pipeline", jax.jit(
                _partial(
                    steady_pipeline_tpu,
                    commit_quorum=self.cfg.commit_quorum,
                    ec=self.cfg.ec_enabled,
                    interpret=pallas_interpret(),
                ),
                donate_argnums=(0,),
                static_argnames=("allow_turnover",),
            ))
        if self._member_mode and member is None:
            member = jnp.ones(self.cfg.rows, bool)
        return self._pipeline_jit(
            state, payloads, counts, jnp.int32(leader),
            jnp.int32(leader_term), alive, slow,
            jnp.int32(floor_prev_term), jnp.int32(repair_floor),
            member if self._member_mode else None, jnp.int32(term_floor),
            allow_turnover=bool(allow_turnover),
        )
