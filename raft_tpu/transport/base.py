"""The ``Transport`` plugin boundary.

This is the seam named by the north star (BASELINE.json): the reference's
"transport" is a global map of Go channels standing in for sockets
(main.go:12, 31-38 — the comment says "ソケットの代わり", stand-in for
sockets). Here a transport owns *where replica state lives and how the
collective steps run*:

- ``SingleDeviceTransport`` — replica axis resident on one device (how the
  benchmark runs on a single TPU chip, and the fast CI path).
- ``TpuMeshTransport``   — one replica row per device over a
  ``jax.sharding.Mesh`` axis; identical program, collectives ride ICI.
- ``LoopbackTransport``  — host-side golden model reproducing the
  reference's message-level semantics for differential testing
  (``raft_tpu.golden``).

All device transports expose the same step signatures so the host engine
(``raft.engine``) is backend-agnostic.
"""

from __future__ import annotations

import logging
from typing import Protocol, Tuple

import jax

from raft_tpu.config import RaftConfig
from raft_tpu.core.state import ReplicaState
from raft_tpu.core.step import RepInfo, VoteInfo

logger = logging.getLogger(__name__)


class Transport(Protocol):
    cfg: RaftConfig

    def init(self) -> ReplicaState:
        """Fresh cluster state placed for this backend."""
        ...

    def replicate(
        self,
        state: ReplicaState,
        client_payload: jax.Array,   # i32[B, R*W] folded batch (see step.py)
        client_count,                # i32 valid entries
        leader,                      # i32 leader replica id
        leader_term,                 # i32
        alive,                       # bool[R]
        slow,                        # bool[R]
        repair: bool = True,         # static: repair-capable vs steady program
        member=None,                 # bool[R] configuration (dynamic quorum)
        repair_floor=0,              # i32 leader ring-validity floor
        floor_prev_term=0,           # i32 attested term of floor-1
    ) -> Tuple[ReplicaState, RepInfo]:
        ...

    def request_votes(
        self, state: ReplicaState, candidate, cand_term, alive
    ) -> Tuple[ReplicaState, VoteInfo]:
        ...


def make_transport(cfg: RaftConfig, devices=None) -> "Transport":
    """Build the configured device transport."""
    from raft_tpu.transport.device import SingleDeviceTransport
    from raft_tpu.transport.tpu_mesh import TpuMeshTransport

    if cfg.transport == "tpu_mesh":
        devices = devices if devices is not None else jax.devices()
        need = cfg.n_replicas * cfg.payload_shards
        if len(devices) >= need:
            return TpuMeshTransport(
                cfg, devices[:need], payload_shards=cfg.payload_shards
            )
        # Fewer chips than the mesh needs: fall back to the resident layout
        # (the program is the same; the replica axis just isn't sharded).
        # Loud on purpose: a benchmark or test that *believes* it ran on a
        # mesh must not silently have run resident.
        logger.warning(
            "tpu_mesh transport needs %d devices (%d replicas x %d payload "
            "shards) but only %d are visible; falling back to "
            "SingleDeviceTransport",
            need, cfg.n_replicas, cfg.payload_shards, len(devices),
        )
        return SingleDeviceTransport(cfg)
    if cfg.transport == "multihost":
        # replica axis across processes/failure domains (pod deployments);
        # a single-process fabric degrades to the flat local device list,
        # and an under-provisioned one falls back to the resident layout
        # with the same loud warning as tpu_mesh
        from raft_tpu.transport.multihost import (
            replica_devices_across_hosts,
        )

        try:
            # only device provisioning may fall back; a config error from
            # transport construction itself must propagate like tpu_mesh's
            devs = replica_devices_across_hosts(
                cfg.n_replicas, cfg.payload_shards, devices
            )
        except ValueError as e:
            logger.warning(
                "multihost placement unavailable (%s); falling back to "
                "SingleDeviceTransport", e,
            )
            return SingleDeviceTransport(cfg)
        return TpuMeshTransport(
            cfg, devs, payload_shards=cfg.payload_shards
        )
    if cfg.transport == "single":
        return SingleDeviceTransport(cfg)
    if cfg.transport == "loopback":
        raise ValueError(
            "the loopback golden model is host-side, not a device transport; "
            "use raft_tpu.golden directly (it exists for differential tests)"
        )
    raise ValueError(f"unknown device transport {cfg.transport!r}")
