"""Multi-host bootstrap: replica rows placed across failure domains.

The reference's "network" is a map of Go channels inside one process
(main.go:12) — all three replicas die together, which defeats the point of
consensus. On a TPU pod the failure domains are hosts/slices, so the mesh
must be built the other way around from a training job's: the **replica
axis spans processes** (each replica's state machine lives on a different
host's chips, AppendEntries/vote collectives ride DCN between slices and
ICI inside one), while the optional **payload-shard axis stays inside a
process** (byte-slices of one replica's log move over local ICI only).

Usage on each host of a pod (standard JAX multi-process setup):

    from raft_tpu.transport.multihost import (
        initialize_multihost, multihost_transport,
    )
    initialize_multihost(coordinator_address="host0:1234",
                         num_processes=N, process_id=i)   # no-op if N == 1
    t = multihost_transport(cfg)       # replica axis across processes
    eng = RaftEngine(cfg, t)           # every process runs the same program

The protocol DATA PLANE (vote rounds, replication, quorum commit — all
`shard_map` collectives whose info outputs are replicated) is fully
multi-process, and so is the FULL ENGINE: every process runs
``RaftEngine`` as a **mirrored deterministic event loop** — same config
seed, same timer heap, same decisions — so all processes issue identical
collective launches, which makes host reads of sharded rows legal as
collectives too (``TpuMeshTransport.fetch``: a jit identity resharded to
fully-replicated). CI proves both layers with real two-OS-process
clusters over the JAX distributed runtime (tests/test_multiprocess.py):
transport-level steps, and the complete engine driving client traffic
and a leadership change end-to-end with byte-identical committed logs on
every process. Mirroring is the control-plane replication strategy: a
host crash kills one replica row's device shards, not the cluster's only
brain — any surviving process still holds the full control state.
Placement rules are additionally covered by fake-fabric unit tests and
the single-process virtual mesh.

Surviving a real process death (what re-formation requires)
-----------------------------------------------------------
``tests/test_multiprocess.py::test_process_death_survivor_reforms`` kills
one of two OS processes with SIGKILL mid-traffic and asserts the survivor
keeps committing; ``test_three_process_reformation_and_rejoin`` runs the
FULL elastic loop at N=3 — the surviving majority agrees on who is left,
derives a new coordinator, re-forms, keeps committing, and the killed
process later rejoins and snapshot-heals back to full strength. The
survivor-agreement/epoch machinery lives in ``transport.reform``
(heartbeats, deterministic coordinator derivation, max-watermark
checkpoint election, write-once epoch publication, join requests). The
recovery contract, honestly stated:

1. **Detection.** A fixed JAX mesh gives no failure notification for a
   non-leader peer: the survivor's next collective simply stalls.
   Detection is therefore a *progress watchdog* — the mirrored loops
   commit in lockstep, so "no committed round for T seconds" is the
   peer-death signal. T must exceed the longest legitimate stall
   (compiles, checkpoint writes). Death of the runtime COORDINATOR is
   detected faster and harder: the coordination service fast-fails every
   surviving worker (an uncatchable LOG(FATAL)), so each host runs a
   tiny supervisor (the k8s/systemd pattern) that treats that exit as
   the detection signal and restarts the worker into the re-formation
   path.
2. **Re-formation is a restart, not a live mesh shrink.** XLA backends
   pin the process set at ``jax.distributed.initialize``; a survivor
   cannot drop a dead peer from a live mesh. It re-execs itself (or is
   restarted by its supervisor — the same thing k8s does), initializes a
   fresh runtime over the processes that remain, and rebuilds the
   transport over the surviving devices.
3. **State comes from stable storage, not device memory.** The dead
   host's replica-row shards are gone. Because checkpoints are
   cluster-wide (mirrored control planes archive every commit) and every
   process writes its own vote WAL, ANY surviving process can restore
   the full cluster: rows whose devices died restart from their last
   durable state — exactly Raft's crash-restart model — and the repair
   window / snapshot install heals them forward. The WAL overlay
   guarantees no restored row regresses below a term it acted in (no
   double vote).
4. **Durability fences acks.** An entry is safely acknowledgeable only
   once a checkpoint covering it is on disk; the test's client records
   acks only after ``save_checkpoint`` returns, and recovery asserts the
   acked sequence is a byte-identical prefix of the restored committed
   log. Entries committed after the last checkpoint survive only if some
   surviving process archived them — acks must wait for the fence.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

from raft_tpu.config import RaftConfig
from raft_tpu.core.comm import shard_map  # noqa: F401 — the version-
#   portable shim every mesh program build (TpuMeshTransport, and via
#   it this module's pod transports) goes through; re-exported here so
#   multihost deployments import the portability seam from the
#   transport they configure. Before the shim, jax.shard_map's absence
#   on this JAX line killed every mesh/multiprocess path at build time.
from raft_tpu.obs import blackbox
from raft_tpu.transport.tpu_mesh import TpuMeshTransport


def _enable_cpu_collectives() -> None:
    """On the CPU backend, multi-process XLA computations need a
    cross-process collectives implementation — without one every
    sharded computation dies with ``INVALID_ARGUMENT: Multiprocess
    computations aren't implemented on the CPU backend``. Select Gloo
    (the CI stand-in for DCN) when the knob exists and is unset; a
    TPU/GPU backend ignores it. Must run BEFORE the backend
    initializes, which is why the distributed dial calls it first."""
    try:
        if jax.config._read("jax_cpu_collectives_implementation") in (
            None, "none",
        ):
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo"
            )
    except Exception:
        pass   # a jax line without the knob: nothing to select


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: int = 1,
    process_id: int = 0,
) -> None:
    """Bring up the JAX distributed runtime (a no-op for one process).

    After this, ``jax.devices()`` returns the GLOBAL device list on every
    process — the raw material for ``replica_devices_across_hosts``."""
    if num_processes <= 1:
        return
    _enable_cpu_collectives()
    # write-before-block (obs.blackbox): the distributed runtime dial is
    # the first cross-process rendezvous — a dead coordinator or a
    # firewalled port hangs exactly here, and only the journal says so
    blackbox.mark(
        "distributed_init", coordinator=str(coordinator_address),
        num_processes=num_processes, process_id=process_id,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    blackbox.mark("distributed_init_done", process_id=process_id)


def replica_devices_across_hosts(
    n_replicas: int,
    payload_shards: int = 1,
    devices: Optional[Sequence] = None,
) -> list:
    """Pick ``n_replicas * payload_shards`` devices so that each replica's
    block comes from a distinct process where possible.

    Grouping key is ``device.process_index`` (the JAX failure domain: one
    host process = one set of locally-attached chips). Placement rules:

    - at least ``n_replicas`` processes: replica i's block is taken wholly
      from process i's devices — every replica in its own failure domain,
      replica-axis collectives ride DCN;
    - fewer processes than replicas: replicas are dealt round-robin over
      the processes (as failure-isolated as the hardware allows), falling
      back to one flat device list for the single-process case.

    Raises when the fabric cannot supply ``payload_shards`` devices from a
    single process for some replica (payload shards must stay on one
    host's ICI — a byte-sliced log row spanning DCN would put the hot
    window path on the slow fabric).
    """
    if devices is None:
        # write-before-block: with no live backend, jax.devices()
        # INITIALIZES one — on a real-chip platform that dials the TPU
        # tunnel and can hang indefinitely (the round-5 failure mode
        # __graft_entry__._backend_initialized documents)
        blackbox.mark(
            "device_enum", n_replicas=n_replicas,
            payload_shards=payload_shards,
        )
    devs = list(devices) if devices is not None else list(jax.devices())
    by_proc: dict = {}
    for d in devs:
        by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
    procs = sorted(by_proc)
    if len(procs) == 1:
        flat = by_proc[procs[0]]
        need = n_replicas * payload_shards
        if len(flat) < need:
            raise ValueError(
                f"need {need} devices, single process has {len(flat)}"
            )
        return flat[:need]
    picked = []
    # Greedy block placement: for each replica pick, among the processes
    # that still have a full payload_shards block free, the least-used one
    # (ties broken toward more free devices). This maximizes failure
    # isolation when processes are plentiful AND still places on uneven
    # fabrics (e.g. 2+6 devices over two processes) where a rigid
    # round-robin would dead-end on an exhausted process.
    used = {p: 0 for p in procs}
    cursor = {p: 0 for p in procs}
    for r in range(n_replicas):
        viable = [
            p for p in procs
            if len(by_proc[p]) - cursor[p] >= payload_shards
        ]
        if not viable:
            free = {p: len(by_proc[p]) - cursor[p] for p in procs}
            raise ValueError(
                f"replica {r}: no process has {payload_shards} free "
                f"devices (free per process: {free}); a replica's payload "
                "shards must stay on one process's ICI"
            )
        p = min(
            viable, key=lambda q: (used[q], -(len(by_proc[q]) - cursor[q]))
        )
        at = cursor[p]
        picked.extend(by_proc[p][at:at + payload_shards])
        cursor[p] = at + payload_shards
        used[p] += 1
    return picked


def multihost_transport(
    cfg: RaftConfig,
    payload_shards: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> TpuMeshTransport:
    """A mesh transport whose replica axis spans hosts (see module doc).
    ``devices`` restricts placement to a subset of the global device list
    (default: all of ``jax.devices()``)."""
    shards = cfg.payload_shards if payload_shards is None else payload_shards
    devs = replica_devices_across_hosts(cfg.n_replicas, shards, devices)
    return TpuMeshTransport(cfg, devs, payload_shards=shards)
