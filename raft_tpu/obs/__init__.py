"""Observability: trace capture + protocol metrics.

The reference's only observability is ``nodelog`` printing
``[Id:Term:CommitIndex:LastApplied][state]message`` to stdout from 19 call
sites (main.go:399-401). That schema is kept verbatim — it is the
differential-test join key between the golden model, the engine, and (by
eye) the original Go binary — and extended with structured capture and the
BASELINE metric set (entries/sec, p50/p99 commit latency).
"""

from raft_tpu.obs.trace import TraceRecord, TraceRecorder
from raft_tpu.obs.metrics import LatencySummary, summarize_engine

__all__ = ["TraceRecord", "TraceRecorder", "LatencySummary", "summarize_engine"]
