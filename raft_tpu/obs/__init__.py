"""Observability: flight recorder, op spans, metrics, forensics.

The reference's only observability is ``nodelog`` printing
``[Id:Term:CommitIndex:LastApplied][state]message`` to stdout from 19
call sites (main.go:399-401). That schema is kept verbatim — it is the
differential-test join key between the golden model, the engine, and
(by eye) the original Go binary — and grown into a real plane:

- ``events``    — the flight recorder: a typed, bounded ring of
  structured events; the legacy nodelog string is now a *rendering*
  (``Event.nodelog()``, byte-identical).
- ``spans``     — causal per-op tracing through router → admission →
  engine → commit → apply, exportable as Chrome/Perfetto trace JSON.
- ``registry``  — counters/gauges/histograms with per-group labels,
  Prometheus text exposition + JSON snapshot.
- ``forensics`` — repro bundles on unexpected chaos verdicts and the
  ``python -m raft_tpu.obs --explain`` timeline reconstruction.
- ``trace``     — the legacy string-capture ``TraceRecorder`` (kept:
  the golden differential tests join on raw lines).
- ``metrics``   — the BASELINE report (entries/s, p50/p99 commit
  latency), now carrying the registry snapshot too.
- ``hostprof``  — per-tick host-time attribution: phase timers tiling
  the engine step (heap_pop / host_pre / pack / dispatch / device_wait
  / host_post), feeding the ``raft_host_phase_seconds`` histogram and
  the bench ``attribution`` leg — plus the wire-side twin
  ``PumpProfiler`` tiling each ingest-pump iteration (read_decode /
  coalesce / ingest / drive / sweep / flush) for the
  ``raft_net_pump_phase_seconds`` histogram and the ``macro`` leg's
  pump table (docs/OBSERVABILITY.md "Wire plane").
- ``blackbox``  — the hang-proof half: per-process append-only progress
  journals (phase marks written BEFORE every blocking operation) and
  the stall watchdog that dumps all-thread stacks + the journal tail
  into a stall bundle when progress stops.
- ``device``    — the device-resident plane: an in-kernel event ring
  (``dev_record`` — legal inside jit/vmap/scan/shard_map) + on-device
  metrics vector written by the recorded step programs, decoded at
  launch boundaries into byte-compatible ``Event`` objects. The trace
  rides inside the compiled program, so the coming K-tick scan fusion
  (ROADMAP item 2) keeps full visibility.
- ``audit``     — the ONLINE safety plane: an incremental
  ``SafetyAuditor`` checking Raft invariants per tick/launch (one
  leader per term, monotone commit/terms, committed-prefix CRC
  immutability, per-client monotone-read watermarks) while the run is
  still going — typed ``AuditViolation`` events, never post-hoc only.
- ``slo``       — streaming log-bucket latency digests (mergeable
  across groups) + per-group SLO objectives with multi-window
  burn-rate evaluation and typed ``SloAlert`` events.
- ``serve``     — the live ops surface: a lock-free ``StatusBoard``
  snapshot the engines publish at flush boundaries, served by a
  stdlib-HTTP ``OpsServer`` (``/metrics`` ``/healthz`` ``/slo``
  ``/status`` ``/compile`` ``/memory`` ``/profile``;
  ``python -m raft_tpu.obs --serve``).
- ``compile``   — the XLA compile plane: ``CompileWatch`` subscribes to
  ``jax.monitoring`` compile events (program attribution via the
  ``labeled`` wrapper at every transport program-cache seam) and the
  ``RetraceSentinel`` turns any post-``freeze()`` compile on a
  registered hot path into a typed ``CompileViolation``
  (``assert_no_recompiles()`` is the tier-1 face).
- ``memory``    — device-memory accounting: a live-buffer census
  (``jax.live_arrays``, bucketed by state-leaf label), baseline/drift
  leak detection across chaos crash-restore and group migration,
  high-water gauges, and the donated-buffer audit.
- ``profiling`` — on-demand ``jax.profiler`` capture
  (``/profile?seconds=N``) merged with the span Perfetto export into
  one timeline artifact, plus per-launch ``StepTraceAnnotation``
  boundaries and the bench device-time helpers.
"""

from raft_tpu.obs import blackbox
from raft_tpu.obs.device import (
    DeviceObs,
    EventRing,
    decode_records,
    dev_record,
    init_ring,
    merged_timeline,
)
from raft_tpu.obs.blackbox import (
    BlackboxJournal,
    StallWatchdog,
    explain_journal,
    explain_stall,
    read_journal,
)
from raft_tpu.obs.audit import AuditViolation, SafetyAuditor
from raft_tpu.obs.compile import (
    CompileRecord,
    CompileViolation,
    CompileWatch,
    RecompileError,
    RetraceSentinel,
    assert_no_recompiles,
)
from raft_tpu.obs.events import Event, FlightRecorder, kind_of
from raft_tpu.obs.memory import (
    DonationReport,
    MemoryCensus,
    MemoryWatch,
    audit_donation,
)
from raft_tpu.obs.forensics import (
    ObsStack,
    explain,
    load_bundle,
    write_bundle,
)
from raft_tpu.obs.hostprof import HostProfiler, PumpProfiler
from raft_tpu.obs.metrics import LatencySummary, summarize_engine
from raft_tpu.obs.registry import MetricsRegistry, parse_prometheus
from raft_tpu.obs.serve import OpsServer, StatusBoard, serve_demo
from raft_tpu.obs.slo import (
    LatencyDigest,
    SLObjective,
    SloAlert,
    SloTracker,
)
from raft_tpu.obs.spans import Span, SpanTracker
from raft_tpu.obs.trace import TraceRecord, TraceRecorder

__all__ = [
    "AuditViolation",
    "BlackboxJournal",
    "CompileRecord",
    "CompileViolation",
    "CompileWatch",
    "DeviceObs",
    "DonationReport",
    "Event",
    "EventRing",
    "FlightRecorder",
    "HostProfiler",
    "PumpProfiler",
    "LatencyDigest",
    "LatencySummary",
    "MemoryCensus",
    "MemoryWatch",
    "MetricsRegistry",
    "ObsStack",
    "OpsServer",
    "RecompileError",
    "RetraceSentinel",
    "SLObjective",
    "SafetyAuditor",
    "SloAlert",
    "SloTracker",
    "Span",
    "SpanTracker",
    "StallWatchdog",
    "StatusBoard",
    "TraceRecord",
    "TraceRecorder",
    "assert_no_recompiles",
    "audit_donation",
    "blackbox",
    "decode_records",
    "dev_record",
    "explain",
    "explain_journal",
    "explain_stall",
    "init_ring",
    "kind_of",
    "load_bundle",
    "merged_timeline",
    "parse_prometheus",
    "read_journal",
    "serve_demo",
    "summarize_engine",
    "write_bundle",
]
