"""Chaos forensics: repro bundles and failure-timeline reconstruction.

Before this module, a failing torture run left behind exactly one
artifact: a seed number to re-run. A repro bundle captures what the run
already knew at the moment the verdict came back wrong — the flight
recorder's event ring, the realized fault schedule, the client op
history, span table, metrics snapshot, seed and config — as one JSON
file, and ``explain()`` (exposed as ``python -m raft_tpu.obs --explain``)
reconstructs the minimal failure timeline from it WITHOUT re-running the
seed: the last leader of each term, the faults in flight around the
violation, and the op that broke linearizability.

The chaos runners write bundles automatically whenever a run ends in
anything but its expected verdict and a destination is configured
(``bundle_dir=`` argument, or the ``RAFT_TPU_BUNDLE_DIR`` environment
variable); with neither set, nothing is written (CI trees stay clean —
the pinned broken-variant tests opt in with a tmp dir).

Joined wire forensics (ISSUE 15): a bundle may carry TWO span tables —
``spans`` (the process's own) and ``client_spans`` (the wire-client
side, when one process ran both ends, as the chaos wire drill does) —
and :func:`explain_joined` reconstructs ONE causal timeline per wire
op by joining span tables on ``wire_trace``: client attempt N → wire
frame → ingest batch (pump iteration) → tick/launch → completion sweep
→ response, across however many artifacts the two processes left
behind. ``python -m raft_tpu.obs --explain CLIENT.json SERVER.json``
(any number of paths) is the CLI entry; nothing re-runs.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

BUNDLE_FORMAT = "raft_tpu.obs/bundle.v1"


@dataclasses.dataclass
class ObsStack:
    """The per-run observability plane the chaos runners attach when
    ``observe=True``: one flight recorder + span tracker + metrics
    registry — plus, when ``device=True``, the device-resident plane
    (``obs.device.DeviceObs``: in-kernel event rings decoded at every
    launch boundary) — shared by every engine the run boots (including
    across crash-restore cycles; each fresh engine gets a fresh ring,
    the DeviceObs accumulates)."""

    recorder: Any
    spans: Any
    registry: Any
    device: Any = None
    audit: Any = None          # obs.audit.SafetyAuditor (online plane)
    slo: Any = None            # obs.slo.SloTracker (online plane)
    compile: Any = None        # obs.compile.CompileWatch (XLA plane)
    memory: Any = None         # obs.memory.MemoryWatch (XLA plane)

    @classmethod
    def build(cls, capacity: int = 65536, device: bool = False,
              audit: bool = False, slo_objectives=None,
              compile_plane: bool = False) -> "ObsStack":
        from raft_tpu.obs.events import FlightRecorder
        from raft_tpu.obs.registry import MetricsRegistry
        from raft_tpu.obs.spans import SpanTracker

        dev = None
        if device:
            from raft_tpu.obs.device import DeviceObs

            dev = DeviceObs()
        recorder = FlightRecorder(capacity=capacity)
        registry = MetricsRegistry()
        auditor = tracker = None
        if audit or slo_objectives is not None:
            from raft_tpu.obs.audit import SafetyAuditor
            from raft_tpu.obs.slo import SloTracker

            auditor = SafetyAuditor(recorder=recorder, registry=registry)
            tracker = SloTracker(
                objectives=tuple(slo_objectives or ()),
                recorder=recorder, registry=registry,
            )
        watch = memwatch = None
        if compile_plane:
            from raft_tpu.obs.compile import CompileWatch, RetraceSentinel
            from raft_tpu.obs.memory import MemoryWatch

            watch = CompileWatch(recorder=recorder, registry=registry)
            RetraceSentinel(watch)
            watch.install()
            memwatch = MemoryWatch(registry=registry, recorder=recorder)
        return cls(
            recorder=recorder,
            spans=SpanTracker(),
            registry=registry,
            device=dev,
            audit=auditor,
            slo=tracker,
            compile=watch,
            memory=memwatch,
        )

    def attach(self, engine) -> None:
        """Point an engine's observability hooks at this stack."""
        engine.recorder = self.recorder
        engine.spans = self.spans
        engine.metrics = self.registry
        if self.audit is not None:
            engine.auditor = self.audit
            # re-attachment across a crash-restore cycle re-verifies
            # the restored committed state against the audit record
            self.audit.on_attach(engine)
        if self.slo is not None:
            engine.slo = self.slo
        if self.device is not None and hasattr(engine, "attach_device_obs"):
            engine.attach_device_obs(self.device)
        if self.memory is not None:
            # re-attachment replaces the previous generation's weakref
            # getters: the census follows the LIVE engine across chaos
            # crash-restore cycles (old generations must collect away —
            # exactly what the flatness pin checks)
            self.memory.watch_engine(engine)

    def close(self) -> None:
        """Detach process-global hooks (the compile watch's monitoring
        subscription). Runners call this when the run ends so one run's
        plane never bleeds into the next."""
        if self.compile is not None:
            self.compile.uninstall()


def resolve_bundle_dir(bundle_dir: Optional[str]) -> Optional[str]:
    """The runner's destination policy: explicit argument, else the
    ``RAFT_TPU_BUNDLE_DIR`` environment variable, else disabled."""
    if bundle_dir is not None:
        return bundle_dir
    return os.environ.get("RAFT_TPU_BUNDLE_DIR") or None


def _b2s(b: Optional[bytes]) -> Optional[str]:
    return None if b is None else b.decode("latin1")


def history_jsonable(history) -> List[dict]:
    return [
        {
            "client": rec.client, "op": rec.op, "key": _b2s(rec.key),
            "value": _b2s(rec.value), "invoke_t": rec.invoke_t,
            "complete_t": rec.complete_t, "status": rec.status,
        }
        for rec in history.ops
    ]


def write_bundle(
    bundle_dir: str,
    *,
    kind: str,
    seed: int,
    expected: str,
    verdict: str,
    detail: str = "",
    violation_key: Optional[bytes] = None,
    repro: str = "",
    config: Optional[object] = None,
    nemesis_log: Optional[List[str]] = None,
    history=None,
    obs: Optional[ObsStack] = None,
    spans=None,
    client_spans=None,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Write one repro bundle; returns the bundle file path.

    ``spans`` overrides the span table when no full ObsStack exists
    (a wire-client-side artifact is just a SpanTracker); ``client_spans``
    adds the client-side table ALONGSIDE a server-side stack when one
    process ran both ends of the wire (the chaos wire drill) — the
    input :func:`explain_joined` joins on."""
    Path(bundle_dir).mkdir(parents=True, exist_ok=True)
    span_table = None
    if spans is not None:
        span_table = spans.to_jsonable()
    elif obs is not None:
        span_table = obs.spans.to_jsonable()
    bundle = {
        "format": BUNDLE_FORMAT,
        "kind": kind,
        "seed": seed,
        "expected": expected,
        "verdict": verdict,
        "detail": detail,
        "violation_key": _b2s(violation_key),
        "repro": repro,
        "config": (
            dataclasses.asdict(config) if dataclasses.is_dataclass(config)
            else config
        ),
        "faults": list(nemesis_log or []),
        "history": history_jsonable(history) if history is not None else [],
        "events": obs.recorder.to_jsonable() if obs is not None else None,
        "spans": span_table,
        "client_spans": (client_spans.to_jsonable()
                         if client_spans is not None else None),
        "metrics": obs.registry.to_json() if obs is not None else None,
        "device_ring": (
            obs.device.to_jsonable()
            if obs is not None and getattr(obs, "device", None) is not None
            else None
        ),
        "audit": (
            obs.audit.to_jsonable()
            if obs is not None and getattr(obs, "audit", None) is not None
            else None
        ),
        "slo": (
            obs.slo.snapshot()
            if obs is not None and getattr(obs, "slo", None) is not None
            else None
        ),
        "compile_log": (
            obs.compile.snapshot()
            if obs is not None
            and getattr(obs, "compile", None) is not None
            else None
        ),
        "memory": (
            obs.memory.snapshot()
            if obs is not None
            and getattr(obs, "memory", None) is not None
            else None
        ),
        "extra": extra or {},
    }
    path = Path(bundle_dir) / f"bundle_{kind}_seed{seed}.json"
    path.write_text(json.dumps(bundle))
    return str(path)


def load_bundle(path: str) -> dict:
    bundle = json.loads(Path(path).read_text())
    if bundle.get("format") != BUNDLE_FORMAT:
        raise ValueError(
            f"{path}: not a raft_tpu repro bundle "
            f"(format={bundle.get('format')!r})"
        )
    return bundle


# -------------------------------------------------- joined wire explain
def _wire_sides(bundles: List[dict]):
    """Partition every wire-traced span across the artifacts into
    (client, server) lists. The discriminator is structural, not
    positional: a span that MINTED its trace has no ``parent_span``
    (the client op root); a span that ADOPTED a remote parent is the
    server side — so it does not matter which artifact carried which
    table, or whether one bundle carried both."""
    from raft_tpu.obs.spans import spans_from_jsonable

    client, server = [], []
    for b in bundles:
        for key in ("spans", "client_spans"):
            tbl = b.get(key)
            if not tbl:
                continue
            for sp in spans_from_jsonable(tbl):
                if sp.wire_trace is None:
                    continue
                (client if sp.parent_span is None else server).append(sp)
    return client, server


def _span_entries(sp, side: str):
    """(t, side, text) timeline entries for one span, in the span's
    own causal (annotation) order."""
    out = []
    label = f"begin {sp.op}"
    if sp.key:
        label += f" key={sp.key.decode('latin1')!r}"
    if side == "server" and sp.client is not None:
        label += f" ({sp.client})"
    out.append((sp.t_start, side, label))
    for t, name, fields in sp.annotations:
        if name.startswith("end:"):
            continue
        desc = name + "".join(
            f" {k}={v}" for k, v in fields.items() if v is not None
        )
        out.append((t, side, desc))
    t_end = sp.t_end if sp.t_end is not None else sp.t_start
    end = f"end:{sp.state}"
    if sp.refusal_reasons:
        end += f" refusals={','.join(sp.refusal_reasons)}"
    out.append((t_end, side, end))
    return out


def explain_joined(bundles: List[dict], max_traces: int = 64) -> str:
    """ONE causal timeline per wire op, joined across both processes'
    span tables on ``wire_trace`` — client attempt N → wire frame →
    ingest batch → tick/launch → completion sweep → response — from
    the artifacts alone (nothing re-runs). A client op with retries
    joins to SEVERAL server spans (one per wire frame); all of them
    render into the op's single timeline."""
    client, server = _wire_sides(bundles)
    by_trace: Dict[int, Tuple[list, list]] = {}
    for sp in client:
        by_trace.setdefault(sp.wire_trace, ([], []))[0].append(sp)
    for sp in server:
        by_trace.setdefault(sp.wire_trace, ([], []))[1].append(sp)
    out = [
        f"joined wire forensics: {len(by_trace)} trace(s) — "
        f"{len(client)} client op(s), {len(server)} server span(s)"
    ]

    def _severity(tid: int) -> tuple:
        # non-ok ops are the forensic signal: render them FIRST so the
        # max_traces elision can only ever drop clean ops
        cs, ss = by_trace[tid]
        ok = all(sp.state == "ok" for sp in cs + ss)
        return (1 if ok else 0, tid)

    shown = 0
    for tid in sorted(by_trace, key=_severity):
        cs, ss = by_trace[tid]
        if shown >= max_traces:
            out.append(
                f"... {len(by_trace) - shown} more trace(s) elided "
                f"(max_traces={max_traces})"
            )
            break
        shown += 1
        root = cs[0] if cs else ss[0]
        head = f"trace 0x{tid:x}: {root.op}"
        if root.key:
            head += f" key={root.key.decode('latin1')!r}"
        if cs:
            head += f" -> {cs[0].state}"
            if cs[0].refusal_reasons:
                head += f" ({cs[0].refusal_reasons[-1]})"
            if cs[0].retries:
                head += f" after {cs[0].retries} retr" + (
                    "y" if cs[0].retries == 1 else "ies")
            if cs[0].redials:
                head += f", {cs[0].redials} redial(s)"
        if not ss:
            head += " [no server span joined]"
        elif not cs:
            head += " [no client span joined]"
        out.append(head)
        # CAUSAL merge, not a timestamp sort: the virtual clock often
        # stamps a whole request/response exchange with ONE time, and
        # the two processes' clocks need not even agree — but the
        # client saga's annotation order is authoritative, and every
        # response annotation carries the answering server span's id
        # (``server_span=``), so each server span ANCHORS exactly
        # before the client entry that observed its response.
        entries = []            # (rank tuple, t, side, text)
        pos = 0
        anchor: Dict[int, int] = {}
        for sp in cs:
            base = pos
            for t, side, text in _span_entries(sp, "client"):
                entries.append(((pos, 1, 0, 0), t, side, text))
                pos += 1
            j = base + 1        # entry index of the first annotation
            for _t, name, fields in sp.annotations:
                if name.startswith("end:"):
                    continue
                ssid = fields.get("server_span")
                if ssid is not None and ssid not in anchor:
                    anchor[ssid] = j
                j += 1
        for o, sp in enumerate(ss):
            sid = sp.span_id if sp.span_id is not None else sp.trace_id
            base = anchor.get(sid, pos)
            for k, (t, side, text) in enumerate(
                _span_entries(sp, "server")
            ):
                # all of a server span's entries land just BEFORE the
                # client entry that saw its response (rank slot 0 < the
                # client's slot 1 at the same base); `o` keeps two
                # spans sharing one base — e.g. two never-answered
                # attempts — as intact blocks instead of interleaving
                # line-by-line, and `k` keeps each span's own order
                entries.append(((base, 0, o, k), t, side, text))
        entries.sort(key=lambda e: e[0])
        out.extend(
            f"  [{side}] t={t:<10.4f} {text}"
            for _rank, t, side, text in entries
        )
    return "\n".join(out)


# --------------------------------------------------------------- explain
_FAULT_T = re.compile(r"^t=(?P<t>[0-9.]+)\s+(?P<desc>.*)$")


def _suspect_op(bundle: dict) -> Optional[dict]:
    """Name the op that broke linearizability, from the recorded history
    alone (no checker re-run): on the checker's offending key, the first
    OK read whose returned value either was never written, was written
    by an op that provably failed, or was invoked only AFTER the read
    completed. Falls back to None when the heuristic finds nothing —
    the per-key timeline is still printed either way."""
    key = bundle.get("violation_key")
    if key is None:
        return None
    kops = [op for op in bundle["history"] if op["key"] == key]
    writers: Dict[Optional[str], dict] = {}
    for op in kops:
        if op["op"] in ("write", "delete"):
            val = op["value"] if op["op"] == "write" else None
            writers.setdefault(val, op)
    for op in kops:
        if op["op"] != "read" or op["status"] != "ok":
            continue
        w = writers.get(op["value"])
        if op["value"] is not None and w is None:
            return dict(op, why="read a value no client ever wrote")
        if w is None:
            continue
        if w["status"] == "fail" and op["value"] is not None:
            # None is also the key's INITIAL state, so a read of None
            # after a failed delete is perfectly linearizable — only a
            # concrete value proves the reader saw the failed writer
            return dict(
                op, why="read a value whose write provably took no effect"
            )
        if (op["complete_t"] is not None
                and w["invoke_t"] > op["complete_t"]):
            return dict(op, why="read a value written only later")
    # new-then-old inversion (the dirty-read signature): a read returns
    # value v_new, and a LATER read returns v_old whose write began
    # before v_new's write — no linearization can order both.
    ok_reads = [op for op in kops
                if op["op"] == "read" and op["status"] == "ok"]
    for i, r1 in enumerate(ok_reads):
        w1 = writers.get(r1["value"])
        if w1 is None or r1["complete_t"] is None:
            continue
        for r2 in ok_reads[i + 1:]:
            if r2["invoke_t"] < r1["complete_t"]:
                continue            # concurrent reads constrain nothing
            w2 = writers.get(r2["value"])
            if w2 is not None and w2["invoke_t"] < w1["invoke_t"]:
                return dict(
                    r2, why=(
                        f"stale read: returned {r2['value']!r} after an "
                        f"earlier read already returned the newer "
                        f"{r1['value']!r}"
                    ),
                )
    return None


def explain(bundle: dict) -> str:
    """The minimal failure timeline, reconstructed from a bundle."""
    out: List[str] = []
    out.append(
        f"{bundle['kind']} seed {bundle['seed']}: verdict "
        f"{bundle['verdict']} (expected {bundle['expected']})"
    )
    if bundle.get("detail"):
        out.append(f"  checker: {bundle['detail']}")
    if bundle.get("repro"):
        out.append(f"  repro:   {bundle['repro']}")

    # -- last leader per term (flight recorder) -------------------------
    events = bundle.get("events")
    if events and events.get("events"):
        from raft_tpu.obs.events import Event

        evs = [Event.from_jsonable(d) for d in events["events"]]
        last_leader: Dict[tuple, Any] = {}
        for e in evs:
            if e.kind == "elect":
                last_leader[(e.group, e.term)] = e
        if last_leader:
            out.append("last leader per term:")
            for (g, term), e in sorted(
                last_leader.items(), key=lambda kv: (kv[0][0] or 0, kv[0][1])
            ):
                scope = f"g{g} " if g is not None else ""
                out.append(
                    f"  {scope}term {term}: {e.node} "
                    f"(elected t={e.t_virtual:.1f})"
                )
        if events.get("dropped"):
            out.append(
                f"  (ring overflowed: {events['dropped']} oldest events "
                "dropped)"
            )
    else:
        out.append("last leader per term: no flight recorder data "
                   "(run with observe=True for the full ring)")

    # -- the violating op ----------------------------------------------
    suspect = _suspect_op(bundle)
    key = bundle.get("violation_key")
    t_focus = None
    if suspect is not None:
        t_focus = suspect.get("complete_t") or suspect.get("invoke_t")
        out.append(
            f"violating op: client {suspect['client']} read "
            f"{suspect['key']!r} -> {suspect['value']!r} "
            f"[{suspect['invoke_t']:.2f}, {suspect['complete_t']:.2f}] "
            f"— {suspect['why']}"
        )
    elif key is not None:
        out.append(
            f"violating op: not isolated by heuristic; offending key "
            f"{key!r} timeline below"
        )

    # -- device plane (obs.device: in-kernel event ring) ---------------
    dev_entries = []
    dr = bundle.get("device_ring")
    if dr is not None:
        from raft_tpu.obs.events import Event

        dev_evs = [Event.from_jsonable(d) for d in dr.get("events", [])]
        by_kind: Dict[str, int] = {}
        for e in dev_evs:
            by_kind[e.kind] = by_kind.get(e.kind, 0) + 1
        kinds = ", ".join(f"{k}={n}" for k, n in sorted(by_kind.items()))
        out.append(
            f"device ring: {dr.get('total_recorded', len(dev_evs))} "
            f"records ({kinds or 'none'})"
        )
        if dr.get("dropped"):
            out.append(
                f"  (ring lapped {dr.get('laps', 0)}x: "
                f"{dr['dropped']} oldest device records dropped)"
            )
        dev_entries = [
            (
                e.t_virtual,
                f"[device] {e.kind} {e.node} term={e.term}"
                + (f" commit={e.commit_index}"
                   if e.kind == "commit" else "")
                + (f" aux={e.fields.get('aux')}"
                   if e.kind in ("repair_floor", "step_down") else ""),
            )
            for e in dev_evs
        ]

    # -- compile plane (obs.compile: retraces + sentinel) ---------------
    cl = bundle.get("compile_log")
    if cl is not None:
        sent = cl.get("sentinel") or {}
        viols = sent.get("violations") or []
        post_freeze = [
            r for r in cl.get("log", [])
            if r.get("frozen") and r.get("event") in ("trace", "compile")
        ]
        out.append(
            f"compile plane: {cl.get('total_traces', 0)} traces, "
            f"{cl.get('total_compiles', 0)} compiles "
            f"({cl.get('total_compile_s', 0.0):.2f}s), "
            f"{len(viols)} hot-path violation(s)"
        )
        for v in viols[:6]:
            shapes = v.get("arg_shapes")
            out.append(
                f"  RETRACE: post-freeze {v['event']} on "
                f"{v['program']!r} at t_wall={v['t_wall']:.1f}s"
                + (f" args=({', '.join(shapes)})" if shapes else "")
            )
        if not viols and post_freeze:
            progs = sorted({r["program"] for r in post_freeze})
            out.append(
                f"  (post-freeze compiles off the hot paths: "
                f"{', '.join(progs)})"
            )

    # -- memory plane (obs.memory: census growth) -----------------------
    mem = bundle.get("memory")
    if mem is not None and mem.get("census"):
        cur, base = mem["census"], mem.get("baseline")
        line = (
            f"memory plane: {cur['n_arrays']} live buffers, "
            f"{cur['total_bytes']} bytes "
            f"(high water {mem.get('high_water_bytes', 0)})"
        )
        out.append(line)
        if base is not None:
            growth = cur["total_bytes"] - base["total_bytes"]
            if growth > 0:
                out.append(
                    f"  CENSUS GREW: {growth:+d} bytes over baseline "
                    f"({base['total_bytes']} -> {cur['total_bytes']}) — "
                    "possible leak across crash-restore/migration"
                )
        don = mem.get("donation")
        if don is not None and not don.get("engaged", True):
            out.append(
                f"  donation IGNORED on backend "
                f"{don.get('backend')!r}: {don.get('detail')}"
            )

    # -- faults in flight (device events interleaved) ------------------
    faults = []
    for line in bundle.get("faults", []):
        m = _FAULT_T.match(line)
        if m:
            faults.append((float(m["t"]), m["desc"]))
    timeline = sorted(faults + dev_entries, key=lambda f: f[0])
    if timeline:
        if t_focus is not None:
            window = [f for f in timeline if f[0] <= t_focus]
            window = window[-(6 + min(len(dev_entries), 6)):]
            label = f"timeline before t={t_focus:.1f}:"
        else:
            window = timeline[-12:]
            label = "final fault/device timeline:"
        out.append(label)
        out.extend(f"  t={t:>8.1f}  {d}" for t, d in window)

    # -- the offending key's op timeline -------------------------------
    if key is not None:
        kops = [op for op in bundle["history"] if op["key"] == key]
        out.append(f"key {key!r} history ({len(kops)} ops):")
        for op in kops:
            end = ("inf" if op["complete_t"] is None
                   else f"{op['complete_t']:.2f}")
            mark = (" <== violation" if suspect is not None
                    and op["invoke_t"] == suspect["invoke_t"]
                    and op["client"] == suspect["client"] else "")
            out.append(
                f"  c{op['client']:<4} {op['op']:<6} "
                f"{(op['value'] or ''):<12} [{op['invoke_t']:.2f}, {end}] "
                f"{op['status']}{mark}"
            )
    return "\n".join(out)
