"""Host-time attribution: per-tick phase timers around the engine step.

The performance question the ROADMAP leaves open (item 2): device time
per step is ~2 µs while the engine's wall cost per tick is two orders
of magnitude higher — and nothing measured WHERE the other 99% goes.
This module decomposes one engine tick into contiguous host phases on
``time.perf_counter``:

==============  ========================================================
phase           what it covers
==============  ========================================================
``heap_pop``    event-heap pop, virtual-clock advance, stale-timer check
``host_pre``    pre-dispatch bookkeeping: CheckQuorum, admission delay
                observation, staged-config drive, batch clamp, repair
                floor attest and the cached last/match fetches
``pack``        ingest batching: entry bytes -> the folded device batch
                (``_pack_entries`` / ``fold_batch`` / EC encode)
``dispatch``    the transport ``replicate`` call itself — on an async
                backend this returns after launch, not completion
``device_wait`` explicit ``jax.block_until_ready`` on the step's
                outputs — device execution + queue time not already
                hidden under dispatch
``host_post``   post-step bookkeeping: truncation notes, seq->index
                mapping, commit/apply/archive, read confirmation, span
                hooks, heartbeat re-arm, mirror digest
==============  ========================================================

The phases are *boundary-marked* — each ``mark(phase)`` attributes the
time since the previous boundary — so they tile the tick interval with
no gaps by construction: their sum equals the measured tick wall time
up to the marking overhead itself. That is what lets the bench
``attribution`` leg promise host+device columns that sum to the wall
slope (docs/PERF.md).

Overhead contract (the flight-recorder contract, extended): the
profiler is pure host bookkeeping, and the ``device_wait`` sync — the
ONE deliberate device interaction — exists only behind
:meth:`HostProfiler.sync`, which no engine path calls unless a profiler
is attached. Observe-off therefore costs zero extra device syncs
(pinned by the sync-counting test, like ``test_obs_plane``'s no-fetch
pin).

Per-tick phase seconds feed the PR-5 metrics registry as the
``raft_host_phase_seconds`` histogram, labeled ``(group, phase)`` —
under ``MultiEngine`` a shared batched launch observes once per
participating group (the launch is shared; attribution is per group by
construction of the group axis).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

#: µs-to-100ms log-spaced buckets: host phases live in the 1 µs - 1 ms
#: band on a local backend and the 10-100 ms band through a dispatch
#: tunnel; the default registry buckets (0.5s+) would flatten both.
HOST_PHASE_BUCKETS = (
    1e-6, 3e-6, 1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 1e-1,
)

PHASES = (
    "heap_pop", "host_pre", "pack", "dispatch", "device_wait", "host_post",
)


class HostProfiler:
    """Boundary-marking per-tick phase accumulator (see module doc).

    Attach with ``engine.hostprof = HostProfiler(registry=engine.metrics)``
    (registry optional — totals work standalone). The engine calls
    ``tick_begin`` / ``mark`` / ``sync`` / ``tick_end`` only when a
    profiler is attached; detached costs one ``is None`` check per site.
    """

    def __init__(self, registry=None, buckets=HOST_PHASE_BUCKETS):
        self.registry = registry
        self._hist = (
            registry.histogram(
                "raft_host_phase_seconds",
                "host wall seconds per engine tick by phase",
                ("group", "phase"), buckets=buckets,
            )
            if registry is not None else None
        )
        self.ticks = 0
        self.phase_s: Dict[str, float] = {}
        self.phase_marks: Dict[str, int] = {}
        self._cur: Dict[str, float] = {}
        self._last: Optional[float] = None

    # ----------------------------------------------------------- marking
    def tick_begin(self) -> None:
        self._cur = {}
        self._last = time.perf_counter()

    def mark(self, phase: str) -> None:
        """Attribute the time since the previous boundary to ``phase``.
        Marking the same phase twice in one tick accumulates (the engine
        marks ``host_pre`` both before and after the pack). Outside an
        open ``tick_begin``/``tick_end`` bracket this is a no-op: call
        paths that reach the marked engine internals directly (e.g. a
        ``read_index`` driving ``_replicate_round`` without a tick)
        must neither leak partial samples into the next tick nor inflate
        the mark counters with samples no tick_end will ever flush."""
        if self._last is None:
            return
        now = time.perf_counter()
        self._cur[phase] = self._cur.get(phase, 0.0) + (now - self._last)
        self.phase_marks[phase] = self.phase_marks.get(phase, 0) + 1
        self._last = now

    def sync(self, *values) -> None:
        """Block until the step's device outputs are ready and attribute
        the wait to ``device_wait`` — the ONE profiler operation that
        touches the device, deliberately absent from every detached
        engine path (the observe-off zero-extra-syncs contract). Like
        :meth:`mark`, a no-op outside an open tick bracket (an
        unattributable block would be pure added latency)."""
        if self._last is None:
            return
        import jax

        jax.block_until_ready(values)
        self.mark("device_wait")

    def tick_end(self, groups: Sequence[str] = ("0",)) -> None:
        """Close the tick: the residue since the last boundary is
        ``host_post`` (post-step bookkeeping runs from the final
        explicit mark to here), then the per-tick phase seconds flush
        into the totals and — when a registry is attached — into the
        ``raft_host_phase_seconds`` histogram once per group label."""
        self.mark("host_post")
        self.ticks += 1
        for phase, s in self._cur.items():
            self.phase_s[phase] = self.phase_s.get(phase, 0.0) + s
            if self._hist is not None:
                for g in groups:
                    self._hist.observe(s, group=str(g), phase=phase)
        self._cur = {}
        self._last = None

    # ----------------------------------------------------------- results
    def totals(self) -> Dict[str, float]:
        """phase -> accumulated seconds over all ticks."""
        return dict(self.phase_s)

    def us_per_tick(self) -> Dict[str, float]:
        """phase -> mean µs per tick (0 ticks -> empty)."""
        if not self.ticks:
            return {}
        return {
            p: s / self.ticks * 1e6 for p, s in sorted(self.phase_s.items())
        }

    def split(self) -> Tuple[float, float]:
        """(host_us_per_tick, device_us_per_tick): ``device_wait`` is
        the device column, every other phase is host control plane."""
        per = self.us_per_tick()
        dev = per.get("device_wait", 0.0)
        return sum(per.values()) - dev, dev


#: the pump phases that TILE one ingest-server pump iteration by
#: construction (boundary marking, exactly the engine-tick discipline
#: above). ``read_decode`` is the sixth attributed phase but lives in
#: the READER tasks — the socket-to-frame work the asyncio loop runs
#: between pump iterations — so it is accumulated alongside, not
#: inside, the iteration bracket (and excluded from the coverage
#: denominator, which is defined over the iteration wall).
PUMP_PHASES = (
    "read_decode", "coalesce", "ingest", "drive", "sweep", "flush",
)

#: power-of-two coalesce-batch-size buckets: one pump ingest batch is
#: 1..max_pending frames
COALESCE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096)


class PumpProfiler:
    """Per-iteration phase attribution for the ingest-server pump —
    the wire-side analogue of :class:`HostProfiler` (ISSUE 15: the
    macro leg claims "the tick loop, not the wire, is the bottleneck";
    this is the instrument that turns the claim into a per-phase
    table).

    ==============  ====================================================
    phase           what it covers
    ==============  ====================================================
    ``read_decode`` reader tasks: socket reads -> parsed frames ->
                    coalesce-buffer appends (outside the pump bracket)
    ``coalesce``    pump-side batch swap + arrival bookkeeping
                    (queue-age observation per coalesced frame)
    ``ingest``      admission + routing + ``StagingRing`` pre-pack, per
                    BATCH of arrivals (the network side of the wall)
    ``drive``       ``backend.drive`` — the tick loop's quantum
    ``sweep``       completion sweep: durable writes + confirmed read
                    tickets resolved back to response frames
    ``flush``       status publish + writer drain (the residue to the
                    iteration boundary, exactly ``host_post``'s rule)
    ==============  ====================================================

    The five pump-side phases are boundary-marked, so they tile the
    iteration wall with no gaps by construction: ``coverage()`` ==
    attributed/wall up to the marking overhead itself (the >= 0.90
    acceptance in the bench macro leg is conservative).

    Distributions: ``raft_net_pump_phase_seconds{phase}`` (µs-scale
    buckets), ``raft_net_coalesce_batch`` (frames per ingest batch) and
    ``raft_net_frame_queue_age_seconds`` (arrival -> ingest age per
    frame) in the attached registry, plus mergeable
    ``obs.slo.LatencyDigest`` percentiles for ``stats()``/bench.

    Overhead contract (the PR-6 rule): pure ``time.perf_counter``
    bookkeeping — no rng, no device interaction anywhere in the class,
    so attaching it costs ZERO extra device syncs (fetch-count pinned
    by tests/test_wire_trace.py) and cannot perturb a seeded run.
    """

    def __init__(self, registry=None, buckets=HOST_PHASE_BUCKETS):
        from raft_tpu.obs.slo import LatencyDigest

        self.registry = registry
        if registry is not None:
            self._hist = registry.histogram(
                "raft_net_pump_phase_seconds",
                "wall seconds per ingest-pump iteration by phase",
                ("phase",), buckets=buckets,
            )
            self._batch_hist = registry.histogram(
                "raft_net_coalesce_batch",
                "frames coalesced into one pump ingest batch",
                (), buckets=COALESCE_BUCKETS,
            )
            self._age_hist = registry.histogram(
                "raft_net_frame_queue_age_seconds",
                "coalesce-buffer residence per frame (arrival->ingest)",
                (), buckets=buckets,
            )
        else:
            self._hist = self._batch_hist = self._age_hist = None
        self.iters = 0
        self.phase_s: Dict[str, float] = {}
        self.iter_wall_s = 0.0
        self.batch_sizes = LatencyDigest()
        self.queue_age = LatencyDigest()
        self._cur: Dict[str, float] = {}
        self._t0: Optional[float] = None
        self._last: Optional[float] = None

    # ----------------------------------------------------------- marking
    def iter_begin(self) -> None:
        self._cur = {}
        self._t0 = self._last = time.perf_counter()

    def mark(self, phase: str) -> None:
        """Attribute time since the previous boundary to ``phase``
        (no-op outside an open iteration bracket, like HostProfiler)."""
        if self._last is None:
            return
        now = time.perf_counter()
        self._cur[phase] = self._cur.get(phase, 0.0) + (now - self._last)
        self._last = now

    def iter_end(self) -> None:
        """Close the iteration: the residue since the last boundary is
        ``flush`` (writer drain runs from the final explicit mark to
        here), then the per-iteration seconds flush into totals and the
        registry histogram."""
        if self._t0 is None:
            return
        self.mark("flush")
        # the flush mark's own boundary IS the iteration end — one
        # clock reading, so the phases tile the wall EXACTLY (a second
        # perf_counter call here would open a sub-µs gap)
        self.iter_wall_s += self._last - self._t0
        self.iters += 1
        for phase, s in self._cur.items():
            self.phase_s[phase] = self.phase_s.get(phase, 0.0) + s
            if self._hist is not None:
                self._hist.observe(s, phase=phase)
        self._cur = {}
        self._t0 = self._last = None

    # --------------------------------------------------- reader-side feed
    def note_read_decode(self, seconds: float) -> None:
        """Reader-task attribution: one socket read's decode + frame
        handling (accumulated outside the iteration bracket)."""
        self.phase_s["read_decode"] = (
            self.phase_s.get("read_decode", 0.0) + seconds
        )
        if self._hist is not None:
            self._hist.observe(seconds, phase="read_decode")

    def observe_batch(self, n_frames: int) -> None:
        self.batch_sizes.observe(float(n_frames))
        if self._batch_hist is not None:
            self._batch_hist.observe(n_frames)

    def observe_age(self, seconds: float) -> None:
        self.queue_age.observe(seconds)
        if self._age_hist is not None:
            self._age_hist.observe(seconds)

    # ----------------------------------------------------------- results
    def totals(self) -> Dict[str, float]:
        return dict(self.phase_s)

    def us_per_iter(self) -> Dict[str, float]:
        """phase -> mean µs per pump iteration (read_decode reported on
        the same denominator for comparability)."""
        if not self.iters:
            return {}
        return {
            p: s / self.iters * 1e6
            for p, s in sorted(self.phase_s.items())
        }

    def coverage(self) -> float:
        """Attributed fraction of the pump iteration wall: the tiled
        phases' sum over the bracketed wall (1.0 up to marking
        overhead; ``read_decode`` is outside both numerator and
        denominator by definition)."""
        if self.iter_wall_s <= 0.0:
            return 0.0
        tiled = sum(s for p, s in self.phase_s.items()
                    if p != "read_decode")
        return tiled / self.iter_wall_s

    def stats(self) -> dict:
        """The ``pump`` block of the server's ``net`` /status section
        (JSON-safe: empty digests report None, never NaN)."""
        def _q(dig, q, scale=1.0):
            return dig.quantile(q) * scale if dig.n else None

        per = self.us_per_iter()
        return {
            "iters": self.iters,
            "us_per_iter": {p: round(v, 2) for p, v in per.items()},
            "coverage": round(self.coverage(), 4),
            "coalesce_batch": {
                "p50": _q(self.batch_sizes, 0.5),
                "p99": _q(self.batch_sizes, 0.99),
                "max": self.batch_sizes.max if self.batch_sizes.n else None,
                "n": self.batch_sizes.n,
            },
            "queue_age_us": {
                "p50": _q(self.queue_age, 0.5, 1e6),
                "p99": _q(self.queue_age, 0.99, 1e6),
                "max": (self.queue_age.max * 1e6
                        if self.queue_age.n else None),
                "n": self.queue_age.n,
            },
        }
