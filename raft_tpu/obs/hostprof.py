"""Host-time attribution: per-tick phase timers around the engine step.

The performance question the ROADMAP leaves open (item 2): device time
per step is ~2 µs while the engine's wall cost per tick is two orders
of magnitude higher — and nothing measured WHERE the other 99% goes.
This module decomposes one engine tick into contiguous host phases on
``time.perf_counter``:

==============  ========================================================
phase           what it covers
==============  ========================================================
``heap_pop``    event-heap pop, virtual-clock advance, stale-timer check
``host_pre``    pre-dispatch bookkeeping: CheckQuorum, admission delay
                observation, staged-config drive, batch clamp, repair
                floor attest and the cached last/match fetches
``pack``        ingest batching: entry bytes -> the folded device batch
                (``_pack_entries`` / ``fold_batch`` / EC encode)
``dispatch``    the transport ``replicate`` call itself — on an async
                backend this returns after launch, not completion
``device_wait`` explicit ``jax.block_until_ready`` on the step's
                outputs — device execution + queue time not already
                hidden under dispatch
``host_post``   post-step bookkeeping: truncation notes, seq->index
                mapping, commit/apply/archive, read confirmation, span
                hooks, heartbeat re-arm, mirror digest
==============  ========================================================

The phases are *boundary-marked* — each ``mark(phase)`` attributes the
time since the previous boundary — so they tile the tick interval with
no gaps by construction: their sum equals the measured tick wall time
up to the marking overhead itself. That is what lets the bench
``attribution`` leg promise host+device columns that sum to the wall
slope (docs/PERF.md).

Overhead contract (the flight-recorder contract, extended): the
profiler is pure host bookkeeping, and the ``device_wait`` sync — the
ONE deliberate device interaction — exists only behind
:meth:`HostProfiler.sync`, which no engine path calls unless a profiler
is attached. Observe-off therefore costs zero extra device syncs
(pinned by the sync-counting test, like ``test_obs_plane``'s no-fetch
pin).

Per-tick phase seconds feed the PR-5 metrics registry as the
``raft_host_phase_seconds`` histogram, labeled ``(group, phase)`` —
under ``MultiEngine`` a shared batched launch observes once per
participating group (the launch is shared; attribution is per group by
construction of the group axis).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

#: µs-to-100ms log-spaced buckets: host phases live in the 1 µs - 1 ms
#: band on a local backend and the 10-100 ms band through a dispatch
#: tunnel; the default registry buckets (0.5s+) would flatten both.
HOST_PHASE_BUCKETS = (
    1e-6, 3e-6, 1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 1e-1,
)

PHASES = (
    "heap_pop", "host_pre", "pack", "dispatch", "device_wait", "host_post",
)


class HostProfiler:
    """Boundary-marking per-tick phase accumulator (see module doc).

    Attach with ``engine.hostprof = HostProfiler(registry=engine.metrics)``
    (registry optional — totals work standalone). The engine calls
    ``tick_begin`` / ``mark`` / ``sync`` / ``tick_end`` only when a
    profiler is attached; detached costs one ``is None`` check per site.
    """

    def __init__(self, registry=None, buckets=HOST_PHASE_BUCKETS):
        self.registry = registry
        self._hist = (
            registry.histogram(
                "raft_host_phase_seconds",
                "host wall seconds per engine tick by phase",
                ("group", "phase"), buckets=buckets,
            )
            if registry is not None else None
        )
        self.ticks = 0
        self.phase_s: Dict[str, float] = {}
        self.phase_marks: Dict[str, int] = {}
        self._cur: Dict[str, float] = {}
        self._last: Optional[float] = None

    # ----------------------------------------------------------- marking
    def tick_begin(self) -> None:
        self._cur = {}
        self._last = time.perf_counter()

    def mark(self, phase: str) -> None:
        """Attribute the time since the previous boundary to ``phase``.
        Marking the same phase twice in one tick accumulates (the engine
        marks ``host_pre`` both before and after the pack). Outside an
        open ``tick_begin``/``tick_end`` bracket this is a no-op: call
        paths that reach the marked engine internals directly (e.g. a
        ``read_index`` driving ``_replicate_round`` without a tick)
        must neither leak partial samples into the next tick nor inflate
        the mark counters with samples no tick_end will ever flush."""
        if self._last is None:
            return
        now = time.perf_counter()
        self._cur[phase] = self._cur.get(phase, 0.0) + (now - self._last)
        self.phase_marks[phase] = self.phase_marks.get(phase, 0) + 1
        self._last = now

    def sync(self, *values) -> None:
        """Block until the step's device outputs are ready and attribute
        the wait to ``device_wait`` — the ONE profiler operation that
        touches the device, deliberately absent from every detached
        engine path (the observe-off zero-extra-syncs contract). Like
        :meth:`mark`, a no-op outside an open tick bracket (an
        unattributable block would be pure added latency)."""
        if self._last is None:
            return
        import jax

        jax.block_until_ready(values)
        self.mark("device_wait")

    def tick_end(self, groups: Sequence[str] = ("0",)) -> None:
        """Close the tick: the residue since the last boundary is
        ``host_post`` (post-step bookkeeping runs from the final
        explicit mark to here), then the per-tick phase seconds flush
        into the totals and — when a registry is attached — into the
        ``raft_host_phase_seconds`` histogram once per group label."""
        self.mark("host_post")
        self.ticks += 1
        for phase, s in self._cur.items():
            self.phase_s[phase] = self.phase_s.get(phase, 0.0) + s
            if self._hist is not None:
                for g in groups:
                    self._hist.observe(s, group=str(g), phase=phase)
        self._cur = {}
        self._last = None

    # ----------------------------------------------------------- results
    def totals(self) -> Dict[str, float]:
        """phase -> accumulated seconds over all ticks."""
        return dict(self.phase_s)

    def us_per_tick(self) -> Dict[str, float]:
        """phase -> mean µs per tick (0 ticks -> empty)."""
        if not self.ticks:
            return {}
        return {
            p: s / self.ticks * 1e6 for p, s in sorted(self.phase_s.items())
        }

    def split(self) -> Tuple[float, float]:
        """(host_us_per_tick, device_us_per_tick): ``device_wait`` is
        the device column, every other phase is host control plane."""
        per = self.us_per_tick()
        dev = per.get("device_wait", 0.0)
        return sum(per.values()) - dev, dev
