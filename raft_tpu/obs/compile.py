"""The compile plane: XLA trace/compile accounting and the retrace
sentinel.

Every perf claim since the K-tick fusion leans on process-cached
compiled programs ("chaos crash-restore transports never recompile",
"one launch per round at G=1024") — yet nothing MEASURED compiles. A
single silent shape-polymorphic retrace on the fused hot path would
invalidate the headline numbers without any signal firing. This module
closes that hole:

- :class:`CompileWatch` subscribes to ``jax.monitoring``'s compile
  events (``/jax/core/compile/jaxpr_trace_duration`` /
  ``jaxpr_to_mlir_module_duration`` / ``backend_compile_duration`` and
  the ``/jax/compilation_cache/*`` hit/miss events) and records every
  trace/lower/compile as a typed :class:`CompileRecord` — program
  label, arg shapes/dtypes, elapsed, cache hit/miss — plus
  ``raft_compiles_total{program}`` / ``raft_retraces_total{program}``
  counters and flight-recorder events.
- **Program attribution** rides a wrapper at the transport
  program-cache seams (:func:`labeled`): ``jax.monitoring`` in this
  jaxlib passes no function name with the event, so the seams that
  build/cache the hot-path programs wrap the jitted callable; the
  wrapper publishes its label (and the call's args, for lazy shape
  capture) in a thread-local for the duration of the call, which is
  exactly when tracing fires. Detached cost is ONE module-list
  truthiness test per launch — no device traffic, no syncs, and the
  launched program is the same object either way (chaos seeds replay
  byte-identical plane-on vs plane-off; pinned).
- :class:`RetraceSentinel` turns any post-``freeze()`` trace/compile on
  a registered hot path into a typed :class:`CompileViolation` (event
  kind ``compile_violation``), exposed to tests as the
  :meth:`RetraceSentinel.assert_no_recompiles` context manager.

Env knobs (the ``RAFT_TPU_FUSE_K`` pattern — read where the plane is
armed, so harnesses opt in without config edits):

- ``RAFT_TPU_COMPILE_SENTINEL=1`` — chaos runners arm the compile plane
  (watch + sentinel + memory census) as if ``--observe-compile`` was
  passed; the sentinel freezes after the warmup phase.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

#: jax.monitoring event name -> the short phase tag a CompileRecord
#: carries. "trace" is the retrace signal (it fires whenever jit sees a
#: novel (shapes, dtypes) signature); "compile" is the XLA backend
#: compile that usually follows.
_EVENT_TAGS = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "compile",
}
_CACHE_TAGS = {
    "/jax/compilation_cache/cache_hits": "cache_hit",
    "/jax/compilation_cache/cache_misses": "cache_miss",
}

#: The registered hot paths: program labels whose post-freeze
#: trace/compile is a CompileViolation. These are the steady-state
#: programs the perf claims lean on — the fused K-tick scans, the
#: per-tick vote/replicate programs, and the staging-slot writer.
DEFAULT_HOT_PATHS = (
    "single.fused",
    "single.replicate",
    "single.replicate_many",
    "single.vote",
    "single.stage",
    "group.replicate",
    "group.vote",
    "group.fused",
    "group_mesh.replicate",
    "group_mesh.vote",
    "group_mesh.fused",
    "tpu_mesh.replicate",
    "tpu_mesh.replicate_many",
    "tpu_mesh.vote",
    "tpu_mesh.fused",
)

UNLABELED = "(unlabeled)"

# ---------------------------------------------------------------- plumbing
#: active watches. The hot-path contract hangs on this list: labeled()
#: wrappers test its truthiness and fall straight through to the jitted
#: callable when no watch is installed.
_WATCHES: List["CompileWatch"] = []
_TLS = threading.local()
_LISTENING = False


def _ensure_listener() -> None:
    """Register the ONE process-wide jax.monitoring listener pair
    (jax.monitoring has no unregister API in this jaxlib — so the
    listener is permanent and dispatches to whatever watches are
    installed right now; with none installed it is two dead branches)."""
    global _LISTENING
    if _LISTENING:
        return
    from jax import monitoring

    monitoring.register_event_duration_secs_listener(_on_duration)
    monitoring.register_event_listener(_on_event)
    _LISTENING = True


def _on_duration(event: str, duration: float, **kw: Any) -> None:
    if not _WATCHES:
        return
    tag = _EVENT_TAGS.get(event)
    if tag is None:
        return
    label = getattr(_TLS, "label", None) or UNLABELED
    shapes = None
    args = getattr(_TLS, "args", None)
    if args is not None:
        shapes = _arg_shapes(args)
    for w in list(_WATCHES):
        w._record(tag, label, duration, shapes)


def _on_event(event: str, **kw: Any) -> None:
    if not _WATCHES:
        return
    tag = _CACHE_TAGS.get(event)
    if tag is None:
        return
    label = getattr(_TLS, "label", None) or UNLABELED
    for w in list(_WATCHES):
        w._record(tag, label, 0.0, None)


def _arg_shapes(args: tuple) -> List[str]:
    """Compact ``dtype[shape]`` rendering of a call's array args —
    computed LAZILY (only when a trace event actually fired during the
    call, never on the cached-program fast path)."""
    out: List[str] = []
    for a in args:
        shp = getattr(a, "shape", None)
        dt = getattr(a, "dtype", None)
        if shp is not None and dt is not None:
            out.append(f"{dt}[{','.join(map(str, shp))}]")
        elif isinstance(a, (int, float, bool)):
            out.append(type(a).__name__)
        else:
            # pytrees (the state operand): summarize leaf count
            try:
                import jax

                leaves = jax.tree.leaves(a)
                out.append(f"pytree({len(leaves)} leaves)")
            except Exception:
                out.append(type(a).__name__)
    return out[:16]


def active() -> bool:
    """True when at least one CompileWatch is installed."""
    return bool(_WATCHES)


def labeled(label: str, fn):
    """Wrap a jitted program built at a program-cache seam. The wrapper
    is the attribution fallback the module docstring describes: while a
    watch is installed, each call publishes ``label`` (and the args, for
    lazy shape capture) in a thread-local around the underlying call and
    counts the launch; with no watch installed the call falls straight
    through. Wrap at cache-STORE time so the wrapper is as process-wide
    as the program it wraps."""

    def call(*args, **kw):
        if not _WATCHES:
            return fn(*args, **kw)
        prev_label = getattr(_TLS, "label", None)
        prev_args = getattr(_TLS, "args", None)
        _TLS.label = label
        _TLS.args = args
        try:
            for w in _WATCHES:
                w._note_launch(label)
            return fn(*args, **kw)
        finally:
            _TLS.label = prev_label
            _TLS.args = prev_args

    call.program_label = label
    call.__wrapped__ = fn
    return call


@contextlib.contextmanager
def program_scope(label: str):
    """Attribute any compile fired inside the block to ``label`` —
    the context-manager face of :func:`labeled` for one-off call
    sites (bench bodies, tests)."""
    prev = getattr(_TLS, "label", None)
    _TLS.label = label
    try:
        yield
    finally:
        _TLS.label = prev


# ----------------------------------------------------------------- records
@dataclasses.dataclass(frozen=True)
class CompileRecord:
    """One XLA-layer event: a jaxpr trace, an MLIR lowering, a backend
    compile, or a persistent-cache hit/miss."""

    seq: int
    t_wall: float                  # seconds since the watch installed
    program: str                   # label from the wrapper seam
    event: str                     # trace | lower | compile | cache_*
    elapsed_s: float
    arg_shapes: Optional[List[str]] = None
    frozen: bool = False           # fired after the sentinel froze

    def to_jsonable(self) -> dict:
        d = dataclasses.asdict(self)
        if d["arg_shapes"] is None:
            del d["arg_shapes"]
        return d


@dataclasses.dataclass(frozen=True)
class CompileViolation:
    """A post-freeze trace/compile on a registered hot path."""

    seq: int
    t_wall: float
    program: str
    event: str
    elapsed_s: float
    arg_shapes: Optional[List[str]] = None

    def __str__(self) -> str:
        shapes = (
            f" args=({', '.join(self.arg_shapes)})" if self.arg_shapes
            else ""
        )
        return (
            f"post-freeze {self.event} on hot path {self.program!r} "
            f"({self.elapsed_s * 1e3:.1f} ms{shapes})"
        )


class RecompileError(AssertionError):
    """Raised by ``assert_no_recompiles`` when the sentinel tripped."""


# ------------------------------------------------------------------- watch
class CompileWatch:
    """Typed flight recorder for the XLA layer (module docstring).

    ``install()``/``uninstall()`` bound the watch's active window; the
    class is also a context manager. All bookkeeping is pure host-side
    arithmetic on the calling thread — no rng, no device traffic — so
    seeded runs replay byte-identically watch-on vs watch-off."""

    def __init__(self, recorder=None, registry=None,
                 capacity: int = 4096) -> None:
        self.recorder = recorder
        self.registry = registry
        self.capacity = capacity
        self.log: deque = deque(maxlen=capacity)
        self.dropped = 0
        self._next_seq = 0
        self._t0 = time.monotonic()
        self.sentinel: Optional["RetraceSentinel"] = None
        # per-program tallies
        self.traces: Dict[str, int] = {}
        self.compiles: Dict[str, int] = {}
        self.compile_s: Dict[str, float] = {}
        self.launches: Dict[str, int] = {}
        self.cache_hits = 0
        self.cache_misses = 0

    # --------------------------------------------------------- lifecycle
    def install(self) -> "CompileWatch":
        _ensure_listener()
        if self not in _WATCHES:
            self._t0 = time.monotonic()
            _WATCHES.append(self)
        return self

    def uninstall(self) -> None:
        if self in _WATCHES:
            _WATCHES.remove(self)

    @property
    def installed(self) -> bool:
        return self in _WATCHES

    def __enter__(self) -> "CompileWatch":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ----------------------------------------------------------- recording
    def _note_launch(self, label: str) -> None:
        self.launches[label] = self.launches.get(label, 0) + 1

    def _record(self, tag: str, label: str, elapsed: float,
                shapes: Optional[List[str]]) -> None:
        frozen = self.sentinel is not None and self.sentinel.frozen
        rec = CompileRecord(
            seq=self._next_seq, t_wall=time.monotonic() - self._t0,
            program=label, event=tag, elapsed_s=elapsed,
            arg_shapes=shapes, frozen=frozen,
        )
        self._next_seq += 1
        if len(self.log) == self.capacity:
            self.dropped += 1
        self.log.append(rec)
        if tag == "trace":
            self.traces[label] = self.traces.get(label, 0) + 1
        elif tag == "compile":
            self.compiles[label] = self.compiles.get(label, 0) + 1
            self.compile_s[label] = (
                self.compile_s.get(label, 0.0) + elapsed
            )
        elif tag == "cache_hit":
            self.cache_hits += 1
        elif tag == "cache_miss":
            self.cache_misses += 1
        if self.registry is not None and tag in ("trace", "compile"):
            name = ("raft_retraces_total" if tag == "trace"
                    else "raft_compiles_total")
            self.registry.counter(
                name, "XLA-layer events by program label", ("program",),
            ).inc(program=label)
        if self.recorder is not None and tag in ("trace", "compile"):
            self.recorder.record(
                node="xla", term=0, kind="compile", t_virtual=rec.t_wall,
                program=label, event=tag,
                elapsed_s=round(elapsed, 6), frozen=frozen,
                **({"arg_shapes": shapes} if shapes else {}),
            )
        if self.sentinel is not None:
            self.sentinel._observe(rec)

    # ------------------------------------------------------------ queries
    @property
    def total_traces(self) -> int:
        return sum(self.traces.values())

    @property
    def total_compiles(self) -> int:
        return sum(self.compiles.values())

    @property
    def total_compile_s(self) -> float:
        return sum(self.compile_s.values())

    def events(self, program: Optional[str] = None,
               event: Optional[str] = None) -> List[CompileRecord]:
        out = list(self.log)
        if program is not None:
            out = [r for r in out if r.program == program]
        if event is not None:
            out = [r for r in out if r.event == event]
        return out

    def by_program(self) -> Dict[str, dict]:
        progs = (set(self.traces) | set(self.compiles)
                 | set(self.launches))
        return {
            p: {
                "launches": self.launches.get(p, 0),
                "traces": self.traces.get(p, 0),
                "compiles": self.compiles.get(p, 0),
                "compile_s": round(self.compile_s.get(p, 0.0), 6),
            }
            for p in sorted(progs)
        }

    def snapshot(self) -> dict:
        """The /compile body and the forensics-bundle entry."""
        return {
            "programs": self.by_program(),
            "total_traces": self.total_traces,
            "total_compiles": self.total_compiles,
            "total_compile_s": round(self.total_compile_s, 6),
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "dropped": self.dropped,
            "log": [r.to_jsonable() for r in self.log],
            "sentinel": (
                self.sentinel.summary() if self.sentinel is not None
                else None
            ),
        }

    def summary(self) -> dict:
        """The light /status section (no event log)."""
        return {
            "total_traces": self.total_traces,
            "total_compiles": self.total_compiles,
            "total_compile_s": round(self.total_compile_s, 6),
            "violations": (
                len(self.sentinel.violations)
                if self.sentinel is not None else None
            ),
            "frozen": (
                self.sentinel.frozen if self.sentinel is not None
                else None
            ),
        }


# ---------------------------------------------------------------- sentinel
class RetraceSentinel:
    """Freeze-semantics guard over a :class:`CompileWatch`.

    Before ``freeze()`` every compile is warmup and merely recorded.
    After it, any trace/compile whose program label is a registered hot
    path becomes a :class:`CompileViolation` — recorded as an event
    (kind ``compile_violation``), counted in
    ``raft_compile_violations_total``, and surfaced by
    :meth:`assert_no_recompiles`."""

    def __init__(self, watch: CompileWatch,
                 hot_paths: Tuple[str, ...] = DEFAULT_HOT_PATHS) -> None:
        self.watch = watch
        self.hot_paths = set(hot_paths)
        self.frozen = False
        self.violations: List[CompileViolation] = []
        watch.sentinel = self

    def register_hot_path(self, label: str) -> None:
        self.hot_paths.add(label)

    def freeze(self) -> None:
        """End of warmup: from here every hot-path compile violates."""
        self.frozen = True

    def thaw(self) -> None:
        """Re-open a warmup window (an intentional reshape — a new
        cluster shape, a first recorded-variant launch)."""
        self.frozen = False

    def _observe(self, rec: CompileRecord) -> None:
        if not self.frozen or rec.event not in ("trace", "compile"):
            return
        if rec.program not in self.hot_paths:
            return
        v = CompileViolation(
            seq=rec.seq, t_wall=rec.t_wall, program=rec.program,
            event=rec.event, elapsed_s=rec.elapsed_s,
            arg_shapes=rec.arg_shapes,
        )
        self.violations.append(v)
        w = self.watch
        if w.registry is not None:
            w.registry.counter(
                "raft_compile_violations_total",
                "post-freeze compiles on registered hot paths",
                ("program",),
            ).inc(program=rec.program)
        if w.recorder is not None:
            w.recorder.record(
                node="xla", term=0, kind="compile_violation",
                t_virtual=rec.t_wall, program=rec.program,
                event=rec.event, elapsed_s=round(rec.elapsed_s, 6),
                **({"arg_shapes": rec.arg_shapes}
                   if rec.arg_shapes else {}),
            )

    def summary(self) -> dict:
        return {
            "frozen": self.frozen,
            "hot_paths": sorted(self.hot_paths),
            "violations": [dataclasses.asdict(v) for v in self.violations],
        }

    @contextlib.contextmanager
    def assert_no_recompiles(self, thaw_after: bool = False):
        """Tier-1 teeth: freeze (if not already frozen), run the block,
        raise :class:`RecompileError` naming every hot-path compile the
        block incurred. Violations from before the block don't count
        against it; they stay recorded."""
        was_frozen = self.frozen
        self.freeze()
        mark = len(self.violations)
        try:
            yield self
        finally:
            if thaw_after and not was_frozen:
                self.frozen = False
        new = self.violations[mark:]
        if new:
            raise RecompileError(
                f"{len(new)} hot-path recompile(s) inside "
                f"assert_no_recompiles():\n  "
                + "\n  ".join(str(v) for v in new)
            )


@contextlib.contextmanager
def assert_no_recompiles(hot_paths: Tuple[str, ...] = DEFAULT_HOT_PATHS):
    """Module-level convenience: install a fresh frozen watch+sentinel
    for the block — ``with obs_compile.assert_no_recompiles(): drive()``
    is the whole steady-state pin."""
    watch = CompileWatch()
    sentinel = RetraceSentinel(watch, hot_paths=hot_paths)
    with watch:
        with sentinel.assert_no_recompiles():
            yield sentinel
