"""SLO plane: streaming latency digests and multi-window burn-rate alerts.

The PR-5 registry histograms answer "what happened" at scrape time; an
operator also needs "are we inside our objective, and how fast are we
burning the error budget" — evaluated ONLINE, on the same virtual clock
the engine runs on, with no extra device traffic.

Two pieces:

- :class:`LatencyDigest` — a fixed-layout log-scale bucket digest. All
  digests share one bucket layout (geometric, factor ``2**0.25`` from
  1 µs to 1e5 s), so digests MERGE by adding count vectors — per-group
  digests roll up into a fleet view without resampling. Quantiles carry
  a bounded relative error: a reported quantile is the geometric
  midpoint of its bucket, so it is within one bucket factor (~19%) of
  the true value (pinned by tests/test_slo.py).
- :class:`SloTracker` — per-(objective, group) good/total counts in
  coarse time buckets on the virtual clock, evaluated as multi-window
  burn rates (the SRE-workbook shape: alert only when BOTH a long and a
  short window burn the error budget faster than a threshold — the long
  window proves significance, the short window proves it is still
  happening). Alerts are typed :class:`SloAlert` events, recorded into
  the PR-5 flight recorder (kind ``slo_alert``) and counted as
  ``raft_slo_alerts_total{slo,severity}`` when a registry is attached.

Determinism contract: pure host arithmetic on values the engine already
computed (commit/read latencies, queue delays) — no rng, no device
fetches; a seeded run replays byte-identically with the tracker
attached or absent (pinned by tests/test_audit.py's fingerprint pins).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

import numpy as np

# One shared bucket layout so any two digests merge: geometric buckets
# factor 2**0.25 (~+19% per bucket) spanning 1 µs .. 1e5 s. Values
# outside clamp into the terminal buckets.
_FACTOR = 2.0 ** 0.25
_LO = 1e-6
_N_BUCKETS = int(math.ceil(math.log(1e5 / _LO, _FACTOR))) + 2


def _bucket_of(v: float) -> int:
    if not (v > _LO):                     # NaN and <= LO land in bucket 0
        return 0
    i = int(math.log(v / _LO, _FACTOR)) + 1
    return min(i, _N_BUCKETS - 1)


def _bucket_mid(i: int) -> float:
    """Geometric midpoint of bucket ``i`` — the quantile estimate whose
    relative error is bounded by the bucket factor."""
    if i <= 0:
        return _LO
    lo = _LO * _FACTOR ** (i - 1)
    return lo * math.sqrt(_FACTOR)


class LatencyDigest:
    """Streaming log-bucket latency digest (module docstring). Fixed
    layout: every instance merges with every other. ``observe_many``
    is the numpy-vectorized bulk path the engine's batched commit
    booking uses (one call per tick/launch, not per entry)."""

    __slots__ = ("counts", "n", "total", "max")

    def __init__(self) -> None:
        self.counts = np.zeros(_N_BUCKETS, np.int64)
        self.n = 0
        self.total = 0.0
        self.max = 0.0

    def observe(self, v: float) -> None:
        self.counts[_bucket_of(v)] += 1
        self.n += 1
        self.total += v
        if v > self.max:
            self.max = v

    def observe_many(self, values: np.ndarray) -> None:
        """Bulk observe: same bucketing formula as ``observe``,
        vectorized (log + bincount)."""
        v = np.asarray(values, np.float64)
        if v.size == 0:
            return
        idx = np.zeros(v.shape, np.int64)
        pos = v > _LO
        idx[pos] = (
            np.log(v[pos] / _LO) / math.log(_FACTOR)
        ).astype(np.int64) + 1
        np.clip(idx, 0, _N_BUCKETS - 1, out=idx)
        self.counts += np.bincount(idx, minlength=_N_BUCKETS)
        self.n += int(v.size)
        self.total += float(v.sum())
        self.max = max(self.max, float(v.max()))

    def merge(self, other: "LatencyDigest") -> "LatencyDigest":
        """Fold ``other`` into self (shared layout: vector add)."""
        self.counts += other.counts
        self.n += other.n
        self.total += other.total
        self.max = max(self.max, other.max)
        return self

    def quantile(self, q: float) -> float:
        """The q-quantile estimate (NaN on an empty digest); within one
        bucket factor of the true sample quantile by construction."""
        if self.n == 0:
            return float("nan")
        rank = max(1, math.ceil(q * self.n))
        i = int(np.searchsorted(np.cumsum(self.counts), rank))
        return _bucket_mid(min(i, _N_BUCKETS - 1))

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else float("nan")

    def to_jsonable(self) -> dict:
        return {
            "n": self.n,
            "mean": self.mean if self.n else None,
            "max": self.max if self.n else None,
            "p50": self.quantile(0.5) if self.n else None,
            "p90": self.quantile(0.9) if self.n else None,
            "p99": self.quantile(0.99) if self.n else None,
            "p999": self.quantile(0.999) if self.n else None,
        }


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One service-level objective: ``target`` fraction of ``metric``
    events must complete under ``threshold_s`` (virtual seconds). The
    error budget is ``1 - target``; burn rate 1.0 = spending the budget
    exactly at the sustainable rate."""

    name: str                 # e.g. "commit_p99"
    metric: str               # "commit" | "read" | "queue_delay"
    threshold_s: float        # good iff value <= threshold
    target: float = 0.999     # objective: fraction of good events

    @property
    def budget(self) -> float:
        return max(1.0 - self.target, 1e-9)


@dataclasses.dataclass(frozen=True)
class SloAlert:
    """Typed burn-rate alert (fired when BOTH windows exceed the
    threshold; cleared when the short window recovers)."""

    slo: str
    group: Optional[int]
    severity: str             # "page" | "ticket"
    burn_rate: float          # the short window's burn rate at firing
    long_s: float
    short_s: float
    t_virtual: float
    kind: str = "fire"        # "fire" | "clear"


#: (long window s, short window s, burn-rate threshold, severity) — the
#: SRE-workbook defaults scaled to the virtual clock. Overridable per
#: tracker; tests use short synthetic windows.
DEFAULT_WINDOWS: Tuple[Tuple[float, float, float, str], ...] = (
    (3600.0, 300.0, 14.4, "page"),
    (21600.0, 1800.0, 6.0, "ticket"),
)


class SloTracker:
    """Per-(objective, group) SLO accounting + digests (module
    docstring). ``observe`` is the hot-path entry (guarded by the engine
    behind ``self.slo is not None``); ``maybe_evaluate`` runs the burn
    computation at most once per ``bucket_s`` of virtual time."""

    def __init__(
        self,
        objectives: Tuple[SLObjective, ...] = (),
        recorder=None,
        registry=None,
        bucket_s: float = 60.0,
        windows: Tuple[Tuple[float, float, float, str], ...] = DEFAULT_WINDOWS,
    ) -> None:
        self.objectives = tuple(objectives)
        self.recorder = recorder
        self.registry = registry
        self.bucket_s = float(bucket_s)
        self.windows = tuple(windows)
        self._span = max((w[0] for w in self.windows), default=0.0)
        self._by_metric: Dict[str, List[SLObjective]] = {}
        for o in self.objectives:
            self._by_metric.setdefault(o.metric, []).append(o)
        self.digests: Dict[Tuple[str, Optional[int]], LatencyDigest] = {}
        #   (metric, group) -> digest; group None = single engine
        self._buckets: Dict[Tuple[str, Optional[int]], Dict[int, list]] = {}
        #   (slo name, group) -> {bucket index -> [good, total]}
        self._active: Dict[Tuple[str, Optional[int], str], SloAlert] = {}
        self.alerts: List[SloAlert] = []
        self.alerts_dropped = 0
        self._last_eval = float("-inf")
        self.ALERT_CAP = 1024

    # ------------------------------------------------------------- feed
    def observe(self, metric: str, v: float,
                t: float, group: Optional[int] = None) -> None:
        dig = self.digests.get((metric, group))
        if dig is None:
            dig = self.digests[(metric, group)] = LatencyDigest()
        dig.observe(v)
        for o in self._by_metric.get(metric, ()):
            key = (o.name, group)
            buckets = self._buckets.get(key)
            if buckets is None:
                buckets = self._buckets[key] = {}
            bi = int(t // self.bucket_s)
            cell = buckets.get(bi)
            if cell is None:
                cell = buckets[bi] = [0, 0]
                # retention: drop buckets older than the longest window
                floor = bi - int(self._span // self.bucket_s) - 2
                for old in [b for b in buckets if b < floor]:
                    del buckets[old]
            cell[1] += 1
            if v <= o.threshold_s:
                cell[0] += 1

    def observe_batch(self, metric: str, values, t: float,
                      group: Optional[int] = None) -> None:
        """Bulk observe for batched commit booking: one digest update
        (vectorized) + one window-bucket update per call, instead of a
        Python call per entry — the hot-path shape that keeps the
        online plane inside its <= 5% overhead contract at the
        headline batch size (bench.py ``attribution.online_plane``)."""
        v = np.asarray(values, np.float64)
        if v.size == 0:
            return
        dig = self.digests.get((metric, group))
        if dig is None:
            dig = self.digests[(metric, group)] = LatencyDigest()
        dig.observe_many(v)
        for o in self._by_metric.get(metric, ()):
            key = (o.name, group)
            buckets = self._buckets.get(key)
            if buckets is None:
                buckets = self._buckets[key] = {}
            bi = int(t // self.bucket_s)
            cell = buckets.get(bi)
            if cell is None:
                cell = buckets[bi] = [0, 0]
                floor = bi - int(self._span // self.bucket_s) - 2
                for old in [b for b in buckets if b < floor]:
                    del buckets[old]
            cell[1] += int(v.size)
            cell[0] += int((v <= o.threshold_s).sum())

    # ------------------------------------------------------- evaluation
    def maybe_evaluate(self, t: float) -> None:
        if t - self._last_eval >= self.bucket_s:
            self.evaluate(t)

    def _burn(self, o: SLObjective, buckets: Dict[int, list],
              t: float, window_s: float) -> Optional[float]:
        """Burn rate over [t - window_s, t]: bad fraction / budget.
        None when the window holds no events (no evidence either way)."""
        lo = int((t - window_s) // self.bucket_s)
        good = total = 0
        for bi, (g, n) in buckets.items():
            if bi >= lo:
                good += g
                total += n
        if total == 0:
            return None
        return ((total - good) / total) / o.budget

    def evaluate(self, t: float) -> None:
        """Multi-window burn-rate pass: fire a typed alert when BOTH the
        long and the short window of a severity tier exceed its burn
        threshold; clear it when the short window recovers."""
        self._last_eval = t
        for o in self.objectives:
            for (name, group), buckets in self._buckets.items():
                if name != o.name:
                    continue
                for long_s, short_s, thresh, severity in self.windows:
                    key = (o.name, group, severity)
                    b_long = self._burn(o, buckets, t, long_s)
                    b_short = self._burn(o, buckets, t, short_s)
                    firing = (
                        b_long is not None and b_short is not None
                        and b_long > thresh and b_short > thresh
                    )
                    if firing and key not in self._active:
                        alert = SloAlert(
                            slo=o.name, group=group, severity=severity,
                            burn_rate=round(b_short, 3), long_s=long_s,
                            short_s=short_s, t_virtual=t,
                        )
                        self._active[key] = alert
                        self._emit(alert)
                    elif key in self._active and (
                        b_short is None or b_short <= thresh
                    ):
                        fired = self._active.pop(key)
                        self._emit(dataclasses.replace(
                            fired, kind="clear", t_virtual=t,
                            burn_rate=round(b_short or 0.0, 3),
                        ))

    def _emit(self, alert: SloAlert) -> None:
        if len(self.alerts) >= self.ALERT_CAP:
            self.alerts_dropped += 1
        else:
            self.alerts.append(alert)
        if self.recorder is not None:
            self.recorder.record(
                node=f"slo/{alert.slo}", term=0, kind="slo_alert",
                t_virtual=alert.t_virtual, group=alert.group,
                severity=alert.severity, burn_rate=alert.burn_rate,
                long_s=alert.long_s, short_s=alert.short_s,
                alert_kind=alert.kind,
            )
        if self.registry is not None and alert.kind == "fire":
            self.registry.counter(
                "raft_slo_alerts_total", "burn-rate alerts fired",
                ("slo", "severity"),
            ).inc(slo=alert.slo, severity=alert.severity)

    # --------------------------------------------------------- snapshot
    def active_alerts(self) -> List[SloAlert]:
        return list(self._active.values())

    def snapshot(self) -> dict:
        """JSON-safe state for ``/slo`` and forensics bundles."""
        def gkey(g):
            return "default" if g is None else str(g)

        digests = {}
        for (metric, group), dig in sorted(
            self.digests.items(), key=lambda kv: (kv[0][0], str(kv[0][1]))
        ):
            digests.setdefault(metric, {})[gkey(group)] = dig.to_jsonable()
        slos = []
        # a scrape before the first evaluation must not feed -inf into
        # the bucket-index arithmetic (OverflowError); burn rates read
        # as of the last evaluation, 0.0 when none has happened yet
        t_eval = (self._last_eval
                  if math.isfinite(self._last_eval) else 0.0)
        for o in self.objectives:
            groups = {}
            for (name, group), buckets in self._buckets.items():
                if name != o.name:
                    continue
                good = sum(g for g, _ in buckets.values())
                total = sum(n for _, n in buckets.values())
                burns = {}
                for long_s, short_s, thresh, severity in self.windows:
                    burns[severity] = {
                        "long_s": long_s, "short_s": short_s,
                        "threshold": thresh,
                        "burn_long": self._burn(o, buckets,
                                                t_eval, long_s),
                        "burn_short": self._burn(o, buckets,
                                                 t_eval, short_s),
                    }
                groups[gkey(group)] = {
                    "good": good, "total": total,
                    "good_fraction": (good / total) if total else None,
                    "burn": burns,
                }
            slos.append({
                "name": o.name, "metric": o.metric,
                "threshold_s": o.threshold_s, "target": o.target,
                "groups": groups,
            })
        return {
            "objectives": slos,
            "digests": digests,
            "alerts_active": [dataclasses.asdict(a)
                              for a in self._active.values()],
            "alerts_total": len(self.alerts) + self.alerts_dropped,
            "alerts": [dataclasses.asdict(a) for a in self.alerts[-32:]],
        }
