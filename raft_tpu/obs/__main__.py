"""Observability CLI: ``python -m raft_tpu.obs``.

Post-mortem tooling over repro bundles (``obs.forensics``) and black-box
artifacts (``obs.blackbox``) — nothing here re-runs a seed:

- ``--explain PATH [PATH ...]``  — reconstruct the failure story from
  whatever PATH is: a repro bundle (minimal failure timeline: last
  leader per term, faults in flight, the violating op — and, when the
  run carried the device plane, the decoded device ring: kind summary,
  overflow laps flagged, device events interleaved into the timeline;
  when it carried the compile-&-memory plane, ``RETRACE:`` /
  ``CENSUS GREW:`` flags from the compile log and memory census),
  a **stall bundle** (who stalled, the blocked phase, journal tail,
  all-thread stacks), a **blackbox journal** ``.jsonl`` (per-process
  phase timeline with durations; the final in-flight phase flagged),
  or a directory of journals (one timeline per process — the multihost
  post-mortem view). MULTIPLE paths must all be repro bundles: their
  span tables are JOINED on the cross-process wire trace id into one
  causal timeline per op (client attempt → wire frame → ingest batch →
  tick → completion sweep → response — ``obs.forensics.explain_joined``;
  a single bundle carrying both a ``spans`` and a ``client_spans``
  table, as the chaos wire drill writes, gets the joined view
  appended automatically).
- ``--render-perfetto BUNDLE``  — convert the bundle's span table to
  Chrome/Perfetto trace JSON (load at ui.perfetto.dev); ``-o`` writes
  to a file, default stdout.
- ``--metrics-dump BUNDLE``     — print the bundle's metrics snapshot
  as Prometheus text exposition (``--json`` for the raw snapshot).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from raft_tpu.obs.blackbox import (
    STALL_FORMAT,
    explain_journal,
    explain_merged,
    explain_stall,
)
from raft_tpu.obs.forensics import (
    BUNDLE_FORMAT,
    explain,
    explain_joined,
    load_bundle,
)


def _render_perfetto(bundle: dict) -> dict:
    from raft_tpu.obs.spans import SpanTracker, spans_from_jsonable

    if not bundle.get("spans"):
        raise SystemExit(
            "bundle carries no span table (run with observe=True)"
        )
    tracker = SpanTracker()
    tracker.spans = spans_from_jsonable(bundle["spans"])
    return tracker.to_perfetto()


def _explain_many(paths: list) -> str:
    """--explain with 2+ paths: every artifact must be a repro bundle;
    their span tables join on the wire trace id into one causal
    timeline per op (the cross-process wire forensics view)."""
    bundles = []
    for path in paths:
        if not os.path.exists(path):
            raise SystemExit(f"{path}: no such file")
        try:
            bundles.append(load_bundle(path))
        except (ValueError, json.JSONDecodeError, OSError) as ex:
            # OSError covers e.g. a journal DIRECTORY among the paths
            # — joined mode is bundles-only, and the user deserves the
            # typed message, not a traceback
            raise SystemExit(
                f"{path}: joined --explain needs repro bundles ({ex})"
            )
    return explain_joined(bundles)


def _explain_any(path: str) -> str:
    """Dispatch --explain on what the artifact actually is: a directory
    of journals, a journal file, a stall bundle, or a repro bundle."""
    if os.path.isdir(path):
        names = sorted(os.listdir(path))
        journals = [
            os.path.join(path, f) for f in names if f.endswith(".jsonl")
        ]
        # the watchdog writes stall bundles into the SAME blackbox dir —
        # the directory post-mortem must surface them (they carry the
        # all-thread stacks), not just the journal timelines
        stalls = []
        for f in names:
            if f.startswith("stall_") and f.endswith(".json"):
                try:
                    with open(os.path.join(path, f)) as fh:
                        doc = json.load(fh)
                except (OSError, json.JSONDecodeError):
                    continue
                if doc.get("format") == STALL_FORMAT:
                    stalls.append(explain_stall(doc))
        if not journals and not stalls:
            raise SystemExit(
                f"{path}: no .jsonl journals or stall bundles in directory"
            )
        parts = [explain_journal(journals)] if journals else []
        if len(journals) > 1:
            # 2+ journals in one directory = a multi-process run: the
            # per-journal views above tell each process's story, the
            # merged wall-clock view tells THE story (a kill -9 in the
            # supervisor's journal next to the victim's last gasp)
            parts.append(explain_merged(journals))
        return "\n\n".join(parts + stalls)
    if not os.path.exists(path):
        # read_journal forgives unreadable files (it must not choke on
        # the artifact of a crash), but a CLI typo must fail loudly, not
        # exit 0 with an "empty journal" shrug
        raise SystemExit(f"{path}: no such file")
    if path.endswith(".jsonl"):
        return explain_journal([path])
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as ex:
        raise SystemExit(f"{path}: not a readable JSON artifact ({ex})")
    if doc.get("format") == STALL_FORMAT:
        return explain_stall(doc)
    if doc.get("format") != BUNDLE_FORMAT:
        raise SystemExit(
            f"{path}: not a raft_tpu artifact "
            f"(format={doc.get('format')!r})"
        )
    text = explain(doc)
    if doc.get("client_spans"):
        # one bundle carrying both sides (the wire drill): the joined
        # per-op view rides along without a second artifact
        text += "\n\n" + explain_joined([doc])
    return text


def _metrics_prometheus(snapshot: dict) -> str:
    """Re-expose a bundle's JSON metrics snapshot as Prometheus text (a
    snapshot is values, not live metric objects, so rebuild a registry)."""
    from raft_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    for name, m in snapshot.items():
        labels = tuple(m["labels"])
        if m["type"] == "counter":
            c = reg.counter(name, m["help"], labels)
            for s in m["series"]:
                c.inc(s["value"], **s["labels"])
        elif m["type"] == "gauge":
            g = reg.gauge(name, m["help"], labels)
            for s in m["series"]:
                g.set(s["value"], **s["labels"])
        elif m["type"] == "histogram":
            buckets = None
            for s in m["series"]:
                bs = [float(b) for b in s["buckets"] if b != "+Inf"]
                buckets = tuple(bs)
                break
            h = reg.histogram(
                name, m["help"], labels,
                buckets=buckets if buckets else (1.0,),
            )
            for s in m["series"]:
                h._counts[tuple(str(s["labels"][n]) for n in labels)] = \
                    list(s["buckets"].values())
                k = tuple(str(s["labels"][n]) for n in labels)
                h._sum[k] = s["sum"]
                h._n[k] = s["count"]
    return reg.to_prometheus()


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.obs",
        description="raft_tpu observability tooling (repro bundles)",
    )
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--explain", metavar="PATH", nargs="+",
                   help="reconstruct the failure timeline from a repro "
                        "bundle, a stall bundle, a blackbox journal "
                        "(.jsonl), or a directory of journals; with "
                        "2+ bundle paths, join their span tables on "
                        "the wire trace id into one causal timeline "
                        "per op (client+server forensics)")
    g.add_argument("--render-perfetto", metavar="BUNDLE",
                   help="bundle span table -> Chrome/Perfetto trace JSON")
    g.add_argument("--metrics-dump", metavar="BUNDLE",
                   help="bundle metrics snapshot -> Prometheus text")
    g.add_argument("--serve", action="store_true",
                   help="boot a demo MultiEngine with the full online "
                        "plane attached (metrics registry, SLO tracker, "
                        "safety auditor, status board, compile watch + "
                        "retrace sentinel, memory census) and serve the "
                        "ops endpoints /metrics /healthz /slo /status "
                        "/compile /memory /profile while driving "
                        "synthetic traffic (Ctrl-C to stop)")
    ap.add_argument("-o", "--output", default=None,
                    help="output file (default stdout)")
    ap.add_argument("--json", action="store_true",
                    help="with --metrics-dump: raw JSON snapshot instead "
                         "of Prometheus text")
    ap.add_argument("--port", type=int, default=8900,
                    help="with --serve: TCP port to bind (0 = ephemeral; "
                         "default 8900)")
    ap.add_argument("--serve-groups", type=int, default=4,
                    help="with --serve: number of demo Raft groups")
    ap.add_argument("--serve-duration", type=float, default=None,
                    metavar="S",
                    help="with --serve: stop after S wall seconds "
                         "(default: run until Ctrl-C)")
    args = ap.parse_args(argv)

    if args.serve:
        from raft_tpu.obs.serve import serve_demo

        result = serve_demo(
            port=args.port, groups=args.serve_groups,
            duration_s=args.serve_duration,
        )
        print(json.dumps(result))
        return 0
    if args.explain:
        text = (_explain_any(args.explain[0]) if len(args.explain) == 1
                else _explain_many(args.explain))
    elif args.render_perfetto:
        text = json.dumps(_render_perfetto(load_bundle(args.render_perfetto)))
    else:
        bundle = load_bundle(args.metrics_dump)
        snap = bundle.get("metrics")
        if not snap:
            raise SystemExit(
                "bundle carries no metrics snapshot (run with observe=True)"
            )
        text = (json.dumps(snap) if args.json
                else _metrics_prometheus(snap))

    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
    else:
        sys.stdout.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
