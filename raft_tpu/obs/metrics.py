"""Protocol metrics: the BASELINE metric set.

``BASELINE.json``'s metric is "log entries committed/sec; p50/p99 commit
latency" — computed here from the engine's per-entry submit/commit
timestamps (virtual-clock seconds for deterministic runs, wall seconds for
live ones). The reference publishes no numbers; its implied commit latency
is the 2 s replication tick (BASELINE.md)."""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from raft_tpu.admission.gate import AdmissionReport


@dataclasses.dataclass(frozen=True)
class LatencySummary:
    count: int
    p50: float
    p99: float
    mean: float
    max: float

    @classmethod
    def of(cls, samples: np.ndarray) -> "LatencySummary":
        if len(samples) == 0:
            return cls(0, float("nan"), float("nan"), float("nan"), float("nan"))
        return cls(
            count=len(samples),
            p50=float(np.percentile(samples, 50)),
            p99=float(np.percentile(samples, 99)),
            mean=float(np.mean(samples)),
            max=float(np.max(samples)),
        )


@dataclasses.dataclass(frozen=True)
class EngineReport:
    committed_entries: int
    elapsed_s: float
    entries_per_sec: float
    commit_latency: LatencySummary
    in_flight_entries: int     # ingested, commit pending (healthy pipeline)
    lost_entries: int          # submitted, never durable (leadership changes)
    leader_changes: int
    # Overload observability (None when admission is disabled): queue
    # depth + high-water, shed counts by reason, admitted counts, the
    # delay controller's state, and head-of-queue sojourn p50/p99 —
    # goodput is ``entries_per_sec`` above (committed work only; shed
    # arrivals never count).
    admission: Optional[AdmissionReport] = None
    # obs.registry.MetricsRegistry snapshot (None when no registry is
    # attached to the engine): the full labeled counter/gauge/histogram
    # dump — elections, heartbeats, repair rounds, sheds by reason,
    # commit-latency buckets (docs/OBSERVABILITY.md).
    metrics: Optional[dict] = None


def summarize_engine(engine, trace=None) -> EngineReport:
    """Metrics from a finished (or paused) engine run; ``trace`` is an
    optional TraceRecorder for leadership-change counting (the engine's
    attached ``recorder`` — structured ``elect`` events — is preferred
    when present)."""
    lat = engine.commit_latencies()
    elapsed = engine.clock.now
    # ``commit_time`` is a BOUNDED stamp window (oldest stamps evict
    # past the archive retention horizon — the host_post residue fix);
    # the all-time committed count lives in ``committed_total``, and
    # the eviction drops submit stamps pairwise so the lost-entry
    # arithmetic stays exact with ``commit_stamps_evicted`` added back.
    committed = getattr(engine, "committed_total", None)
    evicted = getattr(engine, "commit_stamps_evicted", 0)
    if committed is None:
        committed = len(engine.commit_time)
    leader_changes = 0
    recorder = getattr(engine, "recorder", None)
    if recorder is not None:
        leader_changes = len(recorder.events(kind="elect"))
    elif trace is not None:
        leader_changes = len(trace.matching("state changed to leader"))
    in_flight = engine.in_flight_count
    return EngineReport(
        committed_entries=committed,
        elapsed_s=elapsed,
        entries_per_sec=committed / elapsed if elapsed > 0 else float("nan"),
        commit_latency=LatencySummary.of(lat),
        in_flight_entries=in_flight,
        lost_entries=(
            len(engine.submit_time) + evicted - committed
            - len(engine._queue) - in_flight
        ),
        leader_changes=leader_changes,
        admission=(
            engine.admission.report(queue_depth=len(engine._queue))
            if getattr(engine, "admission", None) is not None else None
        ),
        metrics=(
            engine.metrics.snapshot()
            if getattr(engine, "metrics", None) is not None else None
        ),
    )
