"""The flight recorder: a typed, bounded ring of structured events.

Until this module, the whole stack was observed through ONE channel: the
reference's nodelog strings (main.go:399-401), asserted on by substring
grep (``TraceRecorder.matching("state changed to leader")``). The flight
recorder keeps that string as a *rendering* — ``Event.nodelog()`` is
byte-identical to the legacy line, because the line format is the
differential-test join key with the golden model and must not drift —
but the record itself is typed: ``Event(seq, t_virtual, node, group,
term, kind, fields)``, queryable without string surgery.

Ring semantics: the recorder holds the most recent ``capacity`` events.
``seq`` keeps rising monotonically past overflow and ``dropped`` counts
evictions, so a consumer can always tell "quiet run" from "ring wrapped
and the head is gone" (the forensics bundle records both).

Determinism contract: recording is pure host-side bookkeeping — no rng,
no device traffic — so any seeded run replays byte-identically with the
recorder attached or absent. The *emitters* honor the other half: with
no recorder and no trace callback attached, ``RaftEngine.nodelog`` skips
its device fetch entirely (the disabled path costs no device syncs).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

#: Ordered (substring-prefix, kind) catalog for classifying legacy
#: nodelog messages into event kinds. First match wins; call sites may
#: always pass an explicit ``kind`` instead. Kept here — not in the
#: engine — so the golden model and multi engine classify identically.
_KIND_CATALOG = (
    ("state changed to leader", "elect"),
    ("state changed to candidate", "candidate"),
    ("step down to follower", "step_down"),
    ("commit index changed to", "commit"),
    ("configuration committed at", "config_commit"),
    ("promoted from learner to voter", "promote"),
    ("added to configuration as learner", "learner_add"),
    ("added to configuration", "config_add"),
    ("removed from configuration", "config_remove"),
    ("learner removed from configuration", "learner_remove"),
    ("admission shedding ON", "shed_start"),
    ("admission shedding OFF", "shed_stop"),
    ("killed", "kill"),
    ("recover refused", "recover_refused"),
    ("recovered", "recover"),
    ("wiped", "wipe"),
    ("partition installed", "partition"),
    ("partition healed", "heal"),
    ("snapshot installed to", "snapshot_install"),
    ("healed by reconstruction to", "repair"),
    ("suffix re-served to", "repair"),
    ("injected candidacy suppressed by pre-vote", "prevote_suppress"),
    ("pre-vote failed", "prevote_fail"),
    ("vote log replayed", "votelog_replay"),
    ("restored from checkpoint", "restore"),
    ("apply replay is partial", "apply_partial"),
)


def kind_of(msg: str) -> str:
    """Classify a legacy nodelog message into an event kind (``"log"``
    when unrecognized — the event is still recorded and renderable)."""
    for prefix, kind in _KIND_CATALOG:
        if msg.startswith(prefix):
            return kind
    return "log"


@dataclasses.dataclass(frozen=True)
class Event:
    """One structured observability event.

    Events that originate at a legacy nodelog call site carry ``msg``
    plus the full nodelog header fields (``commit_index``,
    ``last_index``, ``state``) and render byte-identically via
    ``nodelog()``. Events from the previously-silent transitions
    (repair floor raises, breaker state changes, ...) carry ``msg=None``
    and structured ``fields`` only — they never enter the legacy trace
    stream, which must not drift."""

    seq: int                     # recorder-monotone, survives ring overflow
    t_virtual: float             # engine virtual-clock seconds
    node: str                    # "Server3", "g2/Server0", "g1/client", ...
    group: Optional[int]         # multi-Raft group scope; None = single
    term: int
    kind: str
    state: str = ""              # role at emission ("leader", ...)
    commit_index: Optional[int] = None
    last_index: Optional[int] = None
    msg: Optional[str] = None    # legacy nodelog message, when one exists
    fields: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def nodelog(self) -> str:
        """The legacy rendering — byte-identical to the pre-recorder
        ``trace`` callback line for events emitted from nodelog sites."""
        if self.msg is None:
            raise ValueError(
                f"event kind {self.kind!r} has no nodelog rendering "
                "(it never entered the legacy trace stream)"
            )
        return (
            f"[{self.node}:{self.term}:{self.commit_index}:"
            f"{self.last_index}][{self.state}]{self.msg}"
        )

    def to_jsonable(self) -> dict:
        d = dataclasses.asdict(self)
        if not d["fields"]:
            del d["fields"]
        return d

    @classmethod
    def from_jsonable(cls, d: dict) -> "Event":
        return cls(**{**{"fields": {}}, **d})


class FlightRecorder:
    """Bounded ring of :class:`Event` with structured query helpers —
    the replacement for grepping ``TraceRecorder.lines``."""

    def __init__(self, capacity: int = 65536):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._next_seq = 0
        self.dropped = 0

    def record(
        self,
        *,
        node: str,
        term: int,
        kind: Optional[str] = None,
        t_virtual: float = 0.0,
        state: str = "",
        group: Optional[int] = None,
        commit_index: Optional[int] = None,
        last_index: Optional[int] = None,
        msg: Optional[str] = None,
        **fields: Any,
    ) -> Event:
        """Append one event; oldest events fall off past ``capacity``
        (counted in ``dropped``). ``kind=None`` classifies from ``msg``."""
        if kind is None:
            kind = kind_of(msg) if msg is not None else "event"
        ev = Event(
            seq=self._next_seq, t_virtual=t_virtual, node=node,
            group=group, term=term, kind=kind, state=state,
            commit_index=commit_index, last_index=last_index,
            msg=msg, fields=fields,
        )
        self._next_seq += 1
        if len(self._ring) == self.capacity:
            self.dropped += 1
        self._ring.append(ev)
        return ev

    # ------------------------------------------------------------ queries
    def __len__(self) -> int:
        return len(self._ring)

    @property
    def total_recorded(self) -> int:
        return self._next_seq

    def events(
        self,
        kind: Optional[str] = None,
        node: Optional[str] = None,
        group: Optional[int] = None,
    ) -> List[Event]:
        out: Iterable[Event] = self._ring
        if kind is not None:
            out = (e for e in out if e.kind == kind)
        if node is not None:
            out = (e for e in out if e.node == node)
        if group is not None:
            out = (e for e in out if e.group == group)
        return list(out)

    def of_kind(self, *kinds: str) -> List[Event]:
        want = set(kinds)
        return [e for e in self._ring if e.kind in want]

    def nodelog_lines(self) -> List[str]:
        """The legacy trace stream re-rendered from the ring (events
        that never had a nodelog line are skipped)."""
        return [e.nodelog() for e in self._ring if e.msg is not None]

    def leaders_by_term(
        self, group: Optional[int] = None
    ) -> Dict[int, set]:
        """term -> nodes that recorded an election win in that term
        (optionally scoped to one multi-Raft group). Election Safety is
        ``all(len(v) <= 1 for v in ...values())`` — the structured
        replacement for ``TraceRecorder.leaders_by_term``."""
        out: Dict[int, set] = {}
        for e in self.events(kind="elect", group=group):
            out.setdefault(e.term, set()).add(e.node)
        return out

    def last_leader_per_term(
        self, group: Optional[int] = None
    ) -> Dict[int, Event]:
        """term -> the LAST election-win event of that term (forensics:
        who held each term when things went wrong)."""
        out: Dict[int, Event] = {}
        for e in self.events(kind="elect", group=group):
            out[e.term] = e
        return out

    # --------------------------------------------------------- (de)serial
    def to_jsonable(self) -> dict:
        return {
            "capacity": self.capacity,
            "dropped": self.dropped,
            "total_recorded": self._next_seq,
            "events": [e.to_jsonable() for e in self._ring],
        }

    @classmethod
    def from_jsonable(cls, d: dict) -> "FlightRecorder":
        rec = cls(capacity=d["capacity"])
        rec.dropped = d.get("dropped", 0)
        rec._next_seq = d.get("total_recorded", len(d["events"]))
        for ed in d["events"]:
            rec._ring.append(Event.from_jsonable(ed))
        return rec
