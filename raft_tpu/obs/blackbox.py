"""Multihost black-box recorder: progress journals + stall watchdog.

Every MULTICHIP bench round since r01 that failed did so the same way:
``rc=124`` with nothing in the tail but an xla_bridge warning — the
external kill arrived while the process was blocked inside some
collective, compile, or barrier, and everything it knew died with it.
The flight recorder (obs/events.py) cannot help there: it lives in
memory and is only dumped by code that runs *after* the hang would have
to end.

This module is the crash-and-hang-proof half of the observability
plane, in two parts:

- :class:`BlackboxJournal` — a per-process append-only, **line-flushed**
  progress journal. The rule is *write the mark BEFORE the blocking
  operation*: device enumeration, mesh build, barrier enter/exit,
  allgather launches (with an id), bench phases, tick counts. Each mark
  is one JSON line, flushed to the kernel, so a SIGKILL'd or wedged
  process still leaves a durable record whose LAST line names the phase
  it never finished. Wired through ``transport/tpu_mesh.py``,
  ``transport/multihost.py``, ``transport/reform.py``, the engine's
  mirror-digest barrier, the chaos runners and
  ``__graft_entry__.dryrun_multichip``.

- :class:`StallWatchdog` — a daemon thread that fires when no
  :meth:`StallWatchdog.pet` arrives for ``deadline_s`` seconds: it dumps
  ``faulthandler`` stacks of ALL threads plus the journal tail into a
  PR-5-style bundle (``stall_<tag>_pid<pid>.json``, format
  ``raft_tpu.obs/stall.v1``), mirrors the same forensics to stderr, and
  can hard-exit the process with a chosen code — so a hung 8-device run
  finally reports *which process, which phase, which barrier* instead
  of an empty rc=124.

Components mark through the module-level active journal
(:func:`set_journal` / :func:`mark`): with no journal installed every
mark is a single ``None`` check — the observe-off path costs nothing
and touches no device state.

``python -m raft_tpu.obs --explain`` understands journals (``.jsonl``
files or a directory of them) and stall bundles: it reconstructs the
per-process phase timeline and names the in-flight phase
(:func:`explain_journal`, :func:`explain_stall`).
"""

from __future__ import annotations

import faulthandler
import json
import os
import sys
import tempfile
import threading
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence

STALL_FORMAT = "raft_tpu.obs/stall.v1"


def resolve_blackbox_dir(blackbox_dir: Optional[str] = None) -> Optional[str]:
    """Destination policy, mirroring ``forensics.resolve_bundle_dir``:
    explicit argument, else ``RAFT_TPU_BLACKBOX_DIR``, else disabled."""
    if blackbox_dir is not None:
        return blackbox_dir
    return os.environ.get("RAFT_TPU_BLACKBOX_DIR") or None


class BlackboxJournal:
    """Append-only, line-flushed progress journal for ONE process.

    Each :meth:`mark` writes one JSON line
    ``{seq, t, mono, pid, proc, phase, ...fields}`` and flushes it to
    the kernel before returning — the write-before-block contract: when
    the next operation hangs forever (or the process is killed), the
    journal already says what it was. No fsync: the threat is process
    death, which kernel buffers survive; OS-crash durability is not
    worth a syscall per allgather on the path being measured.
    Appending (never truncating) means one journal file spans crash-
    restore cycles; ``journal_open`` marks separate the incarnations.
    ``fresh=True`` truncates instead — for fixed-path journals meant to
    hold ONE round (the multichip dryrun), where accreting rounds would
    let ``explain_journal`` merge two runs' timelines into one story.
    """

    def __init__(
        self, path: str, proc: Optional[str] = None, fresh: bool = False,
    ):
        self.path = str(path)
        self.proc = proc or f"pid{os.getpid()}"
        self._lock = threading.Lock()
        self._seq = 0
        self.last_phase: Optional[str] = None
        try:
            Path(self.path).parent.mkdir(parents=True, exist_ok=True)
            self._f = open(self.path, "w" if fresh else "a", buffering=1)
        except OSError as ex:
            # Best-effort like every other write in this module: an
            # unwritable destination (read-only dir, another user's
            # leftover /tmp file) must degrade to no journal, never
            # crash the run the journal exists to observe.
            print(
                f"raft_tpu.obs: blackbox journal {self.path!r} not "
                f"writable ({ex}); journaling disabled", file=sys.stderr,
            )
            self._f = None
        self.mark("journal_open", argv=" ".join(sys.argv[:4]))

    def mark(self, phase: str, /, **fields: Any) -> dict:
        """Durably record that ``phase`` is about to run (or just
        happened — the caller picks the tense; blocking operations mark
        BEFORE). Thread-safe; safe after close (silently dropped, so a
        late watchdog or daemon thread cannot crash shutdown)."""
        with self._lock:
            rec = {
                "seq": self._seq,
                "t": round(time.time(), 6),
                "mono": round(time.monotonic(), 6),
                "pid": os.getpid(),
                "proc": self.proc,
                "phase": phase,
            }
            for k, v in fields.items():
                # the envelope is the reader's grouping key (explain
                # groups timelines by (proc, pid)) — a caller field must
                # never clobber it, or one OS process splits into
                # phantom per-"pid" timelines in the post-mortem. The
                # positional-only ``phase, /`` lets even a field named
                # "phase" land here instead of a TypeError crashing the
                # run the journal observes.
                rec[k if k not in rec else f"field_{k}"] = v
            self._seq += 1
            self.last_phase = phase
            if self._f is not None:
                try:
                    self._f.write(json.dumps(rec) + "\n")
                    # flush (no fsync): the threat model is a hung or
                    # SIGKILL'd PROCESS — kernel-buffered data survives
                    # both. fsync would only add OS-crash durability, at
                    # a syscall per mark on the multihost hot path
                    # (every allgather marks) — perturbing the very
                    # measurement this plane exists to take.
                    self._f.flush()
                except (ValueError, OSError):
                    pass      # closed file / full disk: journal is best-effort
        return rec

    def close(self) -> None:
        if self._f is not None and not self._f.closed:
            self.mark("journal_close")
            self._f.close()


# ------------------------------------------------- module-active journal
_active: Optional[BlackboxJournal] = None


def set_journal(j: Optional[BlackboxJournal]) -> Optional[BlackboxJournal]:
    """Install ``j`` as the process's active journal; returns the
    previous one (callers restore it — see :func:`journal_for`)."""
    global _active
    prev, _active = _active, j
    return prev


def get_journal() -> Optional[BlackboxJournal]:
    return _active


def mark(phase: str, /, **fields: Any) -> None:
    """Mark into the active journal; a no-op (one None check) when no
    journal is installed — the disabled path costs nothing, which is
    why transports and the engine barrier can call this unconditionally."""
    j = _active
    if j is not None:
        j.mark(phase, **fields)


@contextmanager
def journal_for(
    tag: str,
    blackbox_dir: Optional[str] = None,
    proc: Optional[str] = None,
) -> Iterator[Optional[BlackboxJournal]]:
    """Open ``journal_<tag>.jsonl`` under the resolved blackbox dir and
    install it as the active journal for the block; yields None (and
    does nothing) when no destination is configured."""
    bdir = resolve_blackbox_dir(blackbox_dir)
    if bdir is None:
        yield None
        return
    j = BlackboxJournal(os.path.join(bdir, f"journal_{tag}.jsonl"), proc=proc)
    prev = set_journal(j)
    try:
        yield j
    finally:
        set_journal(prev)
        j.close()


# ------------------------------------------------------------- reading
def read_journal(path: str) -> List[dict]:
    """Parse one journal back into its marks, in file order. Torn final
    lines (the process died mid-write) are skipped rather
    than raised — a forensics reader must never choke on the artifact
    of the very crash it is investigating."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out


def journal_tail(path: str, n: int = 40) -> List[dict]:
    return read_journal(path)[-n:]


# ------------------------------------------------------------ watchdog
def _all_thread_stacks() -> str:
    """Python stacks of every live thread via faulthandler (needs a real
    fd, hence the temp file)."""
    try:
        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            return f.read()
    except Exception as ex:            # stack dump must never mask the stall
        return f"<faulthandler dump failed: {ex!r}>"


class StallWatchdog:
    """Fires when no progress (:meth:`pet`) arrives for ``deadline_s``.

    On fire it writes a stall bundle — per-process faulthandler stacks
    of ALL threads, the journal tail, the last journal phase — to
    ``bundle_dir`` (``stall_<tag>_pid<pid>.json``), mirrors the same
    forensics to stderr (so an external log tail carries them even if
    the disk write fails), invokes ``on_fire`` if given, and, when
    ``hard_exit_code`` is set, ``os._exit``s — converting the silent
    external-kill mode (rc=124, parsed: null) into a self-reported
    stall with a full forensic record. Arming, petting and disarming
    are cheap; a clean run that disarms in time writes nothing.
    """

    def __init__(
        self,
        deadline_s: float,
        *,
        tag: str = "run",
        journal: Optional[BlackboxJournal] = None,
        bundle_dir: Optional[str] = None,
        on_fire=None,
        hard_exit_code: Optional[int] = None,
        tail_lines: int = 40,
        poll_s: Optional[float] = None,
    ):
        if deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        self.deadline_s = float(deadline_s)
        self.tag = tag
        self.journal = journal
        self.bundle_dir = resolve_blackbox_dir(bundle_dir)
        self.on_fire = on_fire
        self.hard_exit_code = hard_exit_code
        self.tail_lines = tail_lines
        self._poll_s = poll_s if poll_s is not None else min(
            0.25, self.deadline_s / 4
        )
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_pet = time.monotonic()
        self.fired = False
        self.bundle_path: Optional[str] = None

    # ------------------------------------------------------------ control
    def arm(self) -> "StallWatchdog":
        self._last_pet = time.monotonic()
        self._thread = threading.Thread(
            target=self._watch, daemon=True,
            name=f"stall-watchdog-{self.tag}",
        )
        self._thread.start()
        return self

    def pet(self) -> None:
        """Progress notification: the deadline restarts from now."""
        self._last_pet = time.monotonic()

    def disarm(self) -> None:
        self._done.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def __enter__(self) -> "StallWatchdog":
        return self.arm()

    def __exit__(self, *exc) -> None:
        self.disarm()

    # ------------------------------------------------------------- firing
    def _watch(self) -> None:
        while not self._done.wait(self._poll_s):
            if time.monotonic() - self._last_pet >= self.deadline_s:
                self._fire()
                return

    def _fire(self) -> None:
        if self._done.is_set():
            # a disarm racing a just-expired deadline: the run completed
            # — do not hard-exit it between its last phase and its
            # summary row
            return
        self.fired = True
        stalled_for = time.monotonic() - self._last_pet
        phase = self.journal.last_phase if self.journal is not None else None
        tail = (
            journal_tail(self.journal.path, self.tail_lines)
            if self.journal is not None else []
        )
        stacks = _all_thread_stacks()
        bundle = {
            "format": STALL_FORMAT,
            "kind": "stall",
            "tag": self.tag,
            "pid": os.getpid(),
            "proc": (self.journal.proc if self.journal is not None
                     else f"pid{os.getpid()}"),
            "deadline_s": self.deadline_s,
            "stalled_for_s": round(stalled_for, 3),
            "phase": phase,
            "journal_path": (self.journal.path if self.journal is not None
                             else None),
            "journal_tail": tail,
            "stacks": stacks,
        }
        if self.bundle_dir is not None:
            try:
                Path(self.bundle_dir).mkdir(parents=True, exist_ok=True)
                p = Path(self.bundle_dir) / (
                    f"stall_{self.tag}_pid{os.getpid()}.json"
                )
                p.write_text(json.dumps(bundle))
                self.bundle_path = str(p)
            except OSError as ex:
                print(
                    f"raft_tpu.obs: stall bundle not written to "
                    f"{self.bundle_dir!r}: {ex}", file=sys.stderr,
                )
        # stderr mirror: the external driver's log tail must carry the
        # forensics even when the bundle write itself fails
        print(
            f"raft_tpu.obs STALL: {self.tag} pid {os.getpid()} made no "
            f"progress for {stalled_for:.1f}s (deadline {self.deadline_s:g}s)"
            + (f"; blocked phase: {phase}" if phase else "")
            + (f"; bundle: {self.bundle_path}" if self.bundle_path else ""),
            file=sys.stderr,
        )
        print(stacks, file=sys.stderr)
        if self.on_fire is not None:
            try:
                self.on_fire(bundle)
            except Exception:
                pass
        if self.hard_exit_code is not None and not self._done.is_set():
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(self.hard_exit_code)


# ------------------------------------------------------------- explain
def _fmt_fields(rec: dict) -> str:
    skip = {"seq", "t", "mono", "pid", "proc", "phase"}
    kv = {k: v for k, v in rec.items() if k not in skip}
    return (" " + " ".join(f"{k}={v}" for k, v in kv.items())) if kv else ""


def explain_journal(paths: Sequence[str]) -> str:
    """Reconstruct the per-process phase timeline from one or more
    journals: each mark with its offset from incarnation start and the
    time spent until the NEXT mark; the final mark of each incarnation
    is flagged as in flight — for a hung run that line IS the diagnosis
    (which process, which phase, which barrier). An append-mode journal
    holds one incarnation per ``journal_open`` (a killed run followed by
    a re-run of the same seed appends a second); each is rendered as its
    own timeline, so an earlier wedged incarnation keeps its in-flight
    flag and no duration spans the gap between runs."""
    out: List[str] = []
    for path in paths:
        recs = read_journal(path)
        if not recs:
            out.append(f"{path}: empty or unreadable journal")
            continue
        by_proc: Dict[tuple, List[dict]] = {}
        for r in recs:
            by_proc.setdefault((r.get("proc"), r.get("pid")), []).append(r)
        out.append(f"{path}:")
        for (proc, pid), marks in by_proc.items():
            runs: List[List[dict]] = []
            for r in marks:
                if r.get("phase") == "journal_open" or not runs:
                    runs.append([])
                runs[-1].append(r)
            for run_no, run in enumerate(runs):
                t0 = run[0].get("mono", 0.0)
                tag = f", incarnation {run_no}" if len(runs) > 1 else ""
                out.append(
                    f"  process {proc} (pid {pid}{tag}): {len(run)} marks"
                )
                for i, r in enumerate(run):
                    dt = r.get("mono", 0.0) - t0
                    if i + 1 < len(run):
                        held = run[i + 1].get("mono", 0.0) - r.get("mono", 0.0)
                        dur = f"{held:8.3f}s"
                        flag = ""
                    else:
                        dur = "        "
                        flag = (
                            ""
                            if r.get("phase") == "journal_close"
                            else "   <== in flight at journal end"
                        )
                    out.append(
                        f"    +{dt:9.3f}s  {dur}  "
                        f"{r.get('phase')}{_fmt_fields(r)}{flag}"
                    )
    return "\n".join(out)


def explain_merged(paths: Sequence[str], limit: int = 400) -> str:
    """The MERGED cross-process timeline: every mark from every journal
    interleaved on the shared wall clock (``t`` — the one field
    comparable across processes; ``mono`` restarts with each
    incarnation and never crosses a pid). This is the forensics view a
    multi-process drill needs — ``kill -9`` lands in the supervisor's
    journal, the last gasp in the victim's, the re-election in a
    peer's, and only side by side do they read as one story. Each line
    carries its offset from the EARLIEST mark across all journals plus
    the owning process (``proc[pid]``), so an incarnation change shows
    up as the same proc under a new pid. ``limit`` caps the render from
    the tail (the interesting end of a crashed run), with an elision
    line saying how many earlier marks were folded."""
    recs: List[dict] = []
    for path in paths:
        recs.extend(read_journal(path))
    recs = [r for r in recs if "t" in r]
    if not recs:
        return "no marks in any journal"
    recs.sort(key=lambda r: (r.get("t", 0.0), r.get("pid", 0),
                             r.get("seq", 0)))
    t0 = recs[0]["t"]
    out = [f"merged timeline ({len(recs)} marks, "
           f"{len(set((r.get('proc'), r.get('pid')) for r in recs))} "
           f"process incarnations):"]
    if len(recs) > limit:
        out.append(f"  ... {len(recs) - limit} earlier marks elided")
        recs = recs[-limit:]
    width = max(len(str(r.get("proc"))) for r in recs)
    for r in recs:
        out.append(
            f"  +{r['t'] - t0:9.3f}s  "
            f"{str(r.get('proc')):<{width}} [{r.get('pid')}]  "
            f"{r.get('phase')}{_fmt_fields(r)}"
        )
    return "\n".join(out)


def explain_stall(bundle: dict) -> str:
    """The stall bundle's failure story: who stalled, in which phase,
    the journal tail leading up to it, and every thread's stack."""
    out = [
        f"STALL: {bundle.get('tag')} — process {bundle.get('proc')} "
        f"(pid {bundle.get('pid')}) made no progress for "
        f"{bundle.get('stalled_for_s')}s "
        f"(deadline {bundle.get('deadline_s')}s)",
        f"blocked phase: {bundle.get('phase') or '<no journal attached>'}",
    ]
    tail = bundle.get("journal_tail") or []
    if tail:
        t0 = tail[0].get("mono", 0.0)
        out.append(f"journal tail ({len(tail)} marks, "
                   f"{bundle.get('journal_path')}):")
        for r in tail:
            out.append(
                f"  +{r.get('mono', 0.0) - t0:9.3f}s  "
                f"{r.get('phase')}{_fmt_fields(r)}"
            )
        out.append("  (last mark is the operation that never completed)")
    if bundle.get("stacks"):
        out.append("thread stacks at fire time:")
        out.append(bundle["stacks"].rstrip())
    return "\n".join(out)
