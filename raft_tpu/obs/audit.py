"""Online safety auditor: Raft invariants checked DURING the run.

Every safety verdict before this module was post-hoc — the Wing–Gong
checker and the forensics bundles speak only after a seeded run ends. A
production deployment needs the cheap half of that assurance LIVE:
Ongaro's dissertation frames Leader Completeness / Log Matching /
State-Machine Safety as invariants over watermarks that are incremental
to check, and Jepsen-style monotonicity auditing catches the classic
stale-read classes at a fraction of a full linearizability search.

:class:`SafetyAuditor` attaches to ``RaftEngine`` / ``MultiEngine`` like
the other observability planes (``engine.auditor``, ``None`` = off;
every hook is a guarded host-side call — no rng, no device fetches, so
seeded runs replay byte-identically audited or not). Invariants:

==================  =====================================================
invariant           checked when
==================  =====================================================
leader_unique       an election win is recorded: at most one winner per
                    (group, term) — Election Safety, online
commit_monotone     every tick: the group's commit watermark never
                    regresses (also re-checked when the auditor is
                    re-attached across a crash-restore cycle)
term_monotone       every tick: no replica's term regresses (a ``wipe``
                    legally resets a row — the engine reports it)
log_matching        a committed index is re-fed (re-archive after
                    failover, restore overlap): its (term, payload CRC)
                    must equal what was recorded when it first committed
                    — committed-prefix immutability, the online face of
                    Log Matching / State-Machine Safety
read_uncommitted    a served read returns a value that was never applied
                    for its key — a dirty read, caught at serve time
read_monotone       a client's served read reflects an OLDER applied
                    state than one it already observed for that key —
                    the per-client monotone-read watermark inversion
==================  =====================================================

A violation raises no exception — a production auditor must never take
the service down on its own evidence. It appends a typed
:class:`AuditViolation`, records a ``kind="audit_violation"`` event into
the PR-5 flight recorder, and bumps
``raft_audit_violations_total{invariant}`` when a registry is attached.

The committed-prefix CRC record doubles as the determinism witness: the
auditor's :meth:`commit_digest` reproduces the chaos runner's
``TortureReport.commit_digest`` formula from its own incremental
records, and the falsifiability tests pin the two equal — so the
auditor provably watched the same committed log the offline checker
judged. Entry records are bounded by the same floor-aware sweep as
``ckpt.CheckpointStore`` (``max_entries``); fused K-tick launches feed
whole spans lazily (O(1) per launch), matching ``put_span``.
"""

from __future__ import annotations

import bisect
import dataclasses
import zlib
from typing import Dict, List, Optional, Tuple

#: per-key applied-value history bound (values retained per key for the
#: read-audit lookups; below the floor a read audit degrades gracefully)
APPLIED_CAP = 4096


@dataclasses.dataclass(frozen=True)
class AuditViolation:
    """One typed online invariant violation."""

    invariant: str            # table in the module docstring
    t_virtual: float
    group: Optional[int]
    node: Optional[str]       # "Server2", "g1/Server0", "client:3", ...
    detail: str
    fields: Dict[str, object] = dataclasses.field(default_factory=dict)

    def to_jsonable(self) -> dict:
        d = dataclasses.asdict(self)
        if not d["fields"]:
            del d["fields"]
        return d


def _pcrc(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


class _EntryLedger:
    """Bounded per-group record of the committed prefix: idx ->
    (term, payload CRC), plus lazily-resolved span blocks (the fused
    launch feed). Mirrors ``CheckpointStore``'s floor-aware retention
    so the auditor's digest coverage matches the archive's."""

    def __init__(self, max_entries: Optional[int]) -> None:
        self.max_entries = max_entries
        self.slots: Dict[int, Tuple[Optional[int], int]] = {}
        self.spans: Dict[int, tuple] = {}    # lo -> (hi, items, term, pick)
        self.span_los: List[int] = []
        self.last = 0
        self.first = 1

    def put(self, idx: int, term: Optional[int], crc: int) -> None:
        self.slots[idx] = (term, crc)
        self.last = max(self.last, idx)
        self._sweep()

    def put_span(self, lo: int, items, term: int, pick) -> None:
        if not len(items):
            return
        if lo not in self.spans:
            bisect.insort(self.span_los, lo)
        self.spans[lo] = (lo + len(items) - 1, items, term, pick)
        self.last = max(self.last, lo + len(items) - 1)
        self._sweep()

    def _sweep(self) -> None:
        if self.max_entries is None:
            return
        floor = self.last - self.max_entries
        while self.first <= floor:
            self.slots.pop(self.first, None)
            self.first += 1
        while self.span_los and \
                self.spans[self.span_los[0]][0] < self.first:
            del self.spans[self.span_los.pop(0)]

    def get(self, idx: int) -> Optional[Tuple[Optional[int], int]]:
        """(term, payload CRC) or None; span entries resolve lazily."""
        if idx < self.first:
            return None
        got = self.slots.get(idx)
        if got is not None:
            return got
        if not self.span_los:
            return None
        i = bisect.bisect_right(self.span_los, idx) - 1
        if i < 0:
            return None
        lo = self.span_los[i]
        hi, items, term, pick = self.spans[lo]
        if idx > hi:
            return None
        rec = items[idx - lo]
        return (term, _pcrc(rec if pick is None else rec[pick]))

    def covered_lo(self, hi: int) -> int:
        if self.get(hi) is None:
            return hi + 1
        lo = hi
        while lo - 1 >= 1 and self.get(lo - 1) is not None:
            lo -= 1
        return lo


class SafetyAuditor:
    """The online invariant checker (module docstring). One instance
    spans crash-restore cycles like the flight recorder: the chaos
    runner re-attaches it to each restored engine, and the attach hook
    re-verifies the restored state against the records."""

    VIOLATION_CAP = 1024
    #: default entry-record retention when no engine archive is adopted
    #: (``on_attach`` aligns the cap to the engine's CheckpointStore so
    #: digest coverage matches the archive's); bounded BY DEFAULT — a
    #: long production run must not grow auditor memory without bound.
    DEFAULT_MAX_ENTRIES = 1 << 16

    def __init__(self, recorder=None, registry=None,
                 max_entries: Optional[int] = DEFAULT_MAX_ENTRIES) -> None:
        self.recorder = recorder
        self.registry = registry
        self.max_entries = max_entries
        self.violations: List[AuditViolation] = []
        self.violations_dropped = 0
        self.by_invariant: Dict[str, int] = {}
        self._leaders: Dict[Tuple[Optional[int], int], str] = {}
        self._commit_hwm: Dict[Optional[int], int] = {}
        self._term_hwm: Dict[Tuple[Optional[int], str], int] = {}
        self._ledgers: Dict[Optional[int], _EntryLedger] = {}
        self._applied: Dict[Tuple[Optional[int], bytes], dict] = {}
        #   (group, key) -> {value (bytes|None) -> apply index}; bounded
        #   per key by APPLIED_CAP with an eviction floor
        self._applied_floor: Dict[Tuple[Optional[int], bytes], int] = {}
        self._read_hwm: Dict[Tuple[int, Optional[int], bytes], int] = {}
        #   (client, group, key) -> highest applied index observed
        self.ticks_audited = 0

    # --------------------------------------------------------- emission
    def _violate(self, invariant: str, t: float, detail: str,
                 group: Optional[int] = None, node: Optional[str] = None,
                 **fields) -> None:
        self.by_invariant[invariant] = (
            self.by_invariant.get(invariant, 0) + 1
        )
        v = AuditViolation(
            invariant=invariant, t_virtual=t, group=group, node=node,
            detail=detail, fields=fields,
        )
        if len(self.violations) >= self.VIOLATION_CAP:
            self.violations_dropped += 1
        else:
            self.violations.append(v)
        if self.recorder is not None:
            self.recorder.record(
                node=node or "auditor", term=0, kind="audit_violation",
                t_virtual=t, group=group, invariant=invariant,
                detail=detail, **fields,
            )
        if self.registry is not None:
            self.registry.counter(
                "raft_audit_violations_total",
                "online safety invariant violations", ("invariant",),
            ).inc(invariant=invariant)

    @property
    def total_violations(self) -> int:
        return len(self.violations) + self.violations_dropped

    # ----------------------------------------------------- engine hooks
    def note_elect(self, node: str, term: int, t: float,
                   group: Optional[int] = None) -> None:
        """An election win was recorded; Election Safety demands at most
        one winner per (group, term)."""
        key = (group, term)
        prev = self._leaders.get(key)
        if prev is not None and prev != node:
            self._violate(
                "leader_unique", t,
                f"term {term} won by {node} but already won by {prev}",
                group=group, node=node, term=term, previous=prev,
            )
        self._leaders[key] = node

    def note_wipe(self, node: str, group: Optional[int] = None) -> None:
        """A row's durable identity was destroyed (``engine.wipe``): its
        term legally resets to 0 — the monotonicity watermark resets
        with it."""
        self._term_hwm.pop((group, node), None)

    def note_commit(self, watermark: int, t: float,
                    group: Optional[int] = None) -> None:
        """A commit advance was booked; the watermark must be monotone
        per group."""
        hwm = self._commit_hwm.get(group, 0)
        if watermark < hwm:
            self._violate(
                "commit_monotone", t,
                f"commit watermark advanced to {watermark} below the "
                f"recorded high-water {hwm}",
                group=group, watermark=watermark, high_water=hwm,
            )
        else:
            self._commit_hwm[group] = watermark

    def note_entry(self, idx: int, term: Optional[int], payload: bytes,
                   t: float, group: Optional[int] = None) -> None:
        """A committed entry's bytes were archived. First sighting is
        recorded; a RE-feed (failover re-archive, restore overlap) must
        match the record byte-for-byte — committed-prefix immutability."""
        led = self._ledgers.get(group)
        if led is None:
            led = self._ledgers[group] = _EntryLedger(self.max_entries)
        crc = _pcrc(payload)
        prev = led.get(idx)
        if prev is not None and (
            prev[1] != crc
            or (term is not None and prev[0] is not None
                and prev[0] != term)
        ):
            self._violate(
                "log_matching", t,
                f"committed index {idx} re-fed with term={term} "
                f"crc={crc:08x}, previously term={prev[0]} "
                f"crc={prev[1]:08x}",
                group=group, index=idx,
            )
            return                       # keep the first sighting
        led.put(idx, term, crc)

    def note_entries(self, entries, t: float,
                     group: Optional[int] = None) -> None:
        """Bulk archive feed for the tick path: ``entries`` is a list of
        ``(idx, payload, term)`` in ascending index order. Fresh
        contiguous same-term runs above the ledger tail become ONE lazy
        span block (O(1) amortized — the <= 5% overhead contract at the
        headline batch size); anything overlapping the record goes
        through the per-entry immutability compare."""
        if not entries:
            return
        led = self._ledgers.get(group)
        if led is None:
            led = self._ledgers[group] = _EntryLedger(self.max_entries)
        i, n = 0, len(entries)
        while i < n:
            idx0, _, term0 = entries[i]
            if idx0 <= led.last:
                self.note_entry(idx0, term0, entries[i][1], t,
                                group=group)
                i += 1
                continue
            j = i + 1
            while (j < n and entries[j][2] == term0
                   and entries[j][0] == entries[j - 1][0] + 1):
                j += 1
            led.put_span(idx0, [p for _, p, _ in entries[i:j]], term0,
                         None)
            i = j

    def note_entry_span(self, lo: int, items, term: int, t: float,
                        pick=None, group: Optional[int] = None) -> None:
        """Whole-range feed for the fused K-tick booking path — O(1) per
        launch (entries resolve lazily), mirroring
        ``CheckpointStore.put_span``. Fresh indices only by contract
        (the fused drain commits fresh tail entries), so no per-entry
        immutability compare happens here."""
        led = self._ledgers.get(group)
        if led is None:
            led = self._ledgers[group] = _EntryLedger(self.max_entries)
        led.put_span(lo, items, term, pick)

    def note_state(self, terms, watermark: int, t: float,
                   group: Optional[int] = None,
                   node_prefix: str = "Server") -> None:
        """Per-tick scan of host mirrors the engine already maintains:
        per-replica term monotonicity plus the watermark-regression
        check (catches a rewind that ``note_commit`` — which only sees
        advances — cannot)."""
        self.ticks_audited += 1
        for r, term in enumerate(terms):
            term = int(term)
            key = (group, f"{node_prefix}{r}")
            hwm = self._term_hwm.get(key, 0)
            if term < hwm:
                self._violate(
                    "term_monotone", t,
                    f"{node_prefix}{r} term regressed {hwm} -> {term} "
                    "without a wipe",
                    group=group, node=f"{node_prefix}{r}",
                    high_water=hwm, term=term,
                )
                self._term_hwm[key] = term     # re-anchor; report once
            elif term > hwm:
                self._term_hwm[key] = term
        hwm = self._commit_hwm.get(group, 0)
        if watermark < hwm:
            self._violate(
                "commit_monotone", t,
                f"commit watermark regressed {hwm} -> {watermark}",
                group=group, watermark=int(watermark), high_water=hwm,
            )
            self._commit_hwm[group] = int(watermark)   # report once
        else:
            self._commit_hwm[group] = int(watermark)

    def on_attach(self, engine) -> None:
        """Re-attachment across a crash-restore cycle: the restored
        engine's committed state must extend — never contradict — the
        recorded prefix. Overlapping archived entries are compared
        (a rollback that resurrected different committed bytes trips
        ``log_matching``); a restored watermark below the record trips
        ``commit_monotone``."""
        store = getattr(engine, "store", None)
        wm = getattr(engine, "commit_watermark", None)
        if store is None or wm is None or isinstance(wm, (list,)):
            return
        try:
            wm = int(wm)
        except TypeError:          # MultiEngine vector: per-group checks
            return                 # ride the per-tick note_state instead
        if getattr(store, "max_entries", None):
            # adopt the archive's retention horizon so the auditor's
            # digest coverage (covered_lo) tracks the store's exactly —
            # the cross-check against TortureReport.commit_digest
            # depends on the two sweeping identically
            self.max_entries = store.max_entries
            led0 = self._ledgers.get(None)
            if led0 is not None:
                led0.max_entries = store.max_entries
                led0._sweep()
        t = float(engine.clock.now)
        hwm = self._commit_hwm.get(None, 0)
        if wm < hwm:
            self._violate(
                "commit_monotone", t,
                f"restored commit watermark {wm} below the recorded "
                f"high-water {hwm}",
                watermark=wm, high_water=hwm,
            )
        led = self._ledgers.get(None)
        if led is not None:
            lo = max(led.first, store.first)
            for idx in range(lo, min(wm, led.last) + 1):
                ent = store.get(idx)
                if ent is None:
                    continue
                self.note_entry(idx, ent[1], ent[0], t)

    # ------------------------------------------------- workload hooks
    def note_apply(self, key: bytes, index: int, value: Optional[bytes],
                   group: Optional[int] = None) -> None:
        """A committed entry was applied to the key-value state machine:
        record value -> apply index for the read audits (``value=None``
        records a delete). Bounded per key (APPLIED_CAP)."""
        akey = (group, key)
        hist = self._applied.get(akey)
        if hist is None:
            hist = self._applied[akey] = {}
        hist[value] = index
        if len(hist) > APPLIED_CAP:
            old_v = next(iter(hist))
            self._applied_floor[akey] = max(
                self._applied_floor.get(akey, 0), hist.pop(old_v)
            )

    def observe_read(self, client: int, key: bytes,
                     value: Optional[bytes], t: float,
                     group: Optional[int] = None) -> None:
        """A read was SERVED to ``client``: audit it online. The served
        value's applied index is its watermark; ``None`` with no
        recorded delete is the key's initial state (watermark 0)."""
        akey = (group, key)
        hist = self._applied.get(akey, {})
        w = hist.get(value)
        if w is None:
            floor = self._applied_floor.get(akey, 0)
            if floor > 0:
                # evicted history (None included: an old delete record
                # may have been swept): cannot distinguish "never
                # applied" from "applied long ago" — treat as the floor
                # and let the monotone check below decide
                w = floor
            elif value is None:
                w = 0                     # initial state
            else:
                self._violate(
                    "read_uncommitted", t,
                    f"client {client} read {value!r} for key {key!r}: "
                    "value was never applied (dirty read of "
                    "uncommitted state)",
                    group=group, node=f"client:{client}",
                    client=client,
                )
                return
        rkey = (client, group, key)
        hwm = self._read_hwm.get(rkey, 0)
        if w < hwm:
            self._violate(
                "read_monotone", t,
                f"client {client} read key {key!r} at applied index {w} "
                f"after already observing index {hwm} (stale-read "
                "inversion)",
                group=group, node=f"client:{client}", client=client,
                watermark=w, high_water=hwm,
            )
        else:
            self._read_hwm[rkey] = w

    # ------------------------------------------------------- queries
    def commit_digest(self, group: Optional[int] = None) -> str:
        """The committed-prefix CRC, reproduced from the auditor's own
        incremental records with the chaos runner's exact formula
        (``_SingleTorture.commit_digest``) — the cross-check that pins
        the auditor to the same log the offline checker judged. The
        cross-check contract is SINGLE-ENGINE (``group=None``; the
        attach hook aligns retention to the engine's archive); per-group
        digests are auditor-internal fingerprints — the multi runner's
        report digest uses a term-free formula they deliberately do not
        chase."""
        wm = self._commit_hwm.get(group, 0)
        crc = zlib.crc32(f"wm:{wm}".encode())
        led = self._ledgers.get(group)
        if wm and led is not None:
            for idx in range(led.covered_lo(wm), wm + 1):
                ent = led.get(idx)
                if ent is not None:
                    crc = zlib.crc32(
                        f"{idx}:{ent[0]}:{ent[1]:08x}".encode(), crc
                    )
        return f"{crc:08x}"

    def summary(self) -> dict:
        """Compact state for ``/status`` snapshots (cheap: counters plus
        a copy of the most recent violations)."""
        return {
            "violations_total": self.total_violations,
            "by_invariant": dict(self.by_invariant),
            "ticks_audited": self.ticks_audited,
            "recent": [v.to_jsonable() for v in self.violations[-5:]],
        }

    def to_jsonable(self) -> dict:
        """Full dump for forensics bundles."""
        return {
            "violations_total": self.total_violations,
            "violations_dropped": self.violations_dropped,
            "by_invariant": dict(self.by_invariant),
            "ticks_audited": self.ticks_audited,
            "commit_hwm": {
                str(g): wm for g, wm in sorted(
                    self._commit_hwm.items(), key=lambda kv: str(kv[0])
                )
            },
            "violations": [v.to_jsonable() for v in self.violations],
        }
