"""Metrics registry: counters / gauges / histograms with labels.

The third observability pillar: numeric signals the engines update on
their hot paths (guarded so a detached registry costs nothing), with
per-group labels for multi-Raft, a snapshot API consumed by
``obs.metrics.EngineReport``, Prometheus text exposition and a JSON
dump for forensics bundles. ``parse_prometheus`` closes the loop for
the exposition round-trip test.

Metric names follow Prometheus conventions (``raft_*_total`` counters,
``_seconds`` unit suffixes). The well-known engine metrics:

========================================  =======  =======================
name                                      type     labels
========================================  =======  =======================
raft_elections_total                      counter  group
raft_term_adoptions_total                 counter  group
raft_heartbeat_ticks_total                counter  group
raft_repair_rounds_total                  counter  group
raft_sheds_total                          counter  group, reason
raft_commits_total                        counter  group
raft_snapshot_installs_total              counter  group
raft_snapshot_chunks_total                counter  group
raft_segments_sealed_total                counter  group
raft_net_requests_total                   counter  kind
raft_net_bytes_total                      counter  dir
raft_net_refusals_total                   counter  reason
raft_net_pump_phase_seconds               histogram phase
raft_net_coalesce_batch                   histogram (none)
raft_net_frame_queue_age_seconds          histogram (none)
raft_commit_latency_seconds               histogram group
raft_queue_depth_high_water               gauge    group
raft_term                                 gauge    group
raft_host_mem_bytes                       gauge    root
========================================  =======  =======================

Determinism contract: pure host arithmetic, no rng, no device traffic.
"""

from __future__ import annotations

import math
import re
from typing import Dict, Iterable, List, Optional, Tuple

DEFAULT_BUCKETS = (
    0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 30.0, 60.0, 120.0, 300.0,
)


def _labelkey(labelnames: Tuple[str, ...], labels: dict) -> tuple:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"expected labels {labelnames}, got {tuple(labels)}"
        )
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...]):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)


class Counter(_Metric):
    typ = "counter"

    def __init__(self, name, help, labelnames):
        super().__init__(name, help, labelnames)
        self._values: Dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = _labelkey(self.labelnames, labels)
        self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(_labelkey(self.labelnames, labels), 0.0)

    def series(self) -> Iterable[Tuple[tuple, float]]:
        return self._values.items()


class Gauge(Counter):
    typ = "gauge"

    def set(self, value: float, **labels) -> None:
        self._values[_labelkey(self.labelnames, labels)] = float(value)

    def set_max(self, value: float, **labels) -> None:
        """High-water helper: keep the max of all observations."""
        k = _labelkey(self.labelnames, labels)
        self._values[k] = max(self._values.get(k, float("-inf")), value)


class Histogram(_Metric):
    typ = "histogram"

    def __init__(self, name, help, labelnames, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[tuple, List[int]] = {}   # per-bucket, non-cum.
        self._sum: Dict[tuple, float] = {}
        self._n: Dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        k = _labelkey(self.labelnames, labels)
        if k not in self._counts:
            self._counts[k] = [0] * (len(self.buckets) + 1)
        for i, b in enumerate(self.buckets):
            if value <= b:
                self._counts[k][i] += 1
                break
        else:
            self._counts[k][-1] += 1               # +Inf bucket
        self._sum[k] = self._sum.get(k, 0.0) + value
        self._n[k] = self._n.get(k, 0) + 1

    def summary(self, **labels) -> dict:
        k = _labelkey(self.labelnames, labels)
        return {
            "count": self._n.get(k, 0),
            "sum": self._sum.get(k, 0.0),
            "buckets": dict(zip(
                [str(b) for b in self.buckets] + ["+Inf"],
                self._counts.get(k, [0] * (len(self.buckets) + 1)),
            )),
        }

    def series(self) -> Iterable[tuple]:
        return self._n.keys()


class MetricsRegistry:
    """Named metric registry. ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent re-registration with the same shape), so
    engine layers can share one registry without coordination."""

    def __init__(self) -> None:
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, cls, name, help, labelnames, **kw):
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} re-registered with a different shape"
                )
            return m
        m = cls(name, help, tuple(labelnames), **kw)
        self._metrics[name] = m
        return m

    def counter(self, name, help="", labels=()) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def __contains__(self, name: str) -> bool:
        return name in self._metrics

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """JSON-safe dump: name -> {type, help, labels, series:[{labels,
        value|histogram}]} — the structure ``EngineReport.metrics``
        carries and forensics bundles embed."""
        out = {}
        for name, m in sorted(self._metrics.items()):
            series = []
            if isinstance(m, Histogram):
                for k in sorted(m.series()):
                    series.append({
                        "labels": dict(zip(m.labelnames, k)),
                        **m.summary(**dict(zip(m.labelnames, k))),
                    })
            else:
                for k, v in sorted(m.series()):
                    series.append({
                        "labels": dict(zip(m.labelnames, k)), "value": v,
                    })
            out[name] = {
                "type": m.typ, "help": m.help,
                "labels": list(m.labelnames), "series": series,
            }
        return out

    to_json = snapshot

    # ------------------------------------------------------- exposition
    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for name, m in sorted(self._metrics.items()):
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.typ}")
            if isinstance(m, Histogram):
                for k in sorted(m.series()):
                    base = dict(zip(m.labelnames, k))
                    s = m.summary(**base)
                    cum = 0
                    for b, c in s["buckets"].items():
                        cum += c
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels({**base, 'le': b})} {cum}"
                        )
                    lines.append(
                        f"{name}_sum{_fmt_labels(base)} {_fmt_num(s['sum'])}"
                    )
                    lines.append(
                        f"{name}_count{_fmt_labels(base)} {s['count']}"
                    )
            else:
                for k, v in sorted(m.series()):
                    lines.append(
                        f"{name}{_fmt_labels(dict(zip(m.labelnames, k)))} "
                        f"{_fmt_num(v)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt_num(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$"
)
_LABEL = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"')
_ESCAPED = re.compile(r"\\(.)")


def _unescape(v: str) -> str:
    # single pass, so a literal backslash followed by 'n' survives
    # (sequential str.replace would corrupt it — the round-trip contract)
    return _ESCAPED.sub(lambda m: {"n": "\n"}.get(m[1], m[1]), v)


def parse_prometheus(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """Parse exposition text back into ``name -> {sorted label items ->
    value}`` — the inverse half of the round-trip test. Comment and
    blank lines are skipped; histogram component samples parse as their
    ``_bucket``/``_sum``/``_count`` sample names."""
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        labels = {}
        if m["labels"]:
            for lm in _LABEL.finditer(m["labels"]):
                labels[lm["k"]] = _unescape(lm["v"])
        v = m["value"]
        value = math.inf if v == "+Inf" else (
            -math.inf if v == "-Inf" else float(v)
        )
        out.setdefault(m["name"], {})[tuple(sorted(labels.items()))] = value
    return out
