"""Causal op tracing: one span per client operation, Dapper-style.

A :class:`Span` is the lifetime of ONE client op — submit / linearizable
read — carrying a trace id that propagates through every layer it
crosses: ``Router._with_leader`` (retries, redials, breaker fast-fails),
the admission gate (refusal reasons), ``RaftEngine.submit`` /
``submit_read`` (queueing), ingest (queue delay), commit (replication
rounds) and apply. Each layer *annotates* the span; whoever observes the
op's outcome records exactly one terminal state.

Propagation model: the engines are single-threaded event loops, so the
ambient ``SpanTracker.current`` slot is the trace context — the caller
sets it around the client call (the in-process analogue of a trace-id
header) and the engine binds the span to its sequence number / read
ticket from there. After that the causal chain is keyed by seq → log
index → apply, no ambient state needed.

Cross-process propagation (the wire, docs/OBSERVABILITY.md "Wire
plane"): a span that crosses a process boundary carries ``wire_trace``
— the cross-process trace id minted by the CLIENT side
(``net.client.WireClient``) and propagated in every negotiated frame's
trace context — and, on the adopting (server) side, ``parent_span``,
the remote parent's span id. Joining the two sides' span tables on
``wire_trace`` reconstructs one causal timeline per op
(``obs.forensics.explain_joined``).

Sampling: ``sampled`` is the Dapper head-sampling bit — decided at the
root (``SpanTracker(sample_every=N)`` keeps every Nth trace;
default 1 = everything) and propagated in the wire context so both
sides agree. The TAIL policy overrides the head decision in
:meth:`Span.finish`: an op that ends in anything but ``ok``, or whose
duration exceeds the tracker's ``slow_s`` threshold, is ALWAYS sampled
— slow/refused/unknown-outcome ops never vanish into the sampling
noise, which is what makes a sampled span table forensically sound.

Terminal states:

- ``ok``      — outcome observed (write durable, read served).
- ``failed``  — refused with provably no effect (NotLeader, refused
  read, circuit open).
- ``shed``    — refused by admission (a ``failed`` specialized by cause).
- ``info``    — outcome unknown (crash window, client gave up).

Export: ``to_perfetto()`` emits Chrome/Perfetto trace JSON on the
VIRTUAL clock (virtual seconds scaled into the microsecond ``ts`` field
1:1), so a whole torture run loads into ``ui.perfetto.dev`` as a
timeline — spans as slices per client track, annotations as instants.

Determinism contract: same as the flight recorder — pure host
bookkeeping, no rng, no device traffic; a seeded run replays
byte-identically with the tracker attached or absent.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

TERMINAL_STATES = ("ok", "failed", "shed", "info")


@dataclasses.dataclass
class Span:
    trace_id: int
    op: str                          # "write" | "delete" | "read" | ...
    t_start: float
    client: Optional[object] = None
    key: Optional[bytes] = None
    group: Optional[int] = None
    state: str = "open"              # "open" -> one of TERMINAL_STATES
    t_end: Optional[float] = None
    seq: Optional[int] = None        # engine sequence number, once bound
    ticket: Optional[int] = None     # read ticket, once bound
    retries: int = 0                 # refusals retried (router/client)
    redials: int = 0                 # leadership redials (router)
    queue_delay_s: Optional[float] = None     # submit -> ingest
    replication_rounds: Optional[int] = None  # ingest -> commit, in ticks
    #   for reads, the rounds the serve paid END TO END: 0 = fully
    #   local (lease serve, session serve, follower serve certified by
    #   a valid lease), 1 = a dedicated ReadIndex confirmation round
    read_class: Optional[str] = None
    #   served read class (docs/READS.md matrix): "lease" |
    #   "read_index" | "follower" | "session"; None for writes and
    #   never-served reads
    wire_trace: Optional[int] = None
    #   cross-process trace id (client-minted, rides every negotiated
    #   wire frame) — the join key between the two sides' span tables
    parent_span: Optional[int] = None
    #   remote parent's span id (set on the ADOPTING side: the server
    #   span whose parent is the client op span)
    span_id: Optional[int] = None
    #   this span's WIRE-VISIBLE id, when it differs from the local
    #   trace_id: client roots use wire_trace; a server composes its
    #   listening port into the id so two servers' spans stay
    #   distinguishable in a joined timeline (port << 32 | local id)
    sampled: bool = True
    #   head-sampling decision (tail policy may flip it True in finish)
    slow_s: Optional[float] = None
    #   tail-sampling slowness threshold (copied from the tracker at
    #   begin; None = duration never forces sampling)
    refusal_reasons: List[str] = dataclasses.field(default_factory=list)
    annotations: List[Tuple[float, str, Dict[str, Any]]] = \
        dataclasses.field(default_factory=list)

    @property
    def terminal(self) -> bool:
        return self.state != "open"

    def annotate(self, name: str, t: float, **fields: Any) -> None:
        self.annotations.append((t, name, fields))

    def finish(self, state: str, t: Optional[float], **fields: Any) -> None:
        """Record the span's single terminal state. A second terminal
        transition is a harness bug (an op resolved twice) and raises —
        the contract holds for EVERY span population, engine-side and
        wire-client-side alike (tests/test_wire_trace.py pins the
        client paths). Tail sampling happens here: a non-``ok`` outcome
        or a duration past ``slow_s`` forces ``sampled`` True, whatever
        the head decision said."""
        if state not in TERMINAL_STATES:
            raise ValueError(f"not a terminal span state: {state!r}")
        if self.terminal:
            raise RuntimeError(
                f"span {self.trace_id} already terminal "
                f"({self.state!r}); second terminal {state!r}"
            )
        self.state = state
        self.t_end = t                # None = unbounded (info at give-up)
        if state != "ok":
            self.sampled = True       # tail policy: bad outcomes always
        elif (self.slow_s is not None and t is not None
                and t - self.t_start >= self.slow_s):
            self.sampled = True       # tail policy: slow ops always
        if fields:
            self.annotate(f"end:{state}", t if t is not None else
                          self.t_start, **fields)

    def to_jsonable(self) -> dict:
        d = dataclasses.asdict(self)
        if self.key is not None:
            d["key"] = self.key.decode("latin1")
        d["annotations"] = [
            [t, name, fields] for t, name, fields in self.annotations
        ]
        return d


class SpanTracker:
    """Mints, binds and collects spans for one engine stack.

    ``current`` is the ambient trace context (see module docstring); the
    ``note_*`` hooks are what the engine calls at each causal step — all
    tolerant of unbound ids, so instrumented engines keep working for
    callers that never open spans.

    ``sample_every=N`` head-samples every Nth span (deterministic
    counter, no rng — the determinism contract); ``slow_s`` arms the
    tail policy's slowness override (module docstring)."""

    def __init__(self, sample_every: int = 1,
                 slow_s: Optional[float] = None) -> None:
        self.spans: List[Span] = []
        self.current: Optional[Span] = None
        self.sample_every = max(1, int(sample_every))
        self.slow_s = slow_s
        self._next_id = 1
        self._begun = 0
        self._by_seq: Dict[int, Span] = {}
        self._by_idx: Dict[int, Span] = {}
        self._by_ticket: Dict[int, Span] = {}

    def begin(
        self,
        op: str,
        t: float,
        client: Optional[object] = None,
        key: Optional[bytes] = None,
        group: Optional[int] = None,
    ) -> Span:
        sp = Span(
            trace_id=self._next_id, op=op, t_start=t,
            client=client, key=key, group=group,
            sampled=(self._begun % self.sample_every == 0),
            slow_s=self.slow_s,
        )
        self._next_id += 1
        self._begun += 1
        self.spans.append(sp)
        return sp

    def adopt(self, sp: Span,
              ctx: Optional[Tuple[int, int, bool]]) -> Span:
        """Adopt a remote trace context onto ``sp`` (the server side of
        the wire join): the context's trace id becomes the join key,
        its span id the parent, and its sampling bit OVERRIDES the
        local head decision — the root decided (tail policy still
        applies at finish)."""
        if ctx is not None:
            sp.wire_trace, sp.parent_span, sp.sampled = ctx
        return sp

    # ------------------------------------------------ engine-side hooks
    def note_submit(self, seq: int, t: float) -> None:
        """``RaftEngine.submit`` minted ``seq`` for the current span."""
        sp = self.current
        if sp is None:
            return
        sp.seq = seq
        sp.annotate("queued", t, seq=seq)
        self._by_seq[seq] = sp

    def note_ingest(self, seq: int, idx: int, t: float, tick: int) -> None:
        """The leader tick moved ``seq`` from the host queue into the
        replicated log at ``idx``."""
        sp = self._by_seq.get(seq)
        if sp is None:
            return
        sp.queue_delay_s = t - sp.t_start
        sp.annotate("ingested", t, index=idx, tick=tick,
                    queue_delay_s=sp.queue_delay_s)
        sp._ingest_tick = tick          # type: ignore[attr-defined]
        self._by_idx[idx] = sp

    def note_commit(self, seq: int, t: float, tick: int) -> None:
        sp = self._by_seq.pop(seq, None)
        if sp is None:
            return
        t0 = getattr(sp, "_ingest_tick", None)
        sp.replication_rounds = (tick - t0) if t0 is not None else None
        sp.annotate("committed", t, rounds=sp.replication_rounds)

    def note_apply(self, idx: int, t: float) -> None:
        sp = self._by_idx.pop(idx, None)
        if sp is not None:
            sp.annotate("applied", t)

    def note_refusal(self, reason: str, t: float) -> None:
        """An admission gate / engine refusal hit the current span."""
        sp = self.current
        if sp is not None:
            sp.refusal_reasons.append(reason)
            sp.annotate("refused", t, reason=reason)

    def note_read_ticket(self, ticket: int, t: float) -> None:
        sp = self.current
        if sp is None:
            return
        sp.ticket = ticket
        sp.annotate("ticket", t, ticket=ticket)
        self._by_ticket[ticket] = sp

    def note_read_confirmed(self, ticket: int, idx: int, t: float,
                            cls: Optional[str] = None,
                            rounds: Optional[int] = None) -> None:
        sp = self._by_ticket.pop(ticket, None)
        if sp is not None:
            if cls is not None:
                sp.read_class = cls
            if rounds is not None:
                sp.replication_rounds = rounds
            sp.annotate("confirmed", t, read_index=idx, read_class=cls)

    def note_read_served(self, cls: str, t: float,
                         index: Optional[int] = None,
                         rounds: Optional[int] = None,
                         group: Optional[int] = None) -> None:
        """The current span's read was SERVED under class ``cls``
        (docs/READS.md): stamps the class and the replication rounds
        the read paid end to end — ``rounds=0`` is the span-verified
        zero-round contract (lease and session serves always; follower
        serves when their certification rode a valid lease)."""
        sp = self.current
        if sp is None:
            return
        sp.read_class = cls
        if rounds is not None:
            sp.replication_rounds = rounds
        sp.annotate("served", t, read_class=cls, index=index,
                    rounds=rounds, group=group)

    def note_read_refused(self, ticket: Optional[int], reason: str,
                          t: float) -> None:
        sp = (self._by_ticket.pop(ticket, None) if ticket is not None
              else self.current)
        if sp is not None:
            sp.refusal_reasons.append(reason)
            sp.annotate("refused", t, reason=reason)

    # -------------------------------------------------------- inspection
    def __len__(self) -> int:
        return len(self.spans)

    def open_spans(self) -> List[Span]:
        return [sp for sp in self.spans if not sp.terminal]

    def by_state(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for sp in self.spans:
            out[sp.state] = out.get(sp.state, 0) + 1
        return out

    def sampled_spans(self) -> List[Span]:
        """The spans the sampling policy kept: head-sampled plus every
        tail-promoted one (non-``ok`` terminal or slow — the capture a
        forensics bundle embeds when sampling is on)."""
        return [sp for sp in self.spans if sp.sampled]

    # ------------------------------------------------------------ export
    def to_jsonable(self, sampled_only: bool = False) -> dict:
        spans = self.sampled_spans() if sampled_only else self.spans
        return {"spans": [sp.to_jsonable() for sp in spans]}

    def to_perfetto(self) -> dict:
        """Chrome/Perfetto trace JSON on the virtual clock: pid = raft
        group (0 for single-group), tid = client id; spans are ``X``
        slices, annotations ``i`` instants. ``ts`` is microseconds, so
        virtual seconds are scaled 1e6 and a 300-virtual-second run
        spans a readable 5-minute timeline."""
        evs: List[dict] = []
        pids = set()
        for sp in self.spans:
            pid = sp.group if sp.group is not None else 0
            tid = sp.client if isinstance(sp.client, int) else 0
            pids.add(pid)
            t_end = sp.t_end if sp.t_end is not None else sp.t_start
            name = sp.op
            if sp.key is not None:
                name = f"{sp.op} {sp.key.decode('latin1')}"
            evs.append({
                "name": name, "cat": "op", "ph": "X",
                "ts": sp.t_start * 1e6,
                "dur": max((t_end - sp.t_start) * 1e6, 1.0),
                "pid": pid, "tid": tid,
                "args": {
                    "trace_id": sp.trace_id, "state": sp.state,
                    "seq": sp.seq, "retries": sp.retries,
                    "redials": sp.redials,
                    "queue_delay_s": sp.queue_delay_s,
                    "replication_rounds": sp.replication_rounds,
                    "read_class": sp.read_class,
                    "refusals": sp.refusal_reasons,
                    "wire_trace": sp.wire_trace,
                    "parent_span": sp.parent_span,
                },
            })
            for t, aname, fields in sp.annotations:
                evs.append({
                    "name": aname, "cat": "annotation", "ph": "i",
                    "ts": t * 1e6, "pid": pid, "tid": tid, "s": "t",
                    "args": dict(fields, trace_id=sp.trace_id),
                })
        for pid in sorted(pids):
            evs.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": f"raft group {pid}"},
            })
        return {"traceEvents": evs, "displayTimeUnit": "ms"}


def spans_from_jsonable(d: dict) -> List[Span]:
    """Rehydrate spans from a forensics bundle (keys back to bytes)."""
    out = []
    for sd in d.get("spans", []):
        sd = dict(sd)
        if sd.get("key") is not None:
            sd["key"] = sd["key"].encode("latin1")
        sd["annotations"] = [
            (t, name, fields) for t, name, fields in sd["annotations"]
        ]
        out.append(Span(**sd))
    return out
