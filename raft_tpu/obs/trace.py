"""Trace capture in the reference's nodelog schema.

Format (main.go:399-401): ``[Id:Term:CommitIndex:LastApplied][state]msg``.
Both the golden model and the engine emit it through their ``trace``
callbacks; a ``TraceRecorder`` is that callback plus parsing/filtering for
assertions (e.g. Election Safety: at most one leader transition per term).

Multi-Raft runs (``raft_tpu.multi``) tag the id field with the consensus
group — ``g3/Server0`` — which parses as an ordinary node id here;
``TraceRecord.group`` recovers the scope so per-group assertions (e.g.
Election Safety per group) filter without string surgery.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Iterator, List, Optional

_LINE = re.compile(
    r"^\[(?P<id>[^:\]]+):(?P<term>-?\d+):(?P<commit>-?\d+):(?P<last>-?\d+)\]"
    r"\[(?P<state>[a-z]+)\](?P<msg>.*)$"
)


@dataclasses.dataclass(frozen=True)
class TraceRecord:
    node: str
    term: int
    commit_index: int
    last_index: int
    state: str
    message: str

    @classmethod
    def parse(cls, line: str) -> "TraceRecord":
        m = _LINE.match(line)
        if not m:
            raise ValueError(f"not a nodelog line: {line!r}")
        return cls(
            node=m["id"],
            term=int(m["term"]),
            commit_index=int(m["commit"]),
            last_index=int(m["last"]),
            state=m["state"],
            message=m["msg"],
        )

    @property
    def group(self) -> Optional[int]:
        """Raft-group scope of a multi-Raft nodelog line (``gN/ServerR``
        ids, ``multi.MultiEngine.nodelog``); None for single-group
        lines."""
        m = re.match(r"^g(\d+)/", self.node)
        return int(m.group(1)) if m else None


class TraceRecorder:
    """Callable sink for nodelog lines with query helpers."""

    def __init__(self) -> None:
        self.lines: List[str] = []

    def __call__(self, line: str) -> None:
        self.lines.append(line)

    def __len__(self) -> int:
        return len(self.lines)

    def records(self) -> Iterator[TraceRecord]:
        return (TraceRecord.parse(line) for line in self.lines)

    def matching(self, substring: str) -> List[TraceRecord]:
        return [r for r in self.records() if substring in r.message]

    def leaders_by_term(self) -> dict[int, set]:
        """term -> nodes that logged a leader transition in that term. The
        Election Safety assertion is: every value set has size <= 1."""
        out: dict[int, set] = {}
        for r in self.matching("state changed to leader"):
            out.setdefault(r.term, set()).add(r.node)
        return out
