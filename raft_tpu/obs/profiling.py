"""Device-time measurement via ``jax.profiler`` traces.

The SURVEY §5 tracing row: kernel/collective device time, not host wall
clock. On this rig the distinction is load-bearing — dispatch crosses a
network tunnel whose RTT (~20-100 ms) and ``block_until_ready`` semantics
make wall-clock timing of ~10 us device programs pure noise (bench.py's
round-1 number measured the tunnel, not the kernel). A profiler trace
records the on-device execution span of each compiled module, which is
exact regardless of dispatch latency.

``device_seconds`` runs one call under a trace and returns the device-side
duration of the longest compiled module in it (for a bench body that is
one ``jit`` scan, that IS the program). ``op_breakdown`` aggregates
per-op device durations from the same trace for kernel-level attribution.
"""

from __future__ import annotations

import glob
import gzip
import json
import shutil
import tempfile
from typing import Callable, Optional

import jax
import numpy as np


def _load_latest_trace(trace_dir: str):
    runs = sorted(
        glob.glob(f"{trace_dir}/plugins/profile/*/*.trace.json.gz")
    )
    if not runs:
        return []
    return json.load(gzip.open(runs[-1])).get("traceEvents", [])


def _device_pids(evs) -> set:
    return {
        e["pid"] for e in evs
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and "TPU" in str(e.get("args", {}).get("name", ""))
    }


def device_seconds(
    fn: Callable, mk_args: Callable[[], tuple], warmups: int = 1,
    trace_dir: Optional[str] = None,
) -> float:
    """On-device seconds of one ``fn(*mk_args())`` call; NaN if the platform
    produced no device trace (caller falls back to wall clock).

    ``mk_args`` is a factory so donated buffers are fresh per call. The
    result is forced to host (``np.asarray``) before the trace stops —
    ``block_until_ready`` does not guarantee completion through the tunnel.
    """
    for _ in range(warmups):
        out = fn(*mk_args())
    _ = np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    tmp = trace_dir or tempfile.mkdtemp(prefix="raft_tpu_trace_")
    try:
        jax.profiler.start_trace(tmp)
        try:
            out = fn(*mk_args())
            _ = np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
        finally:
            # always close the profiler session — a leaked session makes
            # every later start_trace fail and would poison all remaining
            # measurements, not just this one
            jax.profiler.stop_trace()
        evs = _load_latest_trace(tmp)
        pids = _device_pids(evs)
        mods = [
            float(e["dur"]) for e in evs
            if e.get("ph") == "X" and e.get("pid") in pids
            and str(e.get("name", "")).startswith("jit_")
        ]
        return max(mods) / 1e6 if mods else float("nan")
    except Exception:
        return float("nan")
    finally:
        if trace_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)


def op_breakdown(trace_dir: str, top: int = 20):
    """[(op_name, calls, total_ms)] for the latest trace in ``trace_dir``."""
    evs = _load_latest_trace(trace_dir)
    pids = _device_pids(evs)
    agg = {}
    for e in evs:
        if e.get("ph") == "X" and e.get("pid") in pids:
            nm = str(e.get("name", ""))
            if nm.startswith("jit_"):
                continue
            c, t = agg.get(nm, (0, 0.0))
            agg[nm] = (c + 1, t + float(e.get("dur", 0)))
    return [
        (nm, c, t / 1e3)
        for nm, (c, t) in sorted(agg.items(), key=lambda kv: -kv[1][1])[:top]
    ]
