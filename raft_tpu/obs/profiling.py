"""On-demand ``jax.profiler`` capture + bench device-time measurement.

The SURVEY §5 tracing row: kernel/collective device time, not host wall
clock. On this rig the distinction is load-bearing — dispatch crosses a
network tunnel whose RTT (~20-100 ms) and ``block_until_ready`` semantics
make wall-clock timing of ~10 us device programs pure noise (bench.py's
round-1 number measured the tunnel, not the kernel). A profiler trace
records the on-device execution span of each compiled module, which is
exact regardless of dispatch latency.

Bench helpers (the original bench-only role): ``device_seconds`` runs
one call under a trace and returns the device-side duration of the
longest compiled module in it (for a bench body that is one ``jit``
scan, that IS the program). ``op_breakdown`` aggregates per-op device
durations from the same trace for kernel-level attribution.

On-demand capture (the compile-&-memory-plane promotion):

- :func:`launch_annotation` — a ``jax.profiler.StepTraceAnnotation``
  the engines wrap around each launch boundary (the fused window, the
  per-tick replicate, the batched group launch) so a capture segments
  by launch. It is a nullcontext unless a capture is ACTIVE — the
  detached cost is one module-bool test per launch, no device traffic.
- :func:`capture_profile` — capture ``seconds`` of wall time while the
  engine keeps running (the OpsServer ``/profile?seconds=N`` endpoint),
  then merge the device trace with the span tracker's Perfetto export
  (``obs.spans.SpanTracker.to_perfetto``) into ONE timeline artifact —
  client-op spans and device kernels in the same ui.perfetto.dev view.
  Destination: explicit argument, else ``RAFT_TPU_PROFILE_DIR``, else a
  temp dir (the same resolution ladder as ``RAFT_TPU_BUNDLE_DIR``).

Captures are serialized process-wide (``jax.profiler`` allows one
session); a concurrent request raises :class:`CaptureBusy`.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Callable, Optional

import jax
import numpy as np

PROFILE_FORMAT = "raft_tpu.obs/profile.v1"

#: span-track pids are offset past any plausible device-trace pid so
#: the two timelines never collide in the merged artifact
SPAN_PID_OFFSET = 900_000


def resolve_profile_dir(profile_dir: Optional[str]) -> Optional[str]:
    """Destination policy: explicit argument, else the
    ``RAFT_TPU_PROFILE_DIR`` environment variable, else None (the
    caller falls back to a temp dir)."""
    if profile_dir is not None:
        return profile_dir
    return os.environ.get("RAFT_TPU_PROFILE_DIR") or None


# ----------------------------------------------------- launch annotations
_capture_active = False
_capture_lock = threading.Lock()
#: shared detached context: nullcontext is stateless and reentrant, so
#: the per-launch detached cost stays one module-bool test + one return
#: (no allocation on the hot dispatch path)
_NULL = contextlib.nullcontext()


class CaptureBusy(RuntimeError):
    """A profiler capture is already in flight (one session allowed)."""


def capture_active() -> bool:
    return _capture_active


def launch_annotation(name: str, step: int):
    """A ``StepTraceAnnotation`` while a capture is active, else the
    shared detached nullcontext (see module docstring)."""
    if not _capture_active:
        return _NULL
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


# ------------------------------------------------------ on-demand capture
def merge_timelines(device_events: list, span_trace: Optional[dict]) -> dict:
    """One Chrome/Perfetto artifact from a device trace and the span
    tracker's export. Span tracks are pid-offset (SPAN_PID_OFFSET) so
    both families keep their own process rows; the device trace rides
    its real (wall-clock) timebase and the span tracks their virtual
    clock — the artifact labels both so a reader isn't misled."""
    evs = list(device_events)
    n_span = 0
    if span_trace:
        for e in span_trace.get("traceEvents", []):
            e = dict(e)
            if "pid" in e:
                e["pid"] = e["pid"] + SPAN_PID_OFFSET
            if e.get("ph") == "M" and e.get("name") == "process_name":
                nm = e.get("args", {}).get("name", "")
                e["args"] = {"name": f"{nm} (virtual clock)"}
            evs.append(e)
            n_span += 1
    return {
        "format": PROFILE_FORMAT,
        "displayTimeUnit": "ms",
        "traceEvents": evs,
        "n_device_events": len(device_events),
        "n_span_events": n_span,
    }


def capture_profile(
    seconds: float,
    spans=None,
    profile_dir: Optional[str] = None,
    sleep: Callable[[float], None] = time.sleep,
    keep_python_frames: bool = False,
) -> dict:
    """Capture ``seconds`` of profiler trace while the engine threads
    keep running, merge with the span export, write the artifact, and
    return ``{"artifact", "raw_dir", "seconds", "n_device_events",
    "n_span_events"}``. Raises :class:`CaptureBusy` when a capture is
    already in flight.

    The merged artifact keeps the kernel/runtime/annotation events and
    drops the host Python-frame events (names starting with ``$`` —
    hundreds of thousands per busy second on the CPU tracer, drowning
    the timeline); ``keep_python_frames=True`` keeps everything, and
    with a configured destination the raw xplane dump is preserved
    next to the artifact either way."""
    global _capture_active
    if not _capture_lock.acquire(blocking=False):
        raise CaptureBusy("a profiler capture is already in flight")
    base = resolve_profile_dir(profile_dir)
    cleanup_raw = False
    try:
        if base is None:
            base = tempfile.mkdtemp(prefix="raft_tpu_profile_")
        os.makedirs(base, exist_ok=True)
        raw = tempfile.mkdtemp(prefix="raw_", dir=base)
        cleanup_raw = True
        jax.profiler.start_trace(raw)
        _capture_active = True
        try:
            sleep(max(seconds, 0.0))
        finally:
            _capture_active = False
            # always close the session — a leaked session poisons every
            # later start_trace (same contract as device_seconds)
            jax.profiler.stop_trace()
        device_events = _load_latest_trace(raw)
        if not keep_python_frames:
            device_events = [
                e for e in device_events
                if not str(e.get("name", "")).startswith("$")
            ]
        merged = merge_timelines(
            device_events,
            spans.to_perfetto() if spans is not None else None,
        )
        stamp = time.strftime("%Y%m%d_%H%M%S")
        path = os.path.join(base, f"profile_{stamp}.json")
        with open(path, "w") as fh:
            json.dump(merged, fh, separators=(",", ":"))
        keep_raw = resolve_profile_dir(profile_dir) is not None
        return {
            "artifact": path,
            # the raw xplane dump survives only with a configured
            # destination; on the temp fallback it is deleted below —
            # never advertise a path that is about to vanish
            "raw_dir": raw if keep_raw else None,
            "seconds": seconds,
            "n_device_events": merged["n_device_events"],
            "n_span_events": merged["n_span_events"],
        }
    finally:
        if cleanup_raw and resolve_profile_dir(profile_dir) is None:
            # an env/arg destination keeps the raw xplane dump for
            # tensorboard; the temp fallback keeps only the artifact
            shutil.rmtree(raw, ignore_errors=True)
        _capture_lock.release()


def _load_latest_trace(trace_dir: str):
    runs = sorted(
        glob.glob(f"{trace_dir}/plugins/profile/*/*.trace.json.gz")
    )
    if not runs:
        return []
    return json.load(gzip.open(runs[-1])).get("traceEvents", [])


def _device_pids(evs) -> set:
    return {
        e["pid"] for e in evs
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and "TPU" in str(e.get("args", {}).get("name", ""))
    }


def device_seconds(
    fn: Callable, mk_args: Callable[[], tuple], warmups: int = 1,
    trace_dir: Optional[str] = None,
) -> float:
    """On-device seconds of one ``fn(*mk_args())`` call; NaN if the platform
    produced no device trace (caller falls back to wall clock).

    ``mk_args`` is a factory so donated buffers are fresh per call. The
    result is forced to host (``np.asarray``) before the trace stops —
    ``block_until_ready`` does not guarantee completion through the tunnel.
    """
    for _ in range(warmups):
        out = fn(*mk_args())
    _ = np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    tmp = trace_dir or tempfile.mkdtemp(prefix="raft_tpu_trace_")
    try:
        jax.profiler.start_trace(tmp)
        try:
            out = fn(*mk_args())
            _ = np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
        finally:
            # always close the profiler session — a leaked session makes
            # every later start_trace fail and would poison all remaining
            # measurements, not just this one
            jax.profiler.stop_trace()
        evs = _load_latest_trace(tmp)
        pids = _device_pids(evs)
        mods = [
            float(e["dur"]) for e in evs
            if e.get("ph") == "X" and e.get("pid") in pids
            and str(e.get("name", "")).startswith("jit_")
        ]
        return max(mods) / 1e6 if mods else float("nan")
    except Exception:
        return float("nan")
    finally:
        if trace_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)


def op_breakdown(trace_dir: str, top: int = 20):
    """[(op_name, calls, total_ms)] for the latest trace in ``trace_dir``."""
    evs = _load_latest_trace(trace_dir)
    pids = _device_pids(evs)
    agg = {}
    for e in evs:
        if e.get("ph") == "X" and e.get("pid") in pids:
            nm = str(e.get("name", ""))
            if nm.startswith("jit_"):
                continue
            c, t = agg.get(nm, (0, 0.0))
            agg[nm] = (c + 1, t + float(e.get("dur", 0)))
    return [
        (nm, c, t / 1e3)
        for nm, (c, t) in sorted(agg.items(), key=lambda kv: -kv[1][1])[:top]
    ]
