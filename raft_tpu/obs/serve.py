"""Live ops surface: a lock-free status board + a stdlib HTTP endpoint.

The metrics registry, SLO tracker and safety auditor are all host-side
state mutated by the single engine thread. This module makes them
scrapeable while the engine runs, without locks on the hot path:

- :class:`StatusBoard` — the engine publishes an IMMUTABLE snapshot
  dict at each flush boundary (one attribute assignment — atomic under
  the GIL, so the server thread always reads a complete snapshot,
  never a half-mutated engine). Publishing costs a small dict build
  from host mirrors the engine already maintains: zero device syncs,
  determinism-neutral, and a ``None`` check is the only cost when no
  board is attached.
- :class:`OpsServer` — ``http.server`` over an ephemeral (or fixed)
  port, serving:

  ==========  ==========================================================
  endpoint    body
  ==========  ==========================================================
  /metrics    Prometheus text exposition of the attached registry
  /healthz    ``{"status": "ok", ...}`` liveness (always 200 once bound)
  /slo        the SLO tracker's snapshot (objectives, digests, burn
              rates, active + recent alerts) as JSON
  /status     the board's composed snapshot: leader map, per-group
              term/commit/applied watermarks, replication lag, queue
              depths, audit summary, breaker state — plus ``compile``
              and ``memory`` summary sections when those planes are
              attached, ``tiered``/``catchup`` sections (seal
              tallies, RS reconstructs, live snapshot-chunk streams)
              when the tiered log store is configured, and a ``net``
              section (connections, draining, in-flight frames,
              bytes in/out, per-reason wire refusals, staged-ingest
              split — plus a ``pump`` block with per-phase
              µs/iteration, attribution coverage and the
              coalesce-batch / frame-queue-age percentiles when a
              ``PumpProfiler`` is attached) when a
              ``raft_tpu.net.IngestServer`` publishes to the same
              board — JSON
  /compile    the CompileWatch snapshot (per-program trace/compile
              tallies, event log, sentinel freeze state + violations)
  /memory     the MemoryWatch snapshot with a FRESH live-buffer census
              (metadata-only: no device sync)
  /profile    ``?seconds=N`` (default 1, clamped to [0.05, 30]):
              capture a ``jax.profiler`` trace for N wall seconds while
              the engine keeps running, merge it with the span
              tracker's Perfetto export, write one timeline artifact
              (``RAFT_TPU_PROFILE_DIR`` or a temp dir) and return its
              path; 409 when a capture is already in flight
  ==========  ==========================================================

Thread-safety contract: ``/status`` and ``/healthz`` serve from
published immutable snapshots only. ``/metrics``, ``/slo`` and the
``/status`` audit fallback render live single-writer state (per-sample
values are plain in-place updates); the one racy case — a container
growing mid-render (new metric/label/digest key) — is retried a few
times scrape-side, which is the standard answer for a pull endpoint.

``python -m raft_tpu.obs --serve`` boots a demo MultiEngine with the
full online plane attached and serves these endpoints while driving
traffic (docs/OBSERVABILITY.md "Online plane").
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


class StatusBoard:
    """Single-writer, many-reader snapshot rendezvous. Sections are
    independent publishers (the engine's ``"engine"`` section, a
    Router's ``"breakers"``): each ``publish`` swaps that section's
    snapshot reference; ``compose`` merges current references into one
    dict without touching any publisher's internals."""

    def __init__(self) -> None:
        self._sections: dict = {}
        self.generation = 0

    def publish(self, snapshot: dict, section: str = "engine") -> None:
        """Swap in ``snapshot`` (treated as immutable from here on)."""
        # rebuild the section dict instead of mutating it: readers hold
        # the OLD composed dict, which must stay internally consistent
        sections = dict(self._sections)
        sections[section] = snapshot
        self._sections = sections
        self.generation += 1

    def compose(self) -> dict:
        sections = self._sections       # one read: a consistent set
        out = dict(sections.get("engine", {}))
        for name, snap in sections.items():
            if name != "engine":
                out[name] = snap
        out["board_generation"] = self.generation
        return out


class OpsServer:
    """The ops endpoint (module docstring). ``port=0`` binds an
    ephemeral port (read ``.port`` after ``start()``)."""

    def __init__(
        self,
        board: Optional[StatusBoard] = None,
        registry=None,
        slo=None,
        auditor=None,
        compile_watch=None,
        memory=None,
        spans=None,
        profile_dir: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self.board = board
        self.registry = registry
        self.slo = slo
        self.auditor = auditor
        self.compile_watch = compile_watch
        self.memory = memory
        self.spans = spans
        self.profile_dir = profile_dir
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ serve
    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        ops = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet by default
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "application/json") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            @staticmethod
            def _render_live(fn):
                """Render live single-writer state with scrape-side
                retries: a dict growing mid-iteration (new metric /
                digest key / active alert) raises RuntimeError — retry
                against the fresh state instead of 500ing the scrape."""
                for attempt in range(3):
                    try:
                        return fn()
                    except RuntimeError:
                        if attempt == 2:
                            raise

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    if ops.registry is None:
                        self._send(404, json.dumps(
                            {"error": "no metrics registry attached"}))
                        return
                    text = self._render_live(ops.registry.to_prometheus)
                    self._send(
                        200, text,
                        ctype="text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/healthz":
                    snap = ops.board.compose() if ops.board else {}
                    self._send(200, json.dumps({
                        "status": "ok" if snap else "no-engine",
                        "t_virtual": snap.get("t_virtual"),
                        "generation": snap.get("board_generation", 0),
                    }))
                elif path == "/slo":
                    if ops.slo is None:
                        self._send(404, json.dumps(
                            {"error": "no SLO tracker attached"}))
                        return
                    body = self._render_live(
                        lambda: json.dumps(ops.slo.snapshot())
                    )
                    self._send(200, body)
                elif path == "/status":
                    if ops.board is None:
                        self._send(404, json.dumps(
                            {"error": "no status board attached"}))
                        return
                    def _compose():
                        snap = ops.board.compose()
                        if (ops.auditor is not None
                                and "audit" not in snap):
                            snap["audit"] = ops.auditor.summary()
                        if (ops.compile_watch is not None
                                and "compile" not in snap):
                            snap["compile"] = ops.compile_watch.summary()
                        if (ops.memory is not None
                                and "memory" not in snap):
                            snap["memory"] = ops.memory.summary()
                        return json.dumps(snap)
                    self._send(200, self._render_live(_compose))
                elif path == "/compile":
                    if ops.compile_watch is None:
                        self._send(404, json.dumps(
                            {"error": "no compile watch attached"}))
                        return
                    body = self._render_live(
                        lambda: json.dumps(ops.compile_watch.snapshot())
                    )
                    self._send(200, body)
                elif path == "/memory":
                    if ops.memory is None:
                        self._send(404, json.dumps(
                            {"error": "no memory watch attached"}))
                        return
                    body = self._render_live(
                        lambda: json.dumps(
                            ops.memory.snapshot(census=True))
                    )
                    self._send(200, body)
                elif path == "/profile":
                    from raft_tpu.obs import profiling

                    import math

                    try:
                        seconds = float(
                            parse_qs(
                                urlparse(self.path).query
                            ).get("seconds", ["1"])[0]
                        )
                    except ValueError:
                        seconds = float("nan")
                    if not math.isfinite(seconds):
                        self._send(400, json.dumps(
                            {"error": "seconds must be a finite number"}))
                        return
                    seconds = min(max(seconds, 0.05), 30.0)
                    try:
                        result = profiling.capture_profile(
                            seconds, spans=ops.spans,
                            profile_dir=ops.profile_dir,
                        )
                    except profiling.CaptureBusy as ex:
                        self._send(409, json.dumps({"error": str(ex)}))
                        return
                    self._send(200, json.dumps(result))
                else:
                    self._send(404, json.dumps({
                        "error": f"unknown path {path!r}",
                        "endpoints": ["/metrics", "/healthz", "/slo",
                                      "/status", "/compile", "/memory",
                                      "/profile"],
                    }))

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="raft-tpu-ops-server",
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "OpsServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def serve_demo(
    port: int = 0,
    groups: int = 4,
    duration_s: Optional[float] = None,
    out=None,
) -> dict:
    """``python -m raft_tpu.obs --serve``: boot a demo ``MultiEngine``
    with the full online plane attached (registry, SLO tracker with a
    commit objective, safety auditor, status board), drive synthetic
    traffic, and serve the ops endpoints until ``duration_s`` wall
    seconds elapse (or forever on Ctrl-C when ``None``). Returns a
    small result dict (the smoke test's hook)."""
    import time as _time

    from raft_tpu.config import RaftConfig
    from raft_tpu.multi.engine import MultiEngine
    from raft_tpu.obs.audit import SafetyAuditor
    from raft_tpu.obs.compile import CompileWatch, RetraceSentinel
    from raft_tpu.obs.events import FlightRecorder
    from raft_tpu.obs.memory import MemoryWatch
    from raft_tpu.obs.registry import MetricsRegistry
    from raft_tpu.obs.slo import SLObjective, SloTracker

    cfg = RaftConfig(
        n_replicas=3, entry_bytes=64, batch_size=8, log_capacity=256,
        transport="single",
    )
    eng = MultiEngine(cfg, groups, recorder=FlightRecorder())
    eng.metrics = MetricsRegistry()
    eng.auditor = SafetyAuditor(
        recorder=eng.recorder, registry=eng.metrics,
        max_entries=2 * cfg.log_capacity,
    )
    eng.slo = SloTracker(
        objectives=(
            SLObjective("commit_fast", "commit",
                        threshold_s=2 * cfg.heartbeat_period),
        ),
        recorder=eng.recorder, registry=eng.metrics,
    )
    board = StatusBoard()
    eng.status_board = board
    watch = CompileWatch(
        recorder=eng.recorder, registry=eng.metrics
    ).install()
    RetraceSentinel(watch)
    memory = MemoryWatch(registry=eng.metrics, recorder=eng.recorder)
    memory.watch_engine(eng, name="multi")
    eng.seed_leaders()
    server = OpsServer(
        board=board, registry=eng.metrics, slo=eng.slo,
        auditor=eng.auditor, compile_watch=watch, memory=memory,
        port=port,
    )
    bound = server.start()
    line = (f"raft_tpu ops endpoint on http://127.0.0.1:{bound} "
            "(/metrics /healthz /slo /status /compile /memory "
            "/profile); Ctrl-C to stop")
    print(line, file=out, flush=True)
    t0 = _time.monotonic()
    submitted = 0
    try:
        while duration_s is None or _time.monotonic() - t0 < duration_s:
            for g in range(groups):
                if eng.leader_id[g] is None:
                    continue
                for i in range(cfg.batch_size):
                    payload = (f"g{g}op{submitted}".encode()
                               .ljust(cfg.entry_bytes, b"\0"))
                    eng.submit(g, payload[:cfg.entry_bytes])
                    submitted += 1
            eng.run_for(2 * cfg.heartbeat_period)
            if watch.sentinel is not None and not watch.sentinel.frozen:
                # warmup over: the demo's program set is built after the
                # first driven window — freeze so /compile shows the
                # sentinel armed
                watch.sentinel.freeze()
            memory.census()
            _time.sleep(0.02)        # pace the virtual cluster for wall
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        watch.uninstall()
    return {
        "port": bound,
        "submitted": submitted,
        "committed": int(eng.commit_watermark.sum()),
        "violations": eng.auditor.total_violations,
        "compiles": watch.total_compiles,
        "compile_violations": len(watch.sentinel.violations),
    }
