"""The memory plane: device-buffer census, leak detection, donation
audit.

The fused steady state donates its state pytree (and event ring) into
every launch, chaos crash-restore rebuilds engines per cycle, and
``migrate_group`` permutes whole device slots — any of which could leak
buffers silently at G=1024 scale. Nothing measured device memory until
this module:

- :meth:`MemoryWatch.census` walks ``jax.live_arrays()`` — metadata
  only: shapes, dtypes, nbytes; NO device sync, NO data transfer — and
  buckets every live buffer. Buffers identity-matched to a registered
  root's pytree leaves (:meth:`register_root` /
  :meth:`watch_engine`) bucket under their state-leaf label
  (``engine.state.payload``); the rest bucket by ``dtype[shape]``.
- **Leak detector**: :meth:`set_baseline` pins the steady-state census;
  :meth:`drift` / :meth:`assert_flat` compare a later census
  bucket-by-bucket — the chaos pins assert the census returns to
  baseline across crash-restore cycles and ``migrate_group`` moves.
- **High-water gauges**: every census updates
  ``raft_device_mem_bytes`` / ``raft_device_mem_bytes_high_water`` /
  ``raft_device_arrays`` (per-root bytes ride
  ``raft_device_state_bytes{root}``).
- :func:`audit_donation` proves donated buffers are NOT silently
  copied: it runs one donated call and checks the donated operands'
  leaves are actually deleted (``Array.is_deleted``). On a backend
  that ignores donation the report says so honestly
  (``honored=False``) instead of passing vacuously.

Determinism contract: census taking is pure host metadata walking — a
seeded run replays byte-identically with the plane attached or absent
(pinned with the compile plane's chaos identity test).
"""

from __future__ import annotations

import dataclasses
import gc
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple


def _leaf_labels(name: str, tree: Any) -> Dict[int, str]:
    """id(leaf array) -> "name.path" for a registered root pytree."""
    import jax

    out: Dict[int, str] = {}
    try:
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    except Exception:
        return out
    for path, leaf in leaves:
        if hasattr(leaf, "nbytes") and hasattr(leaf, "shape"):
            key = "".join(str(p) for p in path)
            out[id(leaf)] = f"{name}{key}"
    return out


@dataclasses.dataclass
class MemoryCensus:
    """One point-in-time live-buffer census. ``by_shape`` covers every
    live buffer; ``unattr_by_shape`` only those NOT reachable from a
    registered root — the population the leak detector watches (a
    leaked old engine generation, an orphaned staging buffer, a
    silently-copied donated state all land there, while a live root's
    fixed-structure pytree cannot grow without bound)."""

    total_bytes: int
    n_arrays: int
    by_label: Dict[str, Tuple[int, int]]    # label -> (count, bytes)
    by_shape: Dict[str, Tuple[int, int]]    # dtype[shape] -> (count, bytes)
    unattr_by_shape: Dict[str, Tuple[int, int]]
    attributed_bytes: int
    host_by_label: Dict[str, int] = dataclasses.field(default_factory=dict)
    #   HOST-side buffers a registered host root accounts for (label ->
    #   bytes): sealed-segment hot tails and decoded-segment caches
    #   (``ckpt.tiered``) live in numpy/bytes, invisible to
    #   ``jax.live_arrays()`` — without this section the tiered store's
    #   RAM would be exactly the unattributed growth the leak detector
    #   exists to flag, reported by nothing.

    @property
    def unattributed_bytes(self) -> int:
        return self.total_bytes - self.attributed_bytes

    def to_jsonable(self) -> dict:
        return {
            "total_bytes": self.total_bytes,
            "n_arrays": self.n_arrays,
            "attributed_bytes": self.attributed_bytes,
            "unattributed_bytes": self.unattributed_bytes,
            "host_by_label": dict(sorted(self.host_by_label.items())),
            "by_label": {
                k: {"count": c, "bytes": b}
                for k, (c, b) in sorted(self.by_label.items())
            },
            "by_shape": {
                k: {"count": c, "bytes": b}
                for k, (c, b) in sorted(self.by_shape.items())
            },
            "unattr_by_shape": {
                k: {"count": c, "bytes": b}
                for k, (c, b) in sorted(self.unattr_by_shape.items())
            },
        }


class MemoryWatch:
    """Device-memory accounting for one run (module docstring)."""

    def __init__(self, registry=None, recorder=None) -> None:
        self.registry = registry
        self.recorder = recorder
        self._roots: Dict[str, Callable[[], Any]] = {}
        self._host_roots: Dict[str, Callable[[], Optional[int]]] = {}
        self.baseline: Optional[MemoryCensus] = None
        self.last: Optional[MemoryCensus] = None
        self.high_water_bytes = 0
        self.high_water_arrays = 0
        self.donation: Optional["DonationReport"] = None
        #: the chaos runner's end-of-run flatness verdict (drift()
        #: taken at quiesce, while the final engine is still alive)
        self.final_drift: Optional[List[str]] = None

    # ------------------------------------------------------------- roots
    def register_root(self, name: str,
                      getter: Callable[[], Any]) -> None:
        """Label the leaves of ``getter()``'s pytree in every census.
        ``getter`` returning ``None`` skips the root (a crashed
        engine)."""
        self._roots[name] = getter

    def register_host_root(self, name: str,
                           nbytes: Callable[[], Optional[int]]) -> None:
        """Account a HOST-side buffer population under ``name``:
        ``nbytes()`` returns the bytes it currently holds (None skips —
        a collected engine). Host roots appear in the census's
        ``host_by_label`` section and the ``raft_host_mem_bytes`` gauge
        — the tiered store's sealed-segment buffers land here as a
        labeled root instead of invisible numpy allocations."""
        self._host_roots[name] = nbytes

    def watch_engine(self, engine, name: str = "engine") -> None:
        """Register an engine's device-resident roots under ``name``:
        the state pytree and event ring (precise per-leaf labels), plus
        a shallow walk of the engine's, its fused driver's and its
        transport chain's instance attributes — which attributes the
        LAZY singletons (the heartbeat zero batch, the staging ring,
        a chaos transport's deferred in-flight messages): buffers
        allocated on first use, which must be attributed or their first
        appearance after ``set_baseline`` would read as a leak. Held
        via weakref so a watched engine can be garbage-collected across
        chaos crash-restore cycles — the whole point of the flatness
        pin."""
        ref = weakref.ref(engine)

        def state_getter():
            e = ref()
            return None if e is None else getattr(e, "state", None)

        def ring_getter():
            e = ref()
            return None if e is None else getattr(e, "_dev_ring", None)

        def host_getter():
            e = ref()
            if e is None:
                return None
            # the engine's own attribute dict (plain containers recurse
            # as pytrees; foreign objects stay opaque leaves), the fused
            # driver's staging ring, and the transport wrapper chain
            # (a ChaosTransport retains delayed message payloads)
            out: Dict[str, Any] = {"self": dict(vars(e))}
            driver = getattr(e, "_fused_driver", None)
            if driver is not None:
                out["staging"] = getattr(driver.staging, "buf", None)
            t = getattr(e, "t", None) or getattr(e, "transport", None)
            depth = 0
            while t is not None and depth < 3:
                out[f"t{depth}"] = dict(vars(t))
                t = getattr(t, "t", None)
                depth += 1
            return out

        # host first: census label maps apply roots in registration
        # order with later wins, so the precise state/ring leaf labels
        # override the generic host-walk labels for shared buffers
        self.register_root(f"{name}.host", host_getter)
        self.register_root(f"{name}.state", state_getter)
        self.register_root(f"{name}.ring", ring_getter)

        # tiered-store HOST buffers (ckpt.tiered): the sealed hot tail
        # and decoded-segment cache are numpy/bytes — never in
        # jax.live_arrays() — so they get their own labeled host root
        # instead of growing unattributed and unreported
        def sealed_bytes():
            e = ref()
            if e is None:
                return None
            store = getattr(e, "store", None)
            if store is not None and hasattr(store, "host_bytes"):
                return store.host_bytes()
            tier = getattr(e, "_tier_host_bytes", None)
            return tier() if tier is not None else None

        self.register_host_root(f"{name}.store.sealed", sealed_bytes)

    # ------------------------------------------------------------ census
    def census(self, collect: bool = False) -> MemoryCensus:
        """Take a census (see module docstring). ``collect=True`` runs
        ``gc.collect()`` first — the leak-detector comparisons want
        dropped-but-uncollected engine generations out of the picture;
        the passive /memory endpoint leaves the collector alone."""
        import jax

        if collect:
            gc.collect()
        labels: Dict[int, str] = {}
        for name, getter in self._roots.items():
            try:
                tree = getter()
            except Exception:
                tree = None
            if tree is not None:
                labels.update(_leaf_labels(name, tree))
        by_label: Dict[str, List[int]] = {}
        by_shape: Dict[str, List[int]] = {}
        unattr: Dict[str, List[int]] = {}
        total = 0
        n = 0
        attributed = 0
        for arr in jax.live_arrays():
            try:
                nbytes = int(arr.nbytes)
                shape_key = (
                    f"{arr.dtype}[{','.join(map(str, arr.shape))}]"
                )
            except Exception:
                continue
            total += nbytes
            n += 1
            sc = by_shape.setdefault(shape_key, [0, 0])
            sc[0] += 1
            sc[1] += nbytes
            label = labels.get(id(arr))
            if label is not None:
                attributed += nbytes
                lc = by_label.setdefault(label, [0, 0])
                lc[0] += 1
                lc[1] += nbytes
            else:
                uc = unattr.setdefault(shape_key, [0, 0])
                uc[0] += 1
                uc[1] += nbytes
        host_by_label: Dict[str, int] = {}
        for hname, nbytes in self._host_roots.items():
            try:
                b = nbytes()
            except Exception:
                b = None
            if b is not None:
                host_by_label[hname] = int(b)
        census = MemoryCensus(
            total_bytes=total, n_arrays=n,
            by_label={k: (c, b) for k, (c, b) in by_label.items()},
            by_shape={k: (c, b) for k, (c, b) in by_shape.items()},
            unattr_by_shape={k: (c, b) for k, (c, b) in unattr.items()},
            attributed_bytes=attributed,
            host_by_label=host_by_label,
        )
        self.last = census
        self.high_water_bytes = max(self.high_water_bytes, total)
        self.high_water_arrays = max(self.high_water_arrays, n)
        if self.registry is not None:
            self.registry.gauge(
                "raft_device_mem_bytes", "live device buffer bytes",
            ).set(total)
            self.registry.gauge(
                "raft_device_mem_bytes_high_water",
                "max live device buffer bytes observed",
            ).set(self.high_water_bytes)
            self.registry.gauge(
                "raft_device_arrays", "live device buffer count",
            ).set(n)
            roots: Dict[str, int] = {}
            for label, (_c, b) in census.by_label.items():
                root = label.split(".", 1)[0]
                roots[root] = roots.get(root, 0) + b
            for root, b in roots.items():
                self.registry.gauge(
                    "raft_device_state_bytes",
                    "live bytes attributed to a registered root",
                    ("root",),
                ).set_max(b, root=root)
            for hname, b in host_by_label.items():
                self.registry.gauge(
                    "raft_host_mem_bytes",
                    "host bytes attributed to a registered host root "
                    "(tiered-store hot tail + segment cache)",
                    ("root",),
                ).set(b, root=hname)
        return census

    # ----------------------------------------------------- leak detector
    def set_baseline(self, collect: bool = True) -> MemoryCensus:
        """Pin the steady-state census the flatness pins compare to."""
        self.baseline = self.census(collect=collect)
        return self.baseline

    def drift(self, tolerance_bytes: int = 0,
              collect: bool = True) -> List[str]:
        """Census-vs-baseline deltas worth flagging, as human-readable
        strings (empty = FLAT). The watched population is the
        UNATTRIBUTED buffers (see :class:`MemoryCensus`): a leaked old
        engine generation, an orphaned staging buffer, or a silently
        copied donated state is by definition unreachable from any live
        registered root and lands in ``unattr_by_shape`` — while a
        registered root's own leaves (including lazily-allocated
        singletons like the heartbeat zero batch) are reachable state,
        bounded by the root's fixed pytree structure."""
        if self.baseline is None:
            raise RuntimeError("set_baseline() before drift()")
        now = self.census(collect=collect)
        out: List[str] = []
        delta = now.unattributed_bytes - self.baseline.unattributed_bytes
        if delta > tolerance_bytes:
            out.append(
                f"unattributed total {delta:+d} bytes "
                f"({self.baseline.unattributed_bytes} -> "
                f"{now.unattributed_bytes})"
            )
        buckets = set(now.unattr_by_shape) | set(
            self.baseline.unattr_by_shape
        )
        for k in sorted(buckets):
            c0, b0 = self.baseline.unattr_by_shape.get(k, (0, 0))
            c1, b1 = now.unattr_by_shape.get(k, (0, 0))
            if c1 > c0 and b1 - b0 > tolerance_bytes:
                out.append(
                    f"bucket {k}: {c1 - c0:+d} unattributed arrays "
                    f"({b1 - b0:+d} bytes)"
                )
        if out and self.recorder is not None:
            self.recorder.record(
                node="mem", term=0, kind="census_drift",
                drift=list(out),
            )
        return out

    def assert_flat(self, tolerance_bytes: int = 0,
                    collect: bool = True) -> None:
        """The leak detector's teeth: raise when the census drifted."""
        drift = self.drift(
            tolerance_bytes=tolerance_bytes, collect=collect
        )
        if drift:
            raise AssertionError(
                "device-memory census is not flat vs baseline:\n  "
                + "\n  ".join(drift)
            )

    # ---------------------------------------------------------- snapshot
    def snapshot(self, census: bool = False) -> dict:
        """The /memory body and the forensics-bundle entry.
        ``census=True`` takes a fresh census first (metadata-only)."""
        if census or self.last is None:
            self.census()
        return {
            "census": self.last.to_jsonable() if self.last else None,
            "baseline": (
                self.baseline.to_jsonable() if self.baseline else None
            ),
            "high_water_bytes": self.high_water_bytes,
            "high_water_arrays": self.high_water_arrays,
            "final_drift": self.final_drift,
            "roots": sorted(self._roots),
            "host_roots": sorted(self._host_roots),
            "donation": (
                dataclasses.asdict(self.donation)
                if self.donation is not None else None
            ),
        }

    def summary(self) -> dict:
        """The light /status section."""
        return {
            "live_bytes": self.last.total_bytes if self.last else None,
            "live_arrays": self.last.n_arrays if self.last else None,
            "host_bytes": (
                sum(self.last.host_by_label.values())
                if self.last and self.last.host_by_label else None
            ),
            "high_water_bytes": self.high_water_bytes,
            "flat": (
                None if self.baseline is None or self.last is None
                else self.last.total_bytes <= self.baseline.total_bytes
            ),
        }


# --------------------------------------------------------------- donation
@dataclasses.dataclass
class DonationReport:
    """Outcome of one donated-call audit.

    ``engaged`` — the backend consumed at least one donated leaf (a
    backend that IGNORES donation copies everything and deletes
    nothing). ``honored`` — every donated leaf was consumed. The gap
    between the two is normal XLA behavior, not a leak: when two
    outputs CSE into one buffer (steady state: ``last_index'`` equals
    ``commit_index'``), one donated input goes unused and survives;
    its buffer frees with the reference, and the census-over-launches
    pin is what proves no per-launch copy accumulates."""

    honored: bool           # every donated leaf was actually consumed
    engaged: bool           # at least one leaf was consumed in place
    backend: str
    n_donated_leaves: int
    n_deleted: int
    detail: str = ""


def audit_donation(call: Callable, args: tuple,
                   donated: Tuple[int, ...] = (0,),
                   watch: Optional[MemoryWatch] = None) -> DonationReport:
    """Run ``call(*args)`` once and prove the donated positional args
    were consumed, not silently copied: after the call the array
    leaves of each donated operand must report ``is_deleted()``. A
    backend that ignores donation (older CPU jaxlibs warn and copy)
    yields ``engaged=False`` — the audit reports the copy instead of
    pretending. The caller must treat the donated args as consumed
    either way (that is already the donation contract)."""
    import jax

    backend = jax.default_backend()
    donated_leaves: List[Any] = []
    for i in donated:
        donated_leaves.extend(
            leaf for leaf in jax.tree.leaves(args[i])
            if hasattr(leaf, "is_deleted")
        )
    call(*args)
    deleted = sum(1 for leaf in donated_leaves if leaf.is_deleted())
    honored = deleted == len(donated_leaves) and donated_leaves != []
    engaged = deleted > 0
    if honored:
        detail = "all donated leaves consumed in place"
    elif engaged:
        detail = (
            f"{len(donated_leaves) - deleted} donated leaves survived "
            "the call (unused donation — typically an output CSE, see "
            "DonationReport)"
        )
    else:
        detail = (
            "no donated leaf was consumed (the backend copied instead "
            "of donating)"
        )
    report = DonationReport(
        honored=honored, engaged=engaged, backend=backend,
        n_donated_leaves=len(donated_leaves), n_deleted=deleted,
        detail=detail,
    )
    if watch is not None:
        watch.donation = report
        if watch.recorder is not None:
            watch.recorder.record(
                node="mem", term=0, kind="donation_audit",
                honored=honored, engaged=engaged, backend=backend,
                n_donated_leaves=report.n_donated_leaves,
                n_deleted=deleted,
            )
    return report
