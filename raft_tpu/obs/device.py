"""Device-resident observability: an in-kernel event ring + metrics.

The PR-5 flight recorder and PR-6 host profiler observe every protocol
transition only because every tick currently returns to the host. The
moment steady-state ticks fuse into one compiled ``lax.scan`` (ROADMAP
item 2) or the control plane rides the ``shard_map`` mesh (item 5),
host-side nodelog call sites see nothing. This module moves the trace
INTO the compiled program, the way the consensus already is:

- :class:`EventRing` — a fixed-capacity ring of fixed-width int32
  records living in device memory, carried through ``jit`` like any
  other state. Each record is ``REC_W`` lanes: (seq, tick, node, group,
  kind-code, term, role, commit, last, aux).
- :func:`dev_record` — the masked write primitive: one
  ``dynamic_update_slice`` + a counter bump, predicated on a traced
  bool, legal inside ``jit`` / ``vmap`` / ``lax.scan`` / ``shard_map``.
  ``seq`` is stamped from the ring's monotone counter, so overflow
  (laps) never reorders or renumbers surviving records.
- :func:`record_replicate_events` / :func:`record_vote_events` — the
  instrumentation bodies ``core.step`` runs behind its static
  ``record`` flag: they derive role change, term adoption, election
  win, commit advance and repair-floor motion purely from the
  (old state, new state, info) triple, so they compose with EVERY
  step formulation (XLA, fused Pallas, mesh) without touching the
  protocol math — the recorded program's state outputs are
  bit-identical to the unrecorded program's by construction.
- an on-device **metrics vector** (``EventRing.counters``): elections,
  term adoptions, commits, heartbeat ticks, repair rounds — per group
  under ``vmap`` — folded into the PR-5 registry at flush.
- :func:`decode_records` — the host-side decoder materialising PR-5
  ``Event`` objects. For kinds that overlap the host recorder's
  nodelog stream (``elect``, ``commit``), the decoded event's
  ``.nodelog()`` rendering is BYTE-IDENTICAL to the line the host
  recorder emits for the same transition — the golden-differential
  join key extends on-device (pinned by tests/test_device_obs.py).
- :class:`DeviceObs` — the host-side accumulation plane an engine
  flushes into once per launch boundary: decoded events (merged with
  host events by :func:`merged_timeline`), cumulative counters,
  overflow accounting.

Determinism contract: recording changes WHICH compiled program runs,
never what it computes — the extra ops read protocol state and write
only the ring. A seeded chaos run replays byte-identically (commit CRC,
verdict, op counts) with device recording on or off, and the
``record=False`` path is HLO-identical to the pre-instrumentation
program (both pinned). Detached costs zero device syncs: no ring is
allocated, no flush ever runs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax

from raft_tpu.obs.events import Event

# ------------------------------------------------------------ record layout
#: int32 lanes per record.
REC_W = 10
#: field offsets inside a record (the order the module docstring names)
F_SEQ, F_TICK, F_NODE, F_GROUP, F_KIND, F_TERM, F_ROLE, F_COMMIT, \
    F_LAST, F_AUX = range(REC_W)

#: kind codes (0 is reserved = "empty slot"; decode rejects it)
K_ELECT = 1          # election win          (host twin: "state changed to leader")
K_COMMIT = 2         # commit advance        (host twin: "commit index changed to N")
K_TERM_ADOPT = 3     # a row adopted a higher term (silent on the host)
K_STEP_DOWN = 4      # step saw a term above the leader's (host acts next tick)
K_REPAIR = 5         # repair window moved (aux = window start index)

KIND_NAMES = {
    K_ELECT: "elect",
    K_COMMIT: "commit",
    K_TERM_ADOPT: "term_adopt",
    K_STEP_DOWN: "step_down",
    K_REPAIR: "repair_floor",
}

#: role codes (record field F_ROLE) -> engine role strings
ROLE_FOLLOWER, ROLE_CANDIDATE, ROLE_LEADER = 0, 1, 2
ROLE_NAMES = {ROLE_FOLLOWER: "follower", ROLE_CANDIDATE: "candidate",
              ROLE_LEADER: "leader"}

# ------------------------------------------------------- on-device counters
#: offsets into ``EventRing.counters`` (the on-device metrics vector)
C_ELECTIONS, C_TERM_ADOPTIONS, C_COMMITS, C_TICKS, C_REPAIRS = range(5)
N_COUNTERS = 5
COUNTER_NAMES = (
    "elections", "term_adoptions", "commits", "heartbeat_ticks",
    "repair_rounds",
)
#: registry metric name for counter i at flush (PR-5 MetricsRegistry)
COUNTER_METRICS = tuple(f"raft_device_{n}_total" for n in COUNTER_NAMES)

# the flush trailer packs (count, tick, counters...) into one REC_W row
assert N_COUNTERS + 2 <= REC_W


@struct.dataclass
class EventRing:
    """The device-resident ring: a pytree carried through jit/scan.

    ``count`` is the monotone seq counter (total records ever written —
    the next record's seq); slot of seq ``s`` is ``s % capacity``, so
    ``max(0, count - capacity)`` oldest records have been lapped.
    ``tick`` counts recorded launches (the device tick stamp records
    carry); ``counters`` is the on-device metrics vector."""

    buf: jax.Array       # i32[capacity, REC_W]
    count: jax.Array     # i32[]
    tick: jax.Array      # i32[]
    counters: jax.Array  # i32[N_COUNTERS]

    @property
    def capacity(self) -> int:
        return self.buf.shape[-2]


def init_ring(capacity: int = 4096) -> EventRing:
    """A fresh empty ring (host-side constant arrays; jit moves them)."""
    if capacity < 1:
        raise ValueError("capacity must be >= 1")
    return EventRing(
        buf=jnp.zeros((capacity, REC_W), jnp.int32),
        count=jnp.zeros((), jnp.int32),
        tick=jnp.zeros((), jnp.int32),
        counters=jnp.zeros((N_COUNTERS,), jnp.int32),
    )


def init_group_rings(capacity: int, n_groups: int) -> EventRing:
    """G independent rings as one batched pytree (leading group axis on
    every leaf) — the shape ``vmap``-ed recorded group steps carry."""
    one = init_ring(capacity)
    return jax.tree.map(
        lambda a: jnp.broadcast_to(a, (n_groups,) + a.shape), one
    )


def make_rec(kind: int, node, term, role: int, commit, last, aux,
             group) -> jax.Array:
    """Assemble one i32[REC_W] record. ``seq`` and ``tick`` are stamped
    by :func:`dev_record`; ``kind``/``role`` are static codes, the rest
    may be traced scalars."""
    z = jnp.int32
    return jnp.stack([
        z(0), z(0), z(node), z(group), z(kind), z(term), z(role),
        z(commit), z(last), z(aux),
    ])


def dev_record(ring: EventRing, cond, rec: jax.Array) -> EventRing:
    """Masked ring append: write ``rec`` at slot ``count % capacity`` and
    bump the seq counter iff ``cond`` — otherwise the ring passes
    through bit-unchanged. One dynamic slice read + one
    ``dynamic_update_slice`` + scalar arithmetic: legal (and cheap)
    inside ``jit``, ``vmap``, ``lax.scan`` and ``shard_map``."""
    cap = ring.buf.shape[-2]
    cond = jnp.asarray(cond, bool)
    slot = lax.rem(ring.count, jnp.int32(cap))
    rec = rec.at[F_SEQ].set(ring.count).at[F_TICK].set(ring.tick)
    cur = lax.dynamic_slice(ring.buf, (slot, jnp.int32(0)), (1, REC_W))
    new = jnp.where(cond, rec[None, :], cur)
    buf = lax.dynamic_update_slice(ring.buf, new, (slot, jnp.int32(0)))
    return ring.replace(buf=buf, count=ring.count + cond.astype(jnp.int32))


def dev_count(ring: EventRing, idx: int, amount) -> EventRing:
    """Bump on-device metrics counter ``idx`` (static) by ``amount``
    (traced i32)."""
    return ring.replace(
        counters=ring.counters.at[idx].add(jnp.int32(amount))
    )


# ------------------------------------------------- kernel instrumentation
def record_replicate_events(
    ring: EventRing, comm, old, new, info, leader, leader_term,
    group_id, *, repair: bool = True, ticks=1,
) -> EventRing:
    """Record one replicate step's interesting transitions, derived
    purely from the (old, new, info) triple — never from the step's
    internals, so every formulation (XLA / fused Pallas / mesh) shares
    this body unchanged. Events: commit advance (the host nodelog
    twin), per-row term adoptions, a step-down signal (``max_term``
    above the leader's), and repair-window motion; counters: ticks,
    commits (entry delta), term adoptions, repair rounds."""
    R = comm.n_replicas
    leader = jnp.int32(leader)
    leader_term = jnp.int32(leader_term)
    old_term = comm.all_gather(old.term)
    new_term = comm.all_gather(new.term)
    new_commit = comm.all_gather(new.commit_index)
    new_last = comm.all_gather(new.last_index)
    old_commit_l = comm.all_gather(old.commit_index)[leader]
    old_last_l = comm.all_gather(old.last_index)[leader]
    legit = leader_term >= 1

    ring = ring.replace(tick=ring.tick + 1)
    # a masked group lane (leader_term 0 under vmap) ran a bit-exact
    # no-op, not a tick — count only legitimate steps. ``ticks`` lets
    # a chunk-granularity caller (the engine's pipelined paths) charge
    # the whole flight's step count to one recorded transition.
    ring = dev_count(ring, C_TICKS, legit.astype(jnp.int32) * jnp.int32(ticks))

    # commit advance, leader-attributed: the decoded twin of the host's
    # "commit index changed to N" line (byte-identical within a stable
    # leadership — the leader row's commit IS the global commit there)
    commit_adv = legit & (info.commit_index > old_commit_l)
    ring = dev_record(ring, commit_adv, make_rec(
        K_COMMIT, leader, leader_term, ROLE_LEADER, info.commit_index,
        new_last[leader], 0, group_id,
    ))
    ring = dev_count(ring, C_COMMITS, jnp.where(
        commit_adv, info.commit_index - old_commit_l, 0
    ))

    # per-row term adoption (static unroll over the replica axis: R
    # conditional single-row writes — in a steady window all no-ops)
    adopt = new_term > old_term
    for p in range(R):
        ring = dev_record(ring, adopt[p], make_rec(
            K_TERM_ADOPT, p, new_term[p], ROLE_FOLLOWER, new_commit[p],
            new_last[p], old_term[p], group_id,
        ))
    ring = dev_count(
        ring, C_TERM_ADOPTIONS, jnp.sum(adopt.astype(jnp.int32))
    )

    # the step saw a term above the leader's: the engine will step the
    # leader down when it reads info.max_term — record the device-side
    # evidence (aux = the leader term that just died)
    step_down = legit & (info.max_term > leader_term)
    ring = dev_record(ring, step_down, make_rec(
        K_STEP_DOWN, leader, info.max_term, ROLE_FOLLOWER,
        new_commit[leader], new_last[leader], leader_term, group_id,
    ))

    if repair:
        # the repair window actually moved entries this step (the
        # compiled-out steady/EC program skips this block statically):
        # repair_count > 0 <=> legit & window start <= leader's old last
        moved = legit & (info.repair_start >= 1) & (
            old_last_l >= info.repair_start
        )
        ring = dev_record(ring, moved, make_rec(
            K_REPAIR, leader, leader_term, ROLE_LEADER,
            info.commit_index, new_last[leader], info.repair_start,
            group_id,
        ))
        ring = dev_count(ring, C_REPAIRS, moved.astype(jnp.int32))
    return ring


def record_vote_events(
    ring: EventRing, comm, old, new, info, candidate, cand_term,
    quorum, group_id,
) -> EventRing:
    """Record one vote round: the election win (the decoded twin of the
    host's "state changed to leader" line — same win rule the engine
    applies: a vote majority AND no higher term heard) plus per-row
    term adoptions."""
    R = comm.n_replicas
    candidate = jnp.int32(candidate)
    cand_term = jnp.int32(cand_term)
    old_term = comm.all_gather(old.term)
    new_term = comm.all_gather(new.term)
    new_commit = comm.all_gather(new.commit_index)
    new_last = comm.all_gather(new.last_index)

    ring = ring.replace(tick=ring.tick + 1)
    win = (info.votes > jnp.int32(quorum)) & (info.max_term <= cand_term)
    ring = dev_record(ring, win, make_rec(
        K_ELECT, candidate, cand_term, ROLE_LEADER,
        new_commit[candidate], new_last[candidate], info.votes, group_id,
    ))
    ring = dev_count(ring, C_ELECTIONS, win.astype(jnp.int32))

    adopt = new_term > old_term
    for p in range(R):
        ring = dev_record(ring, adopt[p], make_rec(
            K_TERM_ADOPT, p, new_term[p], ROLE_FOLLOWER, new_commit[p],
            new_last[p], old_term[p], group_id,
        ))
    ring = dev_count(
        ring, C_TERM_ADOPTIONS, jnp.sum(adopt.astype(jnp.int32))
    )
    return ring


# --------------------------------------------------------------- flushing
def flush_pack(ring: EventRing) -> jax.Array:
    """Pack the whole ring into ONE i32[capacity+1, REC_W] array for a
    single amortised device fetch per launch boundary: the buffer plus a
    trailer row carrying (count, tick, counters...)."""
    trailer = jnp.zeros((REC_W,), jnp.int32)
    trailer = trailer.at[0].set(ring.count).at[1].set(ring.tick)
    trailer = lax.dynamic_update_slice(trailer, ring.counters, (2,))
    return jnp.concatenate([ring.buf, trailer[None, :]], axis=0)


_flush_pack_jit = None
_flush_pack_group_jit = None


def packed_flush(ring: EventRing) -> jax.Array:
    """Jitted :func:`flush_pack` — single ring (i32[cap+1, REC_W]) or
    group-batched rings (i32[G, cap+1, REC_W]); one launch either way."""
    global _flush_pack_jit, _flush_pack_group_jit
    if ring.count.ndim == 0:
        if _flush_pack_jit is None:
            _flush_pack_jit = jax.jit(flush_pack)
        return _flush_pack_jit(ring)
    if _flush_pack_group_jit is None:
        _flush_pack_group_jit = jax.jit(jax.vmap(flush_pack))
    return _flush_pack_group_jit(ring)


def _node_name(node: int, group: int) -> str:
    return f"Server{node}" if group < 0 else f"g{group}/Server{node}"


def _msg_of(kind_code: int, commit: int) -> Optional[str]:
    if kind_code == K_ELECT:
        return "state changed to leader"
    if kind_code == K_COMMIT:
        return f"commit index changed to {commit}"
    return None            # recorder-only: never entered the trace stream


def decode_records(
    packed: np.ndarray,
    start_seq: int = 0,
    t_virtual: float = 0.0,
) -> Tuple[List[Event], int, int, np.ndarray, int]:
    """Decode one :func:`packed_flush` fetch into PR-5 ``Event`` objects.

    Returns ``(events, count, lost, counters, tick)`` where ``events``
    are the decoded records with seq >= ``start_seq`` still resident in
    the ring (seq order), and ``lost`` counts records that lapped out
    between flushes (seq < the oldest resident record but >=
    ``start_seq``). ``Event.seq`` carries the DEVICE seq; ``t_virtual``
    stamps the flush-time virtual clock (the engine flushes once per
    launch, so decoded events carry the tick they surfaced at)."""
    packed = np.asarray(packed)
    cap = packed.shape[0] - 1
    trailer = packed[-1]
    count, tick = int(trailer[0]), int(trailer[1])
    counters = trailer[2 : 2 + N_COUNTERS].astype(np.int64)
    oldest = max(0, count - cap)
    lost = max(0, oldest - start_seq)
    events: List[Event] = []
    for s in range(max(start_seq, oldest), count):
        row = packed[s % cap]
        if int(row[F_SEQ]) != s or int(row[F_KIND]) == 0:
            continue       # torn slot (cannot happen post-flush; belt)
        kind_code = int(row[F_KIND])
        group = int(row[F_GROUP])
        commit = int(row[F_COMMIT])
        events.append(Event(
            seq=s,
            t_virtual=t_virtual,
            node=_node_name(int(row[F_NODE]), group),
            group=None if group < 0 else group,
            term=int(row[F_TERM]),
            kind=KIND_NAMES.get(kind_code, f"dev_kind_{kind_code}"),
            state=ROLE_NAMES.get(int(row[F_ROLE]), ""),
            commit_index=commit,
            last_index=int(row[F_LAST]),
            msg=_msg_of(kind_code, commit),
            fields={
                "device": True, "tick": int(row[F_TICK]),
                "aux": int(row[F_AUX]),
            },
        ))
    return events, count, lost, counters, tick


class DeviceObs:
    """Host-side accumulation plane for device-recorded observability.

    One instance can span several engines / crash-restore cycles (the
    chaos ObsStack holds one per run, like the flight recorder): each
    engine keeps its own ring + flush cursor and ``ingest``s decoded
    events here. ``counters`` accumulates the on-device metrics vector
    per group label; ``dropped`` counts records lapped out before any
    flush saw them (the overflow contract: seq stays monotone, losses
    are reported, never silent)."""

    def __init__(self, capacity: int = 4096,
                 host_capacity: int = 1 << 16):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        from collections import deque

        self.capacity = capacity
        self.events = deque(maxlen=host_capacity)
        #   decoded events, host-side bounded like the FlightRecorder's
        #   ring; host evictions are counted separately from device
        #   laps (``dropped`` = records lost BEFORE any flush saw them)
        self.host_evicted = 0
        self.dropped = 0
        # epoch accounting: each engine attachment is one EPOCH whose
        # device-side readings (seq counter, metrics vector) restart at
        # zero; completed epochs fold into the ``_base_*`` accumulators
        # (new_epoch) so a crash-restored engine ADDS to the plane
        # instead of regressing it, and its seqs re-offset past
        # everything already ingested.
        self._cur_totals: Dict[Optional[int], int] = {}
        self._cur_laps: Dict[Optional[int], int] = {}
        self._cur_counters: Dict[Tuple[str, str], int] = {}
        self._base_totals: Dict[Optional[int], int] = {}
        self._base_laps: Dict[Optional[int], int] = {}
        self._base_counters: Dict[Tuple[str, str], int] = {}

    # ------------------------------------------------------------ epochs
    def new_epoch(self) -> None:
        """Fold the current engine's cumulative device readings into the
        base accumulators — called by ``attach_device_obs`` whenever an
        engine (fresh boot, crash-restore) adopts this plane. Idempotent
        on an empty current epoch."""
        for g, tot in self._cur_totals.items():
            self._base_totals[g] = self._base_totals.get(g, 0) + tot
        for g, laps in self._cur_laps.items():
            self._base_laps[g] = self._base_laps.get(g, 0) + laps
        for key, v in self._cur_counters.items():
            self._base_counters[key] = self._base_counters.get(key, 0) + v
        self._cur_totals = {}
        self._cur_laps = {}
        self._cur_counters = {}

    # ------------------------------------------------------------ ingest
    def ingest(self, events: List[Event], *, total: int, lost: int,
               counters: np.ndarray, group: Optional[int] = None) -> None:
        base = self._base_totals.get(group, 0)
        if base:
            # keep the accumulated stream's seqs monotone across engine
            # generations (each fresh ring restarts at 0)
            import dataclasses

            events = [dataclasses.replace(e, seq=e.seq + base)
                      for e in events]
        room = self.events.maxlen - len(self.events)
        if len(events) > room:
            self.host_evicted += len(events) - room
        self.events.extend(events)
        self.dropped += lost
        self._cur_totals[group] = total
        self._cur_laps[group] = total // self.capacity
        label = "0" if group is None else str(group)
        for i, name in enumerate(COUNTER_METRICS):
            self._cur_counters[(name, label)] = int(counters[i])

    # ----------------------------------------------------------- queries
    @property
    def counters(self) -> Dict[str, Dict[str, int]]:
        """name -> {group label -> value}, summed across epochs."""
        out: Dict[str, Dict[str, int]] = {}
        for src in (self._base_counters, self._cur_counters):
            for (name, label), v in src.items():
                out.setdefault(name, {})
                out[name][label] = out[name].get(label, 0) + v
        return out

    @property
    def total_recorded(self) -> int:
        return (sum(self._base_totals.values())
                + sum(self._cur_totals.values()))

    @property
    def laps(self) -> int:
        groups = set(self._base_laps) | set(self._cur_laps)
        return max(
            (self._base_laps.get(g, 0) + self._cur_laps.get(g, 0)
             for g in groups),
            default=0,
        )

    def of_kind(self, *kinds: str, group: Optional[int] = None):
        want = set(kinds)
        return [
            e for e in self.events
            if e.kind in want and (group is None or e.group == group)
        ]

    def nodelog_lines(self) -> List[str]:
        """The decoded device stream's nodelog renderings (events whose
        kind overlaps the host trace stream — elect / commit)."""
        return [e.nodelog() for e in self.events if e.msg is not None]

    # --------------------------------------------------------- (de)serial
    def to_jsonable(self) -> dict:
        return {
            "capacity": self.capacity,
            "dropped": self.dropped,
            "laps": self.laps,
            "total_recorded": self.total_recorded,
            "counters": self.counters,
            "events": [e.to_jsonable() for e in self.events],
        }

    @classmethod
    def from_jsonable(cls, d: dict) -> "DeviceObs":
        obs = cls(capacity=d.get("capacity", 4096))
        obs.dropped = d.get("dropped", 0)
        for name, series in d.get("counters", {}).items():
            for label, v in series.items():
                obs._base_counters[(name, label)] = int(v)
        obs._base_totals = {None: d.get("total_recorded", len(d["events"]))}
        obs._base_laps = {None: d.get("laps", 0)}
        obs.events.extend(Event.from_jsonable(ed) for ed in d["events"])
        return obs


def merged_timeline(recorder, device_obs) -> List[Event]:
    """Host flight-recorder events and decoded device events as ONE
    stream, ordered by virtual time with device events first inside a
    tie (the device step ran before the host bookkeeping that observed
    it) — the forensics view ``--explain`` interleaves."""
    host = list(recorder._ring) if recorder is not None else []
    dev = list(device_obs.events) if device_obs is not None else []
    tagged = [(e.t_virtual, 0, i, e) for i, e in enumerate(dev)]
    tagged += [(e.t_virtual, 1, i, e) for i, e in enumerate(host)]
    tagged.sort(key=lambda t: t[:3])
    return [e for _, _, _, e in tagged]
