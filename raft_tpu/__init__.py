"""raft_tpu — a TPU-native distributed-consensus framework.

A brand-new implementation of the capabilities of the reference
(eastwd/raft-sample: a 3-node, goroutine+channel Raft demo — leader election +
log replication, /root/reference/main.go), re-designed TPU-first:

- The hot path (AppendEntries replication, ack/vote aggregation, quorum
  commit) is a batched, statically-shaped XLA program over a ``replica`` mesh
  axis (``shard_map``), replacing the reference's serial per-peer channel
  sends + blocking replies (main.go:332-395) with collectives that correlate
  requests and replies by construction.
- The cold path (role transitions, election timers, client I/O) is a
  single-threaded host event loop (``raft.engine``), replacing the
  reference's goroutine-per-node trampoline (main.go:98-109).
- Log-entry batches can be Reed–Solomon erasure-coded over GF(2^8)
  (``ec``) so each follower stores a shard instead of a full copy, with
  all_gather + decode reconstruction on the read path.

See SURVEY.md for the full structural analysis of the reference and
BASELINE.md for the target numbers.
"""

from raft_tpu.admission import Overloaded
from raft_tpu.config import RaftConfig
from raft_tpu.core.state import ReplicaState, init_state
from raft_tpu.multi import MultiEngine, Router
from raft_tpu.raft.engine import RaftEngine

__all__ = [
    "MultiEngine", "Overloaded", "RaftConfig", "RaftEngine",
    "ReplicaState", "Router",
    "init_state",
]

__version__ = "0.1.0"
