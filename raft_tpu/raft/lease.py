"""Leader leases: zero-round linearizable reads (dissertation §6.4.1).

Classic ReadIndex pays one empty quorum round per read (or per batch —
``submit_read``).  A leader LEASE removes even that: every successful
quorum round (a write tick, a pipelined chunk, an explicit
confirmation) doubles as a lease grant, and while the lease is valid
the leader may serve linearizable reads from its own committed state
with ZERO replication rounds — the read costs one host-side clock
compare.

Safety argument (why a lease-holder cannot serve stale data): a new
leader requires votes from a voter majority, and under PreVote's
leader-stickiness clause (§9.6 — ``RaftConfig.read_lease`` REQUIRES
``prevote``) no voter grants while it heard the current leader within
the minimum election timeout ``f0 = follower_timeout[0]``.  The lease
is granted at the instant a quorum round reached a member majority —
the same instant those followers' stickiness clocks reset — so no rival
can be elected (let alone commit a write the lease-read would miss)
until ``f0`` true seconds after the grant.  A lease that expires before
then is safe.

Clocks drift, so "``f0`` seconds after the grant" is measured on the
leader's OWN clock, which may run slow relative to the cluster: the
lease duration is therefore ``f0 / clock_drift_bound``
(``RaftConfig.clock_drift_bound`` — the deployment's assumed worst-case
clock-rate error).  With the leader's true rate ``rho`` (local seconds
per true second), a serve at local elapsed ``< f0 / drift`` happened at
true elapsed ``< f0 / (drift * rho)``, which is ``< f0`` whenever
``rho >= 1 / drift`` — i.e. the plane is provably safe for any skew
inside the assumed bound.  The chaos clock-skew nemesis
(``chaos.nemesis`` ``skew_on``) drives ``rho`` across exactly that
band; the ``broken="lease_skew"`` variant sets ``ignore_drift`` (lease
= full ``f0`` on the local clock — a plane that assumed perfect
clocks), under which a slow clock holds the lease past a rival's
election and serves a provably stale read the extended checker and the
online auditor must both catch (``chaos.runner.reads_run``).

One :class:`LeaseTable` serves both engines — keys are replica rows
(``RaftEngine``) or ``(group, row)`` pairs (``MultiEngine``).  Lease
state is VOLATILE by design: a restarted engine builds a fresh table
and must win a quorum round before serving locally again (a persisted
lease could outlive the stickiness evidence it rests on).
"""

from __future__ import annotations

from typing import Dict, Hashable, Optional, Tuple


class LeaseTable:
    """Drift-bounded leader-lease clocks, one entry per lease holder.

    ``duration_s`` is the raw stickiness window ``f0``; a valid lease
    requires the holder's LOCAL elapsed time since grant to stay under
    ``duration_s / drift_bound`` (see module docstring).  ``set_rate``
    models the holder's clock-rate error (the chaos nemesis's injection
    surface): local elapsed = true elapsed * rate, so ``rate < 1`` is a
    slow clock that overestimates its remaining lease.

    ``ignore_drift=True`` is the deliberately BROKEN plane (the
    ``lease_skew`` falsifiability variant): the drift divisor is
    dropped, so any slow clock inside the assumed band already violates
    the safety argument.  Production code never sets it.
    """

    def __init__(self, duration_s: float, drift_bound: float) -> None:
        if duration_s <= 0:
            raise ValueError("lease duration must be > 0")
        if drift_bound < 1.0:
            raise ValueError("clock_drift_bound must be >= 1.0")
        self.duration_s = float(duration_s)
        self.drift_bound = float(drift_bound)
        self.ignore_drift = False
        self.grants = 0                 # all-time grant count (obs)
        self._grant: Dict[Hashable, Tuple[int, float]] = {}
        #   key -> (term, true grant time): only the LATEST grant per
        #   holder matters — leases renew, never stack
        self._rate: Dict[Hashable, float] = {}

    # ------------------------------------------------------------ skew
    def set_rate(self, key: Hashable, rate: float) -> None:
        """Set ``key``'s local clock rate (1.0 = perfect; the nemesis
        draws inside ``[1/drift_bound, drift_bound]`` — the band the
        correct plane must absorb)."""
        if rate <= 0:
            raise ValueError("clock rate must be > 0")
        if rate == 1.0:
            self._rate.pop(key, None)
        else:
            self._rate[key] = float(rate)

    def rate(self, key: Hashable) -> float:
        return self._rate.get(key, 1.0)

    # ----------------------------------------------------------- lease
    @property
    def effective_duration_s(self) -> float:
        """Local-clock seconds a grant stays valid."""
        if self.ignore_drift:
            return self.duration_s
        return self.duration_s / self.drift_bound

    def grant(self, key: Hashable, term: int, now: float) -> None:
        """A quorum round sourced at ``key`` in ``term`` completed at
        true time ``now`` (the same instant the heard followers'
        stickiness clocks reset — the caller's burden)."""
        self._grant[key] = (int(term), float(now))
        self.grants += 1

    def break_(self, key: Optional[Hashable] = None) -> None:
        """Drop a grant (or all of them): leadership change, membership
        change, crash-restore — anything that invalidates the
        stickiness evidence."""
        if key is None:
            self._grant.clear()
        else:
            self._grant.pop(key, None)

    def remaining_s(self, key: Hashable, term: int, now: float) -> float:
        """LOCAL-clock seconds of lease left (<= 0 = expired / absent /
        a different term's grant)."""
        got = self._grant.get(key)
        if got is None or got[0] != int(term):
            return 0.0
        local_elapsed = (float(now) - got[1]) * self.rate(key)
        return self.effective_duration_s - local_elapsed

    def valid(self, key: Hashable, term: int, now: float) -> bool:
        """Serve-locally predicate, STRICT: at exactly the boundary the
        lease is expired (the safety math needs true elapsed < f0)."""
        return self.remaining_s(key, term, now) > 0.0

    # ------------------------------------------------------------- obs
    def summary(self, key: Hashable, term: int, now: float) -> dict:
        return {
            "granted": key in self._grant,
            "valid": self.valid(key, term, now),
            "remaining_s": round(max(self.remaining_s(key, term, now), 0.0), 6),
            "duration_s": self.effective_duration_s,
            "drift_bound": self.drift_bound,
            "rate": self.rate(key),
            "grants": self.grants,
        }
