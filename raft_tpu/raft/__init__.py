"""Host protocol engine: the cold path of the framework.

Role transitions, election timers, and client I/O are branchy and stateful,
so they live in a single-threaded host event loop (SURVEY.md §7 "design
stance") that launches the batched device steps in ``core.step``. This
replaces the reference's goroutine-per-node trampoline (``Run()``,
main.go:98-109) and its wall-clock timers with one deterministic scheduler
on a virtual clock — every run is replayable from a seed.
"""

from raft_tpu.raft.engine import RaftEngine, VirtualClock

__all__ = ["RaftEngine", "VirtualClock"]
