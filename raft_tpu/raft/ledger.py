"""Bounded commit-stamp ledger — the ONE copy of the eviction/interval
logic both engines share.

``RaftEngine`` keeps one ``(commit_time, submit_time, durable_ranges)``
triple; ``MultiEngine`` keeps one per group. The invariants are subtle
enough (trim-to-exactly-cap batching invariance — the fused and tick
paths must retain IDENTICAL dicts; contiguous-run collapse; neighbour
coalescing; ``is_durable`` answering for every seq ever issued) that two
hand-synchronized copies would drift, so the algorithms live here and
the engines delegate.

Contract (see ``RaftEngine.commit_time``'s comment for the full story):
stamps evict oldest-first past ``cap`` retained entries (dict order IS
stamp order), trimmed to EXACTLY cap so the retained set is a pure
function of the stamp sequence, never of check cadence; evicted seqs —
committed by construction — collapse into merged ``[lo, hi]`` intervals
(one per loss gap) that keep durability queries exact after the stamp
is gone.
"""

from __future__ import annotations

import bisect
from itertools import islice
from typing import Dict, List, Tuple

import numpy as np


def durable_range_covers(ranges: List[List[int]], seq: int) -> bool:
    """True iff ``seq`` lies in one of the merged durable intervals
    (bisect lookup; the intervals are sorted and disjoint)."""
    if not ranges:
        return False
    i = bisect.bisect_right(ranges, [seq, float("inf")]) - 1
    return i >= 0 and ranges[i][0] <= seq <= ranges[i][1]


def merge_durable_range(ranges: List[List[int]], a: int, b: int) -> None:
    """Insert [a, b] into the sorted, disjoint interval list in place,
    coalescing with adjacent/overlapping neighbours."""
    if ranges and ranges[-1][0] <= a <= ranges[-1][1] + 1:
        # common case: the run starts inside or immediately after the
        # tail range (evictions proceed in stamp order)
        if ranges[-1][1] < b:
            ranges[-1][1] = b
        return
    i = bisect.bisect_right(ranges, [a, float("inf")])
    if i > 0 and ranges[i - 1][1] >= a - 1:
        ranges[i - 1][1] = max(ranges[i - 1][1], b)
        i -= 1
    else:
        ranges.insert(i, [a, b])
    # absorb any following ranges the new one now touches
    while i + 1 < len(ranges) and ranges[i + 1][0] <= ranges[i][1] + 1:
        ranges[i][1] = max(ranges[i][1], ranges[i + 1][1])
        del ranges[i + 1]


def evict_commit_stamps(
    commit_time: Dict[int, float],
    submit_time: Dict[int, float],
    cap: int,
    ranges: List[List[int]],
) -> Tuple[Dict[int, float], Dict[int, float], int]:
    """Trim the stamp dicts to exactly ``cap`` retained entries
    (oldest-first; bulk C-level rebuilds), folding the evicted seqs
    into ``ranges`` (mutated in place). Returns the new
    ``(commit_time, submit_time, n_evicted)`` — no-op triple when under
    the cap."""
    n_evict = len(commit_time) - cap
    if n_evict <= 0:
        return commit_time, submit_time, 0
    it = iter(commit_time.items())
    evicted = list(islice(it, n_evict))
    commit_time = dict(it)                 # retained tail, C-level
    if n_evict * 4 < len(submit_time):
        for seq, _ in evicted:
            submit_time.pop(seq, None)
    else:
        drop = {s for s, _ in evicted}
        submit_time = {
            k: v for k, v in submit_time.items() if k not in drop
        }
    # fold the evicted seqs into the merged durable intervals:
    # contiguous runs collapse via one numpy pass (seqs stamp in
    # near-ascending order, so the interval list stays tiny — one
    # interval per loss gap)
    arr = np.fromiter((s for s, _ in evicted), np.int64, n_evict)
    arr.sort()
    breaks = np.flatnonzero(np.diff(arr) != 1)
    starts = np.concatenate(([0], breaks + 1))
    ends = np.concatenate((breaks, [n_evict - 1]))
    for a, b in zip(arr[starts], arr[ends]):
        merge_durable_range(ranges, int(a), int(b))
    return commit_time, submit_time, n_evict
