"""The K-tick fused steady-state engine (ROADMAP item 2).

The attribution leg (docs/PERF.md) measured the headline path host-bound:
~2 µs of device time per tick buried under two orders of magnitude of
host control plane — one undonated launch per heartbeat, plus per-entry
``host_post`` bookkeeping costing 2.5× the device wait. Ongaro's
dissertation treats the steady state (stable leader, no config change,
every follower caught up) as the overwhelmingly common case, and that is
exactly the case a compiler can own: this module fuses runs of K
consecutive leader ticks into ONE compiled ``lax.scan`` launch
(``core.step.fused_steady_scan``), escaping to the host only when a
step's ``interesting`` mask fires or the staging buffer drains.

Three pieces:

- :class:`StagingRing` — the pre-packed DEVICE staging buffer. Client
  submits flush full batches into a device-resident ring of untiled
  payload words (one donated ``dynamic_update_slice`` per batch, paid on
  the client's submit path), so the fused launch reads its windows by
  ring index and the drain loop never pays the 16 MB/launch host→device
  copy. The ring mirrors a queue suffix; any queue mutation other than
  append / aligned pop-front invalidates it (``reset``), and the driver
  re-stages lazily.
- :class:`FusedDriver` — eligibility, window planning, pipelined
  dispatch, and EXACT booking. Eligibility is a host-side proof that
  nothing interesting CAN happen inside the window (stable routed
  leader holding the cluster's highest term, verified steady, fully
  committed, quorum of reachable non-slow voters, no config change in
  flight, no fault/election event due in the window, fault-free
  transport) — the device escape mask is the safety net for the cases
  the proof missed, not the common path. Dispatch pipelines launch N+1
  before booking launch N (``jax.block_until_ready`` only at the
  booking boundary, hostprof marks kept faithful); the previous
  launch's ``halted`` flag threads into the next as a DEVICE scalar, so
  an unbooked escape turns every later launch into a provable no-op
  chain instead of a divergence.
- exact booking — the host replays each fused tick's control-plane
  bookkeeping in order (virtual clock, timer re-arms with the SAME rng
  draws, heap tiebreak counter, CheckQuorum lease, admission delay
  observation, nodelog/metrics emissions) while the per-ENTRY work the
  attribution table blamed (seq→index mapping, commit stamping, archive
  puts, read-ticket confirmation) collapses into one vectorized pass
  per launch: range-keyed commit stamps, a span-archived payload block
  (``CheckpointStore.put_span``), and a single read-confirmation sweep.
  The result is pinned byte-identical to the tick-at-a-time engine —
  committed log, commit/submit stamps, rng stream, heap evolution, and
  seeded chaos fingerprints all replay bit-exact with fusion on or off
  (tests/test_fused_ticks.py).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax

from raft_tpu.obs import profiling
from raft_tpu.obs.compile import labeled

#: shared staging-slot writer: one donated DUS per staged batch (shape-
#: cached per (S, B, W) like any jit; process-wide so chaos restarts
#: never recompile it — the compile plane's "single.stage" hot path)
_STAGE_JIT = labeled("single.stage", jax.jit(
    lambda buf, words, slot: lax.dynamic_update_slice(
        buf, words[None], (slot, jnp.int32(0), jnp.int32(0))
    ),
    donate_argnums=(0,),
))


class StagingRing:
    """Device staging ring of untiled payload words, i32[S, B, W].

    Mirrors the engine queue's aligned prefix: with ``consumed`` entries
    popped since the last reset, absolute batch ``k`` (entries
    ``[kB, (k+1)B)`` counted from the reset point) lives in slot
    ``k % S`` once staged; the queue's head sits at absolute entry
    ``consumed``. Full batches only — the window's trailing partial
    batch drains through the ordinary tick path, which is also where
    the fused window's "staging drained" escape hands control back.
    """

    def __init__(self, batch: int, words: int, slots: int):
        self.B = batch
        self.W = words
        self.S = slots
        self.buf = None          # jnp i32[S, B, W], allocated lazily
        self.consumed = 0        # entries popped since reset
        self.staged = 0          # absolute batches staged since reset
        self.stage_events = 0    # lifetime FULL-batch pack-and-copy
        #   count (never reset): every host->device full-batch copy
        #   this ring ever paid (top_up). The wire tier's staged-ingest
        #   proof reads it per pump phase (net.server): full batches
        #   staged on the NETWORK side of the wall vs on the tick path
        #   must split all/nothing.
        self.stage_tail_events = 0
        #   window-tail packs (stage_tail): the fused window's trailing
        #   PARTIAL batch is staged at launch planning by design — one
        #   per window at most, never per request — so it is counted
        #   apart from the full-batch contract above.

    def _alloc(self) -> None:
        if self.buf is None:
            self.buf = jnp.zeros((self.S, self.B, self.W), jnp.int32)

    def reset(self) -> None:
        """The queue mutated in a way the mirror cannot track (prepend,
        reorder, wholesale swap): drop the staged region. The buffer is
        kept — re-staging overwrites slots."""
        self.consumed = 0
        self.staged = 0

    def consume(self, n_entries: int, queue_len_after: int) -> None:
        """``n_entries`` popped from the queue front. An empty queue
        resets the frame for free (nothing staged is live), which also
        heals any partial-batch misalignment a final short tick left."""
        self.consumed += n_entries
        if queue_len_after == 0:
            self.reset()

    def available_batches(self) -> int:
        """Staged, unconsumed, alignment-verified batches from the
        queue head (0 when the consume cursor sits mid-batch — the
        driver then realigns via reset + top_up)."""
        if self.consumed % self.B:
            return 0
        return max(self.staged - self.consumed // self.B, 0)

    def free_slots(self) -> int:
        return self.S - (self.staged - self.consumed // self.B)

    def stage_tail(self, queue: List, entry_bytes: int,
                   offset: int, count: int) -> None:
        """Stage the queue's trailing PARTIAL batch (zero-padded) into
        the next free slot for the window about to launch, WITHOUT
        advancing the full-batch bookkeeping: the window consumes
        through it (emptying the queue resets the frame) or escapes
        (the next window rebuilds). ``offset`` is the queue position of
        the tail's first entry."""
        self._alloc()
        chunk = queue[offset:offset + count]
        words = np.zeros((self.B, self.W), np.int32)
        words[:count] = np.frombuffer(
            b"".join(p for _, p in chunk), np.uint8
        ).reshape(count, entry_bytes).view(np.int32)
        self.buf = _STAGE_JIT(
            self.buf, words, jnp.int32(self.staged % self.S)
        )
        self.stage_tail_events += 1

    def top_up(self, queue: List, entry_bytes: int,
               max_new: Optional[int] = None) -> int:
        """Stage as many unstaged full batches as fit (bounded by
        ``max_new`` — the submit hook stages at most the one batch the
        arriving entry completed, keeping submit latency flat). Bytes
        come straight from the queue's (seq, payload) tuples; the
        host→device copy happens HERE, on the caller's (client) side of
        the wall, which is the whole point of pre-packing."""
        if self.consumed % self.B:
            return 0
        if self.staged * self.B < self.consumed:
            # the tick path drained PAST the staged region (the ring
            # filled and fusion stayed ineligible — faults armed, not
            # steady — while ordinary ticks kept consuming): the frame
            # fell behind and the next staged offset would be negative.
            # Realign to the current queue head and re-stage from it.
            self.reset()
        self._alloc()
        B = self.B
        total = self.consumed + len(queue)
        staged_new = 0
        while (self.staged + 1) * B <= total and self.free_slots() > 0:
            if max_new is not None and staged_new >= max_new:
                break
            lo = self.staged * B - self.consumed     # queue offset
            chunk = queue[lo:lo + B]
            words = np.frombuffer(
                b"".join(p for _, p in chunk), np.uint8
            ).reshape(B, entry_bytes).view(np.int32)
            self.buf = _STAGE_JIT(
                self.buf, words, jnp.int32(self.staged % self.S)
            )
            self.staged += 1
            staged_new += 1
            self.stage_events += 1
        return staged_new


class FusedDriver:
    """Plans, dispatches, and books fused K-tick windows for one
    :class:`~raft_tpu.raft.engine.RaftEngine` (see module doc)."""

    #: minimum fused window: below 2 ticks the ordinary tick path is
    #: strictly cheaper (no window planning, no staging checks)
    MIN_TICKS = 2

    def __init__(self, engine):
        self.e = engine
        cfg = engine.cfg
        slots = max(4, min(2 * engine.fuse_k, 256))
        self.staging = StagingRing(cfg.batch_size, cfg.shard_words, slots)
        self._single_process = jax.process_count() == 1

    # ------------------------------------------------------ engine hooks
    def on_submit(self) -> None:
        """A submit appended to the queue: stage the batch it completed
        (if any) into the device ring — client-side cost, off the drain
        wall."""
        self.staging.top_up(self.e._queue, self.e.cfg.entry_bytes,
                            max_new=1)

    def on_consumed(self, n_entries: int) -> None:
        self.staging.consume(n_entries, len(self.e._queue))

    def on_queue_replaced(self) -> None:
        self.staging.reset()

    # ------------------------------------------------------- eligibility
    def _heap_bound(self, r: int, eff: np.ndarray) -> float:
        """Earliest heap event the fused window must NOT run past.
        Ignorable (no-op pops or provably-restale-armed timers):

        - stale-generation election/candidate timers (gen mismatch);
        - election timers of rows the window's FIRST tick re-arms
          (heard live member followers — any such timer is stale the
          moment tick 1's re-arm bumps the generation, exactly as in
          the tick-at-a-time run) and of rows whose pop is a no-op
          (dead / non-member: ``_fire_follower`` returns before any
          draw);
        - candidate timers while no candidate exists (eligibility
          guarantees none — the pop is a draw-free no-op);
        - leader-tick events of rows not in the leader role (draw-free
          no-op pops).

        Everything else — fault-plan events, a live unreachable
        member's election timer, unknown kinds — bounds the window.
        """
        e = self.e
        bound = float("inf")
        roles = e.roles
        for (te, _seq, kind, row) in e._q:
            tag, _, gen = kind.partition(":")
            if tag in ("e", "c"):
                if int(gen) != e._timer_gen[row]:
                    continue                     # stale: no-op pop
                if tag == "e" and (
                    not e.alive[row] or not e.member[row]
                    or (eff[row] and roles[row] == "follower"
                        and row != r)
                ):
                    continue
                if tag == "c" and roles[row] != "candidate":
                    continue
            elif tag == "l" and roles[row] != "leader":
                continue
            bound = min(bound, te)
        return bound

    # ------------------------------------------------------------- fire
    def fire(self, r: int, horizon: float) -> bool:
        """Handle the just-popped leader tick for ``r`` as a fused
        window when the eligibility proof holds; False hands the tick
        back to the ordinary ``_fire_leader_tick`` path untouched."""
        e = self.e
        cfg = e.cfg
        if cfg.ec_enabled or cfg.mirror_check_every:
            return False
        if not self._single_process:
            return False
        fused = getattr(e.t, "replicate_fused", None)
        if fused is None:
            return False
        ready = getattr(e.t, "fusion_ready", None)
        if ready is not None and not ready():
            return False
        if (e.leader_id != r or e.roles[r] != "leader"
                or not e.alive[r] or e.slow[r]):
            return False
        term = int(e.lead_terms[r])
        if int(e.terms[r]) > term or int(e.terms.max()) > term:
            return False
        if any(p != r and e.roles[p] != "follower"
               for p in range(cfg.rows)):
            return False
        if (e._staged_config or e._config_seqs
                or e._pending_config is not None or e.learner.any()):
            return False
        if cfg.steady_dispatch == "off" or not e._steady:
            return False
        if e.admission is not None and e.admission.shedding:
            # a shedding window's delay observations gate client-facing
            # refusals tick by tick; keep that on the scrutable path
            return False
        lasts = e._pre_lasts()
        if int(lasts[r]) != e.commit_watermark:
            return False
        eff = e._reach(r)
        live_members = e.alive & e.member
        if not eff[live_members].all():
            return False
        quorum = int(e.member.sum()) // 2 + 1
        if int((eff & e.member & ~e.slow).sum()) < quorum:
            return False
        # window bound: horizon and the heap. The window covers the
        # staged ingest PLUS trailing heartbeat ticks — the tick path
        # fires those at the same instants regardless of backlog, so
        # fusing them is faithful and amortises idle heartbeats too.
        B = cfg.batch_size
        q = len(e._queue)
        t0 = e.clock.now
        hb = cfg.heartbeat_period
        bound = self._heap_bound(r, eff)
        if bound <= t0:
            return False
        # Tick times are generated by the SAME incremental ``t + hb``
        # chain the tick path's heap pushes use — a closed-form
        # ``t0 + j*hb`` differs in the last float ulp, which would leak
        # into commit stamps and heap times (exactness pin).
        times = [t0]
        tj = t0
        while len(times) < 100_000:
            tj = tj + hb
            if tj > horizon or tj >= bound:
                break
            times.append(tj)
        n = len(times)
        if n < self.MIN_TICKS:
            return False
        # staging coverage for the ingest prefix (top up; rebuild when
        # the mirror went stale — misaligned consume, post-failover)
        st = self.staging
        full_need = min(q // B, n)
        if full_need:
            st.top_up(e._queue, cfg.entry_bytes)
            if st.available_batches() < full_need:
                st.reset()
                st.top_up(e._queue, cfg.entry_bytes)
        full_b = min(full_need, st.available_batches()) if full_need else 0
        counts = np.zeros(n, np.int32)
        counts[:full_b] = B
        tail = q - full_b * B
        staged_tail = 0
        if (0 < tail < B and full_b == q // B and full_b < n
                and st.free_slots() > 0):
            # the trailing partial batch rides the window's next tick
            # (the free-slot check keeps it from clobbering a staged,
            # unconsumed full batch when the ring is saturated)
            st.stage_tail(e._queue, cfg.entry_bytes, full_b * B, tail)
            counts[full_b] = tail
            staged_tail = tail
        if full_b * B + staged_tail < q:
            # the staging ring does not cover the whole backlog: the
            # window must END at its last covered ingest tick — a fused
            # heartbeat where the tick path would have ingested is a
            # divergence. The remainder drains via later windows.
            n = full_b + (1 if staged_tail else 0)
            if n < self.MIN_TICKS:
                return False
            counts = counts[:n]
            times = times[:n]
        st._alloc()   # a pure-heartbeat window still passes the ring
        #               operand (count-0 steps mask its content away)
        self._run_window(r, term, eff, times, counts)
        return True

    # ----------------------------------------------------------- window
    def _run_window(self, r: int, term: int, eff: np.ndarray,
                    times: List[float], counts: np.ndarray) -> None:
        """Dispatch the planned window as a chain of power-of-two-sized
        launches (≤ K ticks each; ``n_run`` masks a residual tail
        inside the last launch so the compiled-program set stays at
        ~log2(K) shapes) with the async pipeline: launch i+1 is
        dispatched — carrying launch i's ``halted`` flag as an
        unmaterialised device scalar — BEFORE launch i's booking blocks
        on its outputs, so host booking overlaps device compute and
        ``block_until_ready`` happens only at the booking boundary."""
        e = self.e
        cfg = e.cfg
        hp = e.hostprof
        st = self.staging
        # terms of heard rows reach the leader's before anything books
        # (they already hold it in the steady state; exact replay of
        # the tick path's pre-commit durability fence)
        e.terms[eff] = np.maximum(e.terms[eff], term)
        e._persist_votes()
        floor, fpt = e._floor_attest(r)
        member_arg = e._member_arg()
        eff_dev = jnp.asarray(eff)
        slow_dev = jnp.asarray(e.slow)
        lasts0 = np.asarray(e._pre_lasts()).copy()
        if hp is not None:
            hp.mark("host_pre")
        n = len(counts)
        win = _WindowBook(
            self, r, term, eff, times, int(lasts0[r]), floor,
        )
        win.set_window(n)
        halted = False
        start_batch = st.consumed // st.B
        prev = None
        pos = 0
        k = e.fuse_k
        while pos < n:
            left = n - pos
            size = 1 << (min(left, k).bit_length() - 1)
            if size < left and size * 2 <= k:
                size *= 2                 # round UP: mask the tail with
                #                           n_run instead of a 2nd launch
            n_run = min(left, size)
            cnt = np.zeros(size, np.int32)
            cnt[:n_run] = counts[pos:pos + n_run]
            # launch-boundary annotation: a nullcontext unless an
            # on-demand profiler capture is active (obs.profiling)
            with profiling.launch_annotation(
                "fused_window", e.fused_launches
            ):
                out = e.t.replicate_fused(
                    e.state, st.buf, start_batch % st.S,
                    jnp.asarray(cnt), n_run, halted, r, term, eff_dev,
                    slow_dev, member=member_arg, repair_floor=floor,
                    floor_prev_term=fpt,
                    ring=e._dev_ring,
                )
            if e._dev_ring is not None:
                (e.state, infos, escaped, ran, halted, e._dev_ring) = out
            else:
                e.state, infos, escaped, ran, halted = out
            e.fused_launches += 1
            if hp is not None:
                hp.mark("dispatch")
            if prev is not None:
                win.book_launch(*prev)
            prev = (infos, escaped, ran)
            start_batch += n_run
            pos += n_run
        win.book_launch(*prev)
        win.finish(lasts0)

    # --------------------------------------------------------- plumbing
    @property
    def slots(self) -> int:
        return self.staging.S


class _WindowBook:
    """EXACT host booking of one fused window: per-tick control-plane
    replay (clock, rng draws, heap counter, leases, admission
    observations, nodelog emissions) with the per-entry work vectorized
    per launch — see the module doc. One instance spans the window's
    pipelined launches."""

    def __init__(self, driver: FusedDriver, r: int, term: int,
                 eff: np.ndarray, times: List[float], last0: int,
                 floor: int):
        self.d = driver
        self.r = r
        self.term = term
        self.eff = eff
        self.times = times
        self.last = last0           # leader last_index booked so far
        self.floor = floor
        self.g = 0                  # global tick index in the window
        self.qpos = 0               # queue entries booked (consumed)
        self.halted = False         # no later launch may book (it ran
        #                             as a device no-op chain)
        self.stepped_down = False
        self.final_match = None
        self.confirmed = False

    # ---------------------------------------------------------- booking
    def book_launch(self, infos, escaped, ran) -> None:
        e = self.d.e
        hp = e.hostprof
        if self.halted:
            # the halted flag was threaded into this launch on device:
            # it ran as a no-op chain; there is nothing to book
            return
        if hp is not None:
            hp.sync(infos.commit_index, escaped, ran)
        ci = np.asarray(infos.commit_index)
        fl = np.asarray(infos.frontier_len)
        mt = np.asarray(infos.max_term)
        match = np.asarray(infos.match)
        esc = np.asarray(escaped)
        rn = np.asarray(ran)
        e._flush_device_obs()
        n_run = int(rn.sum())
        for j in range(n_run):
            last_exec = (j == n_run - 1) and bool(esc[j])
            self._book_tick(
                int(ci[j]), int(fl[j]), int(mt[j]), match[j],
                escape=last_exec,
            )
            if self.halted:
                return
        if n_run:
            self.final_match = match[n_run - 1]

    def _book_tick(self, commit: int, frontier: int, max_term: int,
                   match: np.ndarray, escape: bool) -> None:
        """Replay ONE fused tick's host bookkeeping, in the exact order
        ``_fire_leader_tick`` performs it."""
        d = self.d
        e = d.e
        cfg = e.cfg
        r = self.r
        term = self.term
        hb = cfg.heartbeat_period
        t_j = self.times[self.g]
        e.clock.now = max(e.clock.now, t_j)
        e._tick_count += 1
        e.fused_ticks += 1
        e._metric_inc("raft_heartbeat_ticks_total")
        if cfg.check_quorum:
            # the voter quorum is reachable by the eligibility proof:
            # the lease renews exactly as the tick path's branch would
            e._quorum_contact_at[r] = t_j
        if e.admission is not None:
            head_delay = 0.0
            if self.qpos < len(e._queue):
                head_seq = e._queue[self.qpos][0]
                head_delay = t_j - e.submit_time.get(head_seq, t_j)
            transition = e.admission.observe_delay(head_delay)
            if transition == "shed_start":
                e._nodelog_at(
                    r, f"admission shedding ON (head delay "
                    f"{head_delay:.1f}s >= target "
                    f"{e.admission.target_delay_s:g}s for a full "
                    f"interval)", e.commit_watermark, self.last,
                )
            elif transition == "shed_stop":
                e._nodelog_at(
                    r, "admission shedding OFF (delay back under "
                    "target)", e.commit_watermark, self.last,
                )
        if self.g > 0 and e.recorder is not None:
            # the tick path fires the repair_floor_raise event inside
            # tick j's PRE-DISPATCH _floor_attest, computed from the
            # previous tick's end-of-step last (_pre_lasts) — replay at
            # the same position with the same value (tick 0's event was
            # already fired by _run_window's own _floor_attest call)
            self._replay_floor_event(self.last)
        if escape and max_term > term:
            # the step that surfaced a higher term: the tick path books
            # NOTHING from it (no ingest mapping, no commit, no timer
            # re-arm, no next-tick push, no steady update — the flag
            # goes stale exactly as it would there, and the next
            # election win resets it) and steps the leader down
            self.g += 1
            e._step_down_leader(r, max_term)
            self.stepped_down = True
            self.halted = True
            return
        chunk = e._queue[self.qpos:self.qpos + frontier]
        new_last = self.last + frontier
        if frontier and commit >= new_last:
            # the whole batch committed inside its own tick — the
            # steady common case: stamp + archive + watermark in one
            # vectorized pass, skipping the _uncommitted/_seq_at_index
            # round-trip entirely (the entries were never observable
            # as uncommitted)
            self._book_committed_batch(chunk, t_j, new_last, commit)
        elif frontier:
            # escape tick with a partial / uncommitted ingest: the slow
            # path books exactly what the tick path would
            for i, (seq, p) in enumerate(chunk):
                idx = self.last + 1 + i
                e._seq_at_index[idx] = seq
                e._uncommitted[idx] = (p, term)
                if e.spans is not None:
                    e.spans.note_ingest(seq, idx, t_j, e._tick_count)
            e._advance_commit(r, commit)
        self.qpos += frontier
        self.last = new_last
        if escape:
            # the tick path's _update_steady, replayed from this tick's
            # verified match against the post-ingest leader tail
            others = self.eff & ~e.slow
            others[self.r] = False
            e._steady = bool((match[others] >= new_last).all())
        if not self.confirmed and max_term <= term:
            e._confirm_reads(r, term, self.eff, max_term)
            #   _confirm_reads also renews the leader lease; later
            #   fused ticks renew explicitly below so the lease clock
            #   advances tick by tick exactly as the unfused path's
            #   per-tick confirmation would drive it — fusion and
            #   zero-round lease reads compose instead of the window
            #   aging the lease by K ticks at once
            self.confirmed = True
        elif max_term <= term:
            e._lease_renew(r, term, self.eff, max_term)
        e._reset_heard_timers(r)
        self.g += 1
        if escape or self.g == self._n_ticks:
            # the LAST EXECUTED tick pushes the real next leader tick
            e._push(t_j + hb, "l:x", r)
        else:
            # intermediate ticks' pushes are popped by the next fused
            # tick: replay only the tiebreak counter the push+pop pair
            # would have advanced
            e._seq_events += 1
        if escape:
            self.halted = True   # window over: later launches ran as
            #                      device no-op chains, nothing to book

    def set_window(self, n_ticks: int) -> None:
        self._n_ticks = n_ticks

    def _book_committed_batch(self, chunk, t_j: float, new_last: int,
                              commit: int) -> None:
        e = self.d.e
        r = self.r
        term = self.term
        n = len(chunk)
        s0, sl = chunk[0][0], chunk[-1][0]
        if (e.spans is None and e.metrics is None and e.slo is None
                and sl - s0 + 1 == n):
            e.commit_time.update(dict.fromkeys(range(s0, sl + 1), t_j))
        else:
            slo_lat = [] if e.slo is not None else None
            for i, (seq, p) in enumerate(chunk):
                e.commit_time[seq] = t_j
                if e.spans is not None:
                    e.spans.note_ingest(
                        seq, new_last - n + 1 + i, t_j, e._tick_count
                    )
                    e.spans.note_commit(seq, t_j, e._tick_count)
                if e.metrics is not None:
                    e._metric_inc("raft_commits_total")
                    e.metrics.histogram(
                        "raft_commit_latency_seconds",
                        "submit -> durable, virtual seconds", ("group",),
                    ).observe(
                        t_j - e.submit_time.get(seq, t_j), group="0",
                    )
                if slo_lat is not None:
                    slo_lat.append(t_j - e.submit_time.get(seq, t_j))
            if slo_lat:
                e.slo.observe_batch("commit", slo_lat, t_j)
        e.committed_total += n
        e.store.put_span(new_last - n + 1, chunk, term, pick=1)
        if e.auditor is not None:
            # span-granularity audit feed, O(1) per launch like
            # put_span: entries resolve lazily inside the auditor
            e.auditor.note_entry_span(
                new_last - n + 1, chunk, term, t_j, pick=1
            )
            e.auditor.note_commit(commit, t_j)
        if commit > e._row_commit[r]:
            e._row_commit[r] = commit
        e._lease_ok_term[r] = term
        #   the fused batch commit IS a current-term watermark advance
        #   riding r's own round — mirror _advance_commit's lease gate
        e.commit_watermark = commit
        e._nodelog_at(r, f"commit index changed to {commit}",
                      commit, new_last, kind="commit")
        e._evict_commit_stamps()
        e._drain_apply()

    def _replay_floor_event(self, last: int) -> None:
        """The tick path's ``_floor_attest`` records a recorder-only
        event when the lap horizon raises the repair floor past the
        high-water mark; replay it at the tick where it would fire."""
        e = self.d.e
        r = self.r
        cap = e.state.capacity
        lap = last - cap + 1
        floor = max(int(e._ring_floor[r]), lap)
        if floor > 1 and floor > e._floor_event_hwm.get(r, 0):
            e._floor_event_hwm[r] = floor
            e._record_event(
                r, "repair_floor_raise", floor=floor, lap_horizon=lap,
                ring_floor=int(e._ring_floor[r]),
            )

    # ------------------------------------------------------------ close
    def finish(self, lasts0: np.ndarray) -> None:
        """Window epilogue: consume the booked queue prefix, retire the
        staging mirror, refresh the host snapshots, and re-derive the
        steady flag from the final tick's verified match — all the
        state the tick path maintains incrementally."""
        d = self.d
        e = d.e
        if self.qpos:
            e._queue = e._queue[self.qpos:]
            d.staging.consume(self.qpos, len(e._queue))
        e._note_truncations(lasts0)
        if self.stepped_down:
            return
        if not self.halted and self.final_match is not None:
            others = self.eff & ~e.slow
            others[self.r] = False
            e._steady = bool(
                (self.final_match[others] >= self.last).all()
            )
