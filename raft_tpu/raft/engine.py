"""The cluster engine: timers + roles on host, protocol steps on device.

Capability map to the reference (SURVEY.md §1, §3):

- ``Run()`` role trampoline (main.go:98-109)       -> ``roles[]`` + the event
  loop: each replica's role is host metadata; transitions happen when timer
  events fire or device-step results (``max_term``) demand them.
- follower election timeout (main.go:114, 171-177) -> ``_fire_follower``:
  role -> candidate, term+1, a device vote round (``vote_step``).
- candidate round + majority (main.go:253-284)     -> ``_campaign``: one
  collective vote step replaces the serial peer poll; majority promotes to
  leader and triggers an immediate authority heartbeat.
- leader 2 s tick (main.go:332-395)                -> ``_fire_leader_tick``:
  drain up to one batch from the client queue, run one replicate step
  (ingest + repair + replicate + quorum commit fused on device).
- leader step-down (main.go:309-321)               -> after any step, if
  ``info.max_term`` exceeds the leader's term the leader reverts to
  follower (the reference learns this from an AppendEntries with a higher
  term; here the term rides the same collective).
- client loop (main.go:87-95)                      -> ``submit()`` queues
  payloads; unlike the reference's fire-and-forget client (which never gets
  a reply — comment main.go:330), ``submit`` returns a sequence number and
  ``commit_watermark`` tells the client when it is durable.

Beyond reference parity, the client surface the reference never offers:

- ``submit_pipelined``   — chunked compiled-scan ingest, one host sync per
  ~capacity/batch steps (SURVEY §7 hard part 1);
- ``committed_entries``  — committed-range reads (EC decodes from any k
  live shard rows);
- ``register_apply``     — ordered exactly-once apply stream (the state
  machine the reference lacks; see raft_tpu.examples.ReplicatedKV);
- ``save_checkpoint`` / ``restore`` — whole-process durable restart (the
  persistence main.go:18-21 only comments about);
- ``vote_log=`` — transition-time (term, votedFor) durability: a
  write-ahead record fsync'd before the engine acts on any vote round,
  term adoption, or step-down, so a crash between a vote and the next
  checkpoint cannot double-vote (ckpt.votelog has the fence argument).

Timers run on a virtual clock by default — tests and differential runs are
deterministic and fast (no 10-29 s waits); the live demo (raft_tpu.demo)
paces the event heap against wall time.
"""

from __future__ import annotations

import heapq
import os
import random
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.admission import AdmissionGate, Overloaded
from raft_tpu.config import RaftConfig
from raft_tpu.core.state import NO_VOTE, ReplicaState, fold_batch
from raft_tpu.obs import blackbox
from raft_tpu.obs import profiling as _profiling
from raft_tpu.transport.base import Transport, make_transport

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


def _pipeline_backend_ok() -> bool:
    """The single-launch pipeline chunk runs on REAL hardware only —
    deliberately stricter than ``ring._pallas_ok``: an engine chunk spans
    the whole ring, so the flight always revisits destination blocks,
    which interpret mode cannot model under in-place aliasing (bench.py's
    lap gate asserts the regime on hardware; CI covers the engine gate
    and bookkeeping through a transport shim that patches this hook)."""
    import jax

    return jax.default_backend() == "tpu"


class LinearizableReadRefused(Exception):
    # deliberately NOT a RuntimeError: ReplicatedKV.linearizable_get's
    # other failure mode (apply stream paused behind an archive gap)
    # raises RuntimeError, and the two demand different recovery actions
    # (retry against the real leader vs wait for the gap to heal) — the
    # types must stay distinguishable by `except` clause.
    """``read_linearizable`` could not confirm leadership: the caller is
    not leader, was deposed during the confirmation round, or cannot
    reach a quorum of the configuration (e.g. a minority-side leader
    during a partition). The read must be retried against the real
    leader — serving it here could return stale state."""


class TicketEvicted(LinearizableReadRefused):
    """A ``submit_read`` ticket was FIFO-evicted at the outstanding-ticket
    cap (2^16) before it was polled. Subclasses
    ``LinearizableReadRefused`` because the recovery action is the same —
    re-issue the read — but kept distinct so a client can tell "my
    binding died" from "I fell off the queue under fan-out pressure"
    (multi-group routers multiply outstanding tickets). Tickets are
    poll-once: a ticket already consumed by ``read_confirmed`` that is
    re-polled after the eviction floor passed it also reads as evicted,
    not ``KeyError`` — indistinguishable by design, identical action."""


class LearnerLagging(RuntimeError):
    """``promote`` refused: the learner's current-term verified match is
    still more than ``cfg.promote_max_lag`` entries behind the leader's
    last index. Promoting now would let a far-behind row count against
    the commit quorum — the availability regression the learner phase
    exists to prevent (dissertation §4.2.1). Retry once replication /
    snapshot install has caught the learner up; the engine's own staged
    promotion (``add_server`` / ``replace``) retries every leader tick."""


class MirrorDesyncError(Exception):
    """The mirrored multihost control planes' decision streams diverged
    (``RaftConfig.mirror_check_every``): a fail-stop with both digests
    in the message, instead of the silent wrong collective or hang a
    divergence would otherwise become. Recovery is a process-group
    restart from stable storage (transport.reform) — the in-memory
    control state of at least one process is untrustworthy."""


class VirtualClock:
    """Deterministic time source; the engine advances it to each event."""

    def __init__(self) -> None:
        self.now = 0.0

    def __call__(self) -> float:
        return self.now


class RaftEngine:
    """One process hosting all replica control planes.

    ``READ_TICKET_CAP``: outstanding ``submit_read`` tickets retained
    before FIFO eviction (evicted tickets poll as ``TicketEvicted``).
    Class attribute so tests exercise the eviction path at test-sized
    volume.

    The reference runs one goroutine per node against shared channels; here
    one host thread owns every replica's timers and roles, and the *data*
    plane (all replicas' state transitions) is the batched device program.
    Fault masks (``alive``/``slow``) are first-class: a "dead" replica's
    timers do not fire and the device step ignores it, which is exactly how
    the reference's only failure mode (a silent node) manifests. Beyond
    those, ``connectivity`` expresses link-level partitions (split-brain;
    see ``partition``/``heal_partition``) and ``member`` the current
    configuration (live add/remove via ``add_server``/``remove_server``).
    On a multihost transport, run one engine per process with the same
    config: mirrored deterministic event loops issue identical collective
    launches (transport.multihost).
    """

    READ_TICKET_CAP = 1 << 16
    READ_TICKET_TTL_FACTOR = 3.0
    #   With admission configured, a ticket idle this many max election
    #   timeouts is treated as abandoned and evicted at the gate (see
    #   submit_read) — the age analogue of the FIFO cap, which a smaller
    #   admission bound can never reach.

    def __init__(
        self,
        cfg: RaftConfig,
        transport: Optional[Transport] = None,
        trace: Optional[Callable[[str], None]] = None,
        vote_log: Optional[str] = None,
        recorder=None,
    ):
        self.cfg = cfg
        self.t: Transport = transport if transport is not None else make_transport(cfg)
        self._fetch = getattr(self.t, "fetch", np.asarray)
        #   Host view of device values. On a multi-process (multihost)
        #   transport this is a COLLECTIVE (reshard-to-replicated), legal
        #   because every process runs this engine as a mirrored
        #   deterministic event loop — same seed, same heap, identical
        #   launches (see transport.multihost / tests/test_multiprocess).
        self.state: ReplicaState = self.t.init()
        self.rng = random.Random(cfg.seed)
        self.clock = VirtualClock()
        self._trace = trace
        self.recorder = recorder
        #   obs.events.FlightRecorder (None = off): every nodelog call
        #   site records a typed Event whose ``.nodelog()`` rendering is
        #   byte-identical to the legacy trace line, plus the
        #   previously-silent transitions (_record_event). With neither
        #   a recorder nor a trace callback attached, nodelog skips its
        #   device fetch entirely — the disabled path costs no syncs.
        self.spans = None
        #   obs.spans.SpanTracker (None = off): causal per-op tracing —
        #   submit/submit_read bind the ambient span to their seq or
        #   ticket; ingest/commit/apply annotate it (docs/OBSERVABILITY).
        self.metrics = None
        #   obs.registry.MetricsRegistry (None = off): protocol counters
        #   (elections, heartbeats, repair rounds, sheds, commit-latency
        #   histogram), labeled group="0" for the single-group engine.
        self.hostprof = None
        #   obs.hostprof.HostProfiler (None = off): per-tick host-time
        #   attribution — phase timers tiling step_event (heap_pop,
        #   host_pre, pack, dispatch, device_wait, host_post). Detached
        #   costs one None check per site and performs ZERO extra device
        #   syncs: the profiler's block_until_ready lives only behind
        #   HostProfiler.sync, which no detached path calls (pinned by
        #   tests/test_perf_obs.py, like the nodelog no-fetch pin).
        self.auditor = None
        #   obs.audit.SafetyAuditor (None = off): the online safety
        #   plane — guarded host-side hooks at election wins, commit
        #   advances, archive feeds and tick boundaries check Raft
        #   invariants (one leader per term, monotone commit/terms,
        #   committed-prefix immutability) DURING the run. Pure host
        #   arithmetic over mirrors the engine already maintains: no
        #   device fetches, determinism-neutral (docs/OBSERVABILITY.md
        #   "Online plane").
        self.slo = None
        #   obs.slo.SloTracker (None = off): streaming latency digests
        #   (commit / read / queue-delay) with multi-window burn-rate
        #   SLO evaluation on the virtual clock. Same contract: guarded
        #   host-side observes, zero extra device syncs.
        self.status_board = None
        #   obs.serve.StatusBoard (None = off): the engine publishes an
        #   immutable host-mirror snapshot at each event-loop flush
        #   boundary; the ops HTTP server (obs.serve.OpsServer) reads
        #   it lock-free from its own thread.
        self.device_obs = None
        #   obs.device.DeviceObs (None = off): the device-resident
        #   observability plane — attach_device_obs allocates an
        #   in-kernel EventRing the replicate/vote launches thread
        #   through (record=True step programs), and every launch
        #   boundary flushes ONE packed fetch of ring + counters into
        #   this host accumulator. Detached costs zero extra device
        #   syncs and dispatches the exact pre-instrumentation programs
        #   (HLO-identity pinned by tests/test_device_obs.py).
        self._dev_ring = None
        self._dev_flushed = 0
        self._dev_counters_folded = None
        self._tick_count = 0
        #   Leader ticks fired so far — the replication-round clock the
        #   span tracker diffs for rounds-to-commit (always maintained:
        #   one int increment, determinism-neutral either way).

        n = cfg.rows
        self.member = np.zeros(n, bool)
        self.member[: cfg.n_replicas] = True
        #   Current configuration (dissertation-§4 single-server change):
        #   rows beyond the initial n_replicas idle masked-out until
        #   add_server commits them in. Quorums are counted over members
        #   (the device step receives the mask for its denominator; the
        #   engine composes it into every reach mask).
        self.learner = np.zeros(n, bool)
        #   Non-voting learners (dissertation §4.2.1): rows that receive
        #   replication, repair and snapshot install (they ride the
        #   replication reach mask) but are excluded from vote reach,
        #   commit counting and CheckQuorum. ``promote`` turns a
        #   caught-up learner into a voter via an ordinary configuration
        #   entry; ``add_server`` is learner-then-promote.
        self._wiped = np.zeros(n, bool)
        #   Rows whose durable identity was destroyed by ``wipe`` while
        #   still a configured VOTER. Such a row must never run again
        #   under its old identity (it may have voted or acked durably —
        #   restarting it amnesiac is the classic double-vote /
        #   lost-ack hazard); ``recover`` refuses until the row has been
        #   removed from the configuration (``replace``), after which it
        #   may rejoin as a fresh learner.
        self._staged_config: List[Tuple[str, int]] = []
        #   Deferred single-server steps ("add_learner" / "promote",
        #   row): the learner-then-promote ladder of ``add_server`` and
        #   the remove→add_learner→promote ladder of ``replace``. The
        #   routed leader tick drives the head whenever no change is in
        #   flight; a lagging learner's "promote" simply waits
        #   (LearnerLagging) until catch-up. Host-only state: lost on a
        #   whole-process restart like any other in-flight intent (the
        #   operator re-issues; committed config state is durable).
        self.roles: List[str] = [FOLLOWER] * n
        self.terms = np.zeros(n, np.int64)     # host mirror for timer logic
        self.lead_terms = np.zeros(n, np.int64)
        #   The term each replica last won an election in. Distinct from
        #   ``terms`` (highest term SEEN): a split-brain stale leader keeps
        #   ticking in its lead term, and hearing any higher term — which
        #   raises ``terms[r]`` past ``lead_terms[r]`` via another step's
        #   adoption — is exactly the step-down condition (main.go:309-321).
        self.alive = np.ones(n, bool)
        self.slow = np.zeros(n, bool)
        self.connectivity = np.ones((n, n), bool)
        #   Link-level reachability (partition fault mode): replica a can
        #   exchange messages with b iff connectivity[a, b]. Composed with
        #   ``alive`` into each step's effective mask — the device program
        #   is unchanged; a partitioned-away row neither hears windows or
        #   votes nor reports acks or terms back (core.step masks
        #   max_term by the same mask).
        self.leader_id: Optional[int] = None
        self.leader_term = 0
        self._last_heard = np.full(n, -1e18)
        #   When each replica last heard a leader's traffic (virtual
        #   clock) — the §9.6 leader-stickiness evidence for PreVote.
        self._mirror_digest = 0
        self._mirror_decisions = 0
        #   Rolling CRC of the decision stream + check cadence counter
        #   (multihost mirror desync guard — _mirror_digest_step).
        self._reads: Dict[int, list] = {}
        self._next_read_ticket = 0
        #   Batched ReadIndex queue: ticket -> [row, noted index, bound
        #   term, status, mint time] (submit_read / read_confirmed /
        #   _confirm_reads; the mint time drives the admission-path
        #   idle-TTL eviction).
        self._read_buckets: Dict[Tuple[int, int], set] = {}
        #   (row, bound term) -> pending tickets. A confirming quorum
        #   round touches exactly its own (r, term) bucket instead of
        #   walking all (up to 2^16) outstanding tickets per tick.
        self._read_evict_floor = 0
        #   Every ticket below this was either consumed or FIFO-evicted;
        #   polling one raises TicketEvicted, not an opaque KeyError.
        self._quorum_contact_at: Dict[int, float] = {}
        #   Per-leader: when it last contacted a member majority
        #   (CheckQuorum's lease clock).
        self.commit_watermark = 0                  # committed LOG INDEX
        self.submit_time: Dict[int, float] = {}    # seq -> submit time
        self.commit_time: Dict[int, float] = {}    # seq -> commit time
        #   (commit_time[s] - submit_time[s] is the per-entry commit latency
        #    the obs package histograms — the BASELINE p50/p99 metric)
        self.committed_total = 0
        #   All-time committed-entry count: ``commit_time`` itself is
        #   BOUNDED (the host_post residue ROADMAP item 2 left behind —
        #   per-entry stamps grew without bound over a long run). Stamps
        #   are evicted oldest-first past ``_commit_stamp_cap``,
        #   mirroring the CheckpointStore's floor-aware retention; the
        #   durability answer for evicted committed seqs survives in
        #   ``_durable_ranges`` (merged seq intervals — tiny: one
        #   interval per loss gap), so ``is_durable`` still answers for
        #   every seq ever issued.
        self.commit_stamps_evicted = 0
        self._commit_stamp_cap = 2 * cfg.log_capacity
        self._durable_ranges: List[List[int]] = []
        self._seq_at_index: Dict[int, int] = {}    # log index -> client seq
        #   Mapped at ingestion time, because log indices and sequence
        #   numbers diverge once a leadership change drops queued entries.
        self._hb_payload = None                    # cached all-zero batch
        if cfg.ec_enabled:
            from raft_tpu.ec.rs import RSCode

            # Provisioned for the FULL row headroom (config.py): shard i
            # lives on row i forever; membership changes never re-shard.
            self._code = RSCode(cfg.rows, cfg.rs_k)
        else:
            self._code = None
        self._uncommitted: Dict[int, Tuple[bytes, int]] = {}
        #   log index -> (full payload, ingest term). Two consumers: under
        #   EC, recovered replicas are re-served the uncommitted suffix from
        #   here (fewer than commit_quorum replicas hold those shards, so
        #   reconstruction can't — otherwise a dead-and-back follower pair
        #   would stall commit forever at the k+margin quorum); in both
        #   modes, entries move from here into the checkpoint store when
        #   they commit. Bounded by ring backpressure:
        #   leader_last - commit <= log_capacity entries.
        from raft_tpu.ckpt import CheckpointStore, SnapshotShipper

        tiered_root = (
            os.environ.get("RAFT_TPU_TIERED_DIR", "") or cfg.tiered_log_dir
        )
        if tiered_root:
            # Tiered archive (ckpt.tiered, ROADMAP item 6): hot tail in
            # RAM, sealed RS-coded segments on disk — coverage reaches
            # the whole history while RAM stays bounded. Each engine
            # seals under its own fresh subdirectory: segments are an
            # engine-lifetime cache of durable state (a restore rebuilds
            # its archive from the checkpoint, not the old generation's
            # segment files). Env override mirrors RAFT_TPU_FUSE_K so
            # chaos/torture runs flip the tier without config edits —
            # replays are pinned byte-identical either way.
            import tempfile

            from raft_tpu.ckpt import TieredStore

            os.makedirs(tiered_root, exist_ok=True)
            hot = cfg.tiered_hot_entries or 2 * cfg.log_capacity
            self.store: CheckpointStore = TieredStore(
                cfg.entry_bytes,
                root=tempfile.mkdtemp(prefix="tier_", dir=tiered_root),
                hot_entries=hot,
                segment_entries=min(hot, (
                    cfg.segment_entries
                    or max(1, cfg.log_capacity // 2)
                )),
                rs_k=cfg.segment_rs_k,
                rs_m=cfg.segment_rs_m,
                on_seal=self._note_seal,
                checkpoint_span=2 * cfg.log_capacity,
            )
        else:
            self.store = CheckpointStore(
                cfg.entry_bytes, max_entries=2 * cfg.log_capacity
            )
        #   Host archive of the committed log (term + bytes per entry) —
        #   the "persistent data" the reference comments but never writes
        #   (main.go:18-21). Snapshot-installs for ring-lapped replicas are
        #   served from it (raft_tpu.ckpt). Both snapshot consumers clamp
        #   their range to the last log_capacity entries, so the plain
        #   store compacts beyond 2x that instead of growing without
        #   bound; the tiered store seals the same horizon to disk
        #   instead, keeping full-history coverage at bounded RAM.
        self._tiered_store = self.store if tiered_root else None
        #   non-None when the archive is tiered: the apply-cursor seal
        #   ceiling and the /status tier section key off it
        self._shipper = SnapshotShipper(
            cfg.catchup_chunk_entries or cfg.batch_size
        )
        #   Incremental snapshot shipping (ckpt.ship): lapped replicas
        #   catch up in admission-budgeted chunks per leader tick
        #   instead of one monolithic install — see _stream_snapshot.
        self._lasts_snapshot = None   # see _pre_lasts
        self._match_snapshot = None
        #   cached (match_index, match_term) host pair for
        #   _effective_match — same lifetime as _lasts_snapshot:
        #   refreshed lazily, dropped whenever a step or host-side
        #   mutation moves match state
        self._term_floor = 1   # first log index of the current leader's
        #   term (dissertation §5.4.2 gate for the fused steady program,
        #   core.step_pallas): set to last_index+1 on every election win,
        #   clamped down when a truncation drops the tail below it.
        #   Meaningless while no leader is elected (nothing dispatches).
        self._ring_floor = np.ones(n, np.int64)
        #   Per-replica smallest log index whose ring slot is guaranteed to
        #   hold that entry's real bytes. Normally 1 (rings fill from
        #   index 1), but a snapshot install seeds a replica's ring only
        #   from the snapshot tail's start: slots below it still hold init
        #   zeros (or pre-install leftovers), and a committed-range read
        #   from them would return garbage labeled as committed data.
        self._floor_event_hwm: Dict[int, int] = {}
        #   Highest repair floor already reported to the flight recorder
        #   per leader row (the floor is recomputed every tick; the
        #   EVENT fires only when it rises).
        self._match_stall = [0] * n
        #   Consecutive leader ticks each replica has sat below the ring
        #   horizon without match progress. After a leadership change every
        #   match legitimately resets to 0 and the repair window re-verifies
        #   healthy replicas within a tick or two; only a replica that
        #   STAYS stalled under the horizon is truly lapped and needs a
        #   snapshot install.

        self._steady = False
        #   True when the last replicate step showed every live non-slow
        #   follower fully caught up: the next step may run the
        #   steady-state program (repair window compiled out, ~10% faster).
        #   Conservatively cleared by every event that can create a
        #   straggler (recover, slow toggles, leadership change) — a wrong
        #   True only delays repair by one tick (liveness, never safety).
        self._apply_fns: List[Tuple[Callable[[int, bytes], None], int]] = []
        #   (callback, first index it receives) — per-registrant starts so
        #   a late replay=False joiner never sees history that was merely
        #   paused behind an archive gap at its registration time
        self.applied_index = 0
        #   State-machine apply cursor (see register_apply). The reference
        #   HAS no state machine — values are stored, never applied
        #   (SURVEY §2, main.go:149) — so this hook is what turns the
        #   replicated log into a replicated state machine.
        self._lost_gaps: set = set()   # unrecoverable apply gaps, logged once
        self._queue: List[Tuple[int, bytes]] = []  # pending (seq, payload)
        self.fuse_k = max(
            1, int(os.environ.get("RAFT_TPU_FUSE_K", "") or cfg.fuse_k)
        )
        #   K-tick steady-state fusion (ROADMAP item 2; raft.steady):
        #   >1 lets ``run_for``-driven drains fuse runs of consecutive
        #   steady leader ticks into single compiled scan launches. The
        #   env override exists so chaos/torture runners can be pointed
        #   at the fused path without touching configs — replays are
        #   pinned byte-identical either way.
        self.fused_launches = 0
        self.fused_ticks = 0
        self._fused_driver = None
        if self.fuse_k > 1:
            from raft_tpu.raft.steady import FusedDriver

            self._fused_driver = FusedDriver(self)
        self.lease = None
        if cfg.read_lease:
            from raft_tpu.raft.lease import LeaseTable

            # Leader leases (raft.lease; docs/READS.md): every quorum
            # round grants, and a valid lease serves linearizable reads
            # locally with zero replication rounds. VOLATILE by design:
            # a restored engine starts with no grants.
            self.lease = LeaseTable(
                cfg.follower_timeout[0], cfg.clock_drift_bound
            )
        self._row_commit = np.zeros(n, np.int64)
        #   Per-row mirror of the commit index each row's OWN rounds
        #   last reported — a stale split-brain leader's entry freezes
        #   at partition time while the global commit_watermark follows
        #   the majority. Lease reads serve at THIS index (the leader's
        #   local knowledge), which is exactly what makes the clock-skew
        #   falsifiability story honest: a broken lease serves a frozen
        #   index as if it were fresh.
        self._lease_ok_term = np.full(n, -1, np.int64)
        #   §6.4's "leader must have committed an entry in its term"
        #   gate: lease serves only once a watermark advance rode one of
        #   r's own rounds in its current lead term (Leader Completeness
        #   then puts every previously-acked write below _row_commit[r]).
        self.read_class_counts: Dict[str, int] = {}
        #   served reads by class (lease / read_index / ...): the
        #   /status ``reads`` section and the raft_reads_total{class}
        #   counter's host-side twin (always maintained — plain ints).
        self.admission = AdmissionGate.from_config(cfg, self.clock)
        #   Bounded admission (raft_tpu.admission; None = legacy
        #   unbounded): submit/submit_read arrivals pass the gate before
        #   anything is queued, and the leader tick feeds the gate the
        #   head-of-queue sojourn for the CoDel delay controller. The
        #   depth bound governs ADMISSION — entries re-queued by failover
        #   truncation were already admitted once and may transiently
        #   push the queue past it (they are re-queued, never re-shed).
        self._config_seqs: Dict[int, Tuple[tuple, tuple]] = {}
        #   seq -> (old member mask, new member mask) for in-flight
        #   configuration-change entries (add_server / remove_server)
        self._pending_config: Optional[Tuple[int, tuple, tuple, int]] = None
        #   (log index, old mask, new mask, ingest term) of the one
        #   uncommitted change
        self._fault_events: list = []              # FaultPlan merge targets
        self._next_seq = 1
        self._q: List[Tuple[float, int, str, int]] = []   # (t, tiebreak, kind, replica)
        self._seq_events = 0
        self._timer_gen = [0] * n
        self._votelog = None
        self._persisted_terms = np.zeros(n, np.int64)
        self._persisted_vf = np.full(n, NO_VOTE, np.int64)
        if vote_log is not None:
            # Transition-time durability (ckpt.votelog): replay any
            # existing records into the fresh state — a restarted process
            # must not vote twice in a term it voted in, even with no
            # checkpoint between the vote and the crash — then keep
            # appending at every (term, votedFor) transition.
            from raft_tpu.ckpt import VoteLog, merge_restored

            terms = self.terms.copy()
            vf = self._fetch(self.state.voted_for).astype(np.int64)
            terms, vf = merge_restored(n, terms, vf, vote_log)
            if (terms != self.terms).any() or (
                vf != self._fetch(self.state.voted_for)
            ).any():
                self.state = self.state.replace(
                    term=jnp.asarray(terms, self.state.term.dtype),
                    voted_for=jnp.asarray(vf, self.state.voted_for.dtype),
                )
                self.terms = terms
                for r in range(n):
                    self.nodelog(r, "vote log replayed")
            self._attach_votelog(vote_log)
        for r in range(n):
            if self.member[r]:
                self._arm_follower(r)

    # ------------------------------------------------------------------ util
    def _nodelog_at(self, r: int, msg: str, commit: int, last: int,
                    kind: Optional[str] = None, **fields) -> str:
        """``nodelog`` with caller-supplied commit/last values — the
        fused-window booking replay's emission path (the per-tick state
        is reconstructed from the launch's stacked infos, so no device
        fetch happens mid-booking). Rendering and recorder schema are
        byte-identical to :meth:`nodelog`'s."""
        rec = self.recorder
        if rec is None and self._trace is None:
            return ""
        line = (
            f"[Server{r}:{self.terms[r]}:{commit}:{last}]"
            f"[{self.roles[r]}]{msg}"
        )
        if rec is not None:
            rec.record(
                node=f"Server{r}", term=int(self.terms[r]), kind=kind,
                t_virtual=self.clock.now, state=self.roles[r],
                commit_index=commit, last_index=last, msg=msg, **fields,
            )
        if self._trace is not None:
            self._trace(line)
        return line

    def nodelog(self, r: int, msg: str, kind: Optional[str] = None,
                **fields) -> str:
        """The reference's trace schema (main.go:399-401) — the differential
        join key: [Id:Term:CommitIndex:LastApplied][state]msg.

        With a flight recorder attached the same emission records a typed
        ``obs.events.Event`` (``kind`` explicit or classified from the
        message; the legacy line is exactly ``Event.nodelog()``). With
        NEITHER sink attached the device fetch is skipped — observability
        off costs no device syncs (on a multihost transport the fetch is
        a collective, so sinks must be attached symmetrically across
        processes, as the mirrored event loop already requires)."""
        rec = self.recorder
        if rec is None and self._trace is None:
            return ""
        ci_li = self._fetch(
            jnp.stack([self.state.commit_index, self.state.last_index])
        )   # one fetch (a collective on multihost) for both fields
        line = (
            f"[Server{r}:{self.terms[r]}:{int(ci_li[0, r])}:"
            f"{int(ci_li[1, r])}][{self.roles[r]}]{msg}"
        )
        if rec is not None:
            rec.record(
                node=f"Server{r}", term=int(self.terms[r]), kind=kind,
                t_virtual=self.clock.now, state=self.roles[r],
                commit_index=int(ci_li[0, r]), last_index=int(ci_li[1, r]),
                msg=msg, **fields,
            )
        if self._trace is not None:  # not truthiness: empty sinks are falsy
            self._trace(line)
        return line

    def _record_event(self, r: int, kind: str, **fields) -> None:
        """Record a structured event that has NO legacy nodelog line (the
        previously-silent transitions: repair floor raises, span-free
        internals). Never enters the trace stream — the nodelog line set
        is the differential join key and must not drift — and reads only
        host mirrors, so it costs no device fetch."""
        if self.recorder is not None:
            self.recorder.record(
                node=f"Server{r}", term=int(self.terms[r]), kind=kind,
                t_virtual=self.clock.now, state=self.roles[r], **fields,
            )

    def _metric_inc(self, name: str, help_: str = "", **labels) -> None:
        """Guarded counter bump (no-op without a registry). The single
        engine is group "0"; extra labels (e.g. shed ``reason``) ride
        along. Pure host arithmetic — determinism-neutral."""
        if self.metrics is None:
            return
        labels.setdefault("group", "0")
        self.metrics.counter(name, help_, tuple(labels)).inc(**labels)

    def _note_seal(self, n_entries: int) -> None:
        """Tiered-store seal callback: one segment of ``n_entries``
        committed entries was RS-coded and spilled to disk."""
        self._metric_inc(
            "raft_segments_sealed_total",
            "sealed cold-tier segments spilled to disk",
        )

    # ------------------------------------------- device observability plane
    def attach_device_obs(self, obs=None, capacity: int = 4096):
        """Attach the device-resident observability plane (obs.device):
        subsequent replicate/vote launches run the recorded step
        programs (state outputs bit-identical — recording derives from
        the transition, outside the protocol math) and each launch
        boundary flushes the ring + on-device counters into ``obs``
        (a DeviceObs; one is created when omitted). Passing an existing
        DeviceObs lets one plane span crash-restore cycles, like the
        flight recorder (each attachment opens a new accumulation
        epoch). The pipelined chunk launches (``submit_pipelined``)
        record at CHUNK granularity (``_dev_record_chunk``) — the same
        granularity the host nodelog observes them at. Returns the
        DeviceObs."""
        from raft_tpu.obs.device import N_COUNTERS, DeviceObs, init_ring

        self.device_obs = obs if obs is not None else DeviceObs(capacity)
        self.device_obs.new_epoch()
        #   each attachment is an epoch: a crash-restored engine's fresh
        #   ring (seqs and counters restarting at 0) ADDS to the plane's
        #   accumulators instead of regressing them
        self._dev_ring = init_ring(self.device_obs.capacity)
        self._dev_flushed = 0
        self._dev_counters_folded = np.zeros(N_COUNTERS, np.int64)
        return self.device_obs

    def detach_device_obs(self) -> None:
        """Back to the pre-instrumentation programs; the DeviceObs keeps
        everything already flushed."""
        self._flush_device_obs()
        self.device_obs = None
        self._dev_ring = None

    def _flush_device_obs(self) -> None:
        """One amortised fetch per launch boundary: pack the ring buffer
        + seq counter + metrics vector into a single array, decode new
        records into PR-5 Events, fold counter deltas into the
        registry. Pure read — no engine decision depends on it, so
        recording stays determinism-neutral."""
        if self.device_obs is None or self._dev_ring is None:
            return
        from raft_tpu.obs.device import (
            COUNTER_METRICS,
            decode_records,
            packed_flush,
        )

        packed = np.asarray(self._fetch(packed_flush(self._dev_ring)))
        events, count, lost, counters, _tick = decode_records(
            packed, self._dev_flushed, t_virtual=self.clock.now,
        )
        if count == self._dev_flushed and not np.any(
            counters - self._dev_counters_folded
        ):
            return
        self.device_obs.ingest(
            events, total=count, lost=lost, counters=counters, group=None,
        )
        self._dev_flushed = count
        if self.metrics is not None:
            for i, name in enumerate(COUNTER_METRICS):
                delta = int(counters[i] - self._dev_counters_folded[i])
                if delta:
                    self.metrics.counter(
                        name, "on-device protocol counter", ("group",)
                    ).inc(delta, group="0")
        self._dev_counters_folded = counters

    def _dev_pre_chunk(self):
        """Pre-capture the scalars chunk recording needs (term / commit
        / last vectors) BEFORE a pipelined launch: ``replicate_pipeline``
        donates the state buffers, so the old values must be copied out
        first. None when the device plane is detached."""
        if self._dev_ring is None:
            return None
        if not hasattr(self, "_dev_pre_jit"):
            self._dev_pre_jit = jax.jit(
                lambda s: (s.term, s.commit_index, s.last_index)
            )
        return self._dev_pre_jit(self.state)

    def _dev_record_chunk(self, pre, info, r: int, term: int,
                          ticks: int) -> None:
        """Chunk-granularity device recording for the pipelined launches
        (``submit_pipelined``): the fused pipeline kernel cannot carry
        the per-step ring, so the chunk records its AGGREGATE transition
        — one commit-advance event (exactly mirroring the ONE host
        nodelog commit line each chunk produces via ``_advance_commit``)
        plus term adoptions, step-down evidence and counter deltas. The
        device plane is therefore never silently dark on a path the
        host observes; ``heartbeat_ticks`` is charged the chunk's step
        count."""
        if self._dev_ring is None or pre is None:
            return
        if not hasattr(self, "_dev_chunk_jit"):
            from raft_tpu.core.comm import SingleDeviceComm
            from raft_tpu.obs.device import record_replicate_events

            comm = SingleDeviceComm(self.cfg.rows)

            def _rec(ring, pre_term, pre_commit, pre_last, state, info,
                     leader, lterm, ticks):
                # a view of the pre-launch state: only the three small
                # vectors recording reads are swapped in; the other
                # leaves alias the post-launch buffers untouched
                old_view = state.replace(
                    term=pre_term, commit_index=pre_commit,
                    last_index=pre_last,
                )
                return record_replicate_events(
                    ring, comm, old_view, state, info, leader, lterm,
                    -1, repair=False, ticks=ticks,
                )

            self._dev_chunk_jit = jax.jit(_rec)
        self._dev_ring = self._dev_chunk_jit(
            self._dev_ring, *pre, self.state, info, jnp.int32(r),
            jnp.int32(term), jnp.int32(ticks),
        )
        self._flush_device_obs()

    def _attach_votelog(self, path: str) -> None:
        from raft_tpu.ckpt import VoteLog

        self._votelog = VoteLog(path)
        self._persisted_terms = self.terms.astype(np.int64).copy()
        self._persisted_vf = self._fetch(self.state.voted_for).astype(np.int64)

    def _persist_votes(self, vf: Optional[np.ndarray] = None) -> None:
        """Durably record every (term, votedFor) row that changed since
        the last record — called BEFORE the engine acts on the transition
        (the fence argument in ckpt.votelog). ``vf`` is the device
        voted_for when the caller has it (vote rounds); without it,
        adoption semantics apply: a row whose term advanced holds NO_VOTE
        in the new term (core.step resets voted_for on adoption)."""
        if self._votelog is None:
            return
        rows = []
        for r in range(self.cfg.rows):
            t = int(self.terms[r])
            if vf is not None:
                v = int(vf[r])
            elif t == self._persisted_terms[r]:
                v = int(self._persisted_vf[r])
            else:
                v = NO_VOTE
            if t != self._persisted_terms[r] or v != self._persisted_vf[r]:
                rows.append((r, t, v))
                self._persisted_terms[r] = t
                self._persisted_vf[r] = v
        if rows:
            self._votelog.record_many(rows)

    def _push(self, t: float, kind: str, replica: int) -> None:
        heapq.heappush(self._q, (t, self._seq_events, kind, replica))
        self._seq_events += 1

    def _arm_follower(self, r: int) -> None:
        """Randomized election timeout (reference: uniform int 10-29 s,
        main.go:114) scaled by the configured window."""
        self._timer_gen[r] += 1
        lo, hi = self.cfg.follower_timeout
        self._push(self.clock.now + self.rng.uniform(lo, hi), f"e:{self._timer_gen[r]}", r)

    def _arm_candidate(self, r: int) -> None:
        # reference: uniform 10-13 s (main.go:194)
        self._timer_gen[r] += 1
        lo, hi = self.cfg.candidate_timeout
        self._push(self.clock.now + self.rng.uniform(lo, hi), f"c:{self._timer_gen[r]}", r)

    # ------------------------------------------------------------- client API
    def submit(self, payload: bytes, client=None) -> int:
        """Queue one entry; returns its sequence number. The entry is
        durable once ``seq in engine.commit_time`` (``is_durable(seq)``).
        The reference's client never learns the fate of an entry
        (main.go:330); here the engine reports it honestly — including the
        loss case: entries queued or ingested-but-uncommitted across a
        leadership change may be dropped (the reference drops them too) and
        their seq simply never becomes durable; clients resubmit.

        With admission configured (``cfg.admission_max_writes``), an
        arrival that finds the queue at its bound, the delay controller
        shedding, or — when ``client`` is given — its fair share
        exceeded, raises ``admission.Overloaded`` BEFORE anything is
        queued (no seq is minted; provably no effect; retry after the
        carried hint). ``client`` is an opaque id used only for the
        fair-share accounting."""
        if len(payload) != self.cfg.entry_bytes:
            raise ValueError(
                f"payload must be exactly {self.cfg.entry_bytes} bytes"
            )
        if self.admission is not None:
            try:
                self.admission.admit_write(len(self._queue), client)
            except Overloaded as ex:
                # the gate refused before anything queued; the span (if
                # one is ambient) and shed counter record the reason
                if self.spans is not None:
                    self.spans.note_refusal(ex.reason, self.clock.now)
                self._metric_inc("raft_sheds_total", reason=ex.reason)
                raise
        seq = self._next_seq
        self._next_seq += 1
        self._queue.append((seq, payload))
        self.submit_time[seq] = self.clock.now
        if self.spans is not None:
            self.spans.note_submit(seq, self.clock.now)
        if self.metrics is not None:
            self.metrics.gauge(
                "raft_queue_depth_high_water",
                "max host write-queue depth observed", ("group",),
            ).set_max(len(self._queue), group="0")
        if self._fused_driver is not None:
            # pre-pack the completed batch into the device staging ring
            # (client-side cost — the fused drain reads it by index)
            self._fused_driver.on_submit()
        return seq

    def is_durable(self, seq: int) -> bool:
        if seq in self.commit_time:
            return True
        return self._durable_range_covers(seq)

    def _durable_range_covers(self, seq: int) -> bool:
        """True iff ``seq``'s stamp was evicted from the bounded
        ``commit_time`` window — evicted seqs were committed by
        construction, summarized as merged intervals
        (``raft.ledger`` — the shared ledger both engines delegate to)."""
        from raft_tpu.raft.ledger import durable_range_covers

        return durable_range_covers(self._durable_ranges, seq)

    def _evict_commit_stamps(self) -> None:
        """Bound the per-entry stamp dicts (the ``host_post`` residue of
        ROADMAP item 2): past ``_commit_stamp_cap`` retained stamps,
        evict oldest-first (dict order IS stamp order) into the merged
        durable-seq intervals, dropping the matching ``submit_time``
        records too. Mirrors the CheckpointStore retention horizon
        (``2 * log_capacity`` entries), so latency samples stay
        available exactly as long as the archived bytes do.

        Trim-to-exactly-cap makes the retained set a pure function of
        the stamp SEQUENCE, not of check cadence — the fused K-tick
        path (one check per launch) and the tick path (one per advance)
        end every run with identical dicts, which the fused byte-
        identity pins compare. The algorithm (bulk C-level rebuilds,
        numpy run-collapse) lives in ``raft.ledger``, shared verbatim
        with ``MultiEngine``'s per-group ledgers."""
        from raft_tpu.raft.ledger import evict_commit_stamps

        self.commit_time, self.submit_time, n = evict_commit_stamps(
            self.commit_time, self.submit_time, self._commit_stamp_cap,
            self._durable_ranges,
        )
        self.commit_stamps_evicted += n

    def _pack_entries(self, entries, padded_len: int) -> np.ndarray:
        """(seq, payload) pairs -> u8[padded_len, entry_bytes], zero-padded
        past the real entries (shared by the tick and pipelined ingest)."""
        if entries and len(entries) == padded_len:
            # no padding needed: zero-copy view over the joined bytes
            return np.frombuffer(
                b"".join(p for _, p in entries), np.uint8
            ).reshape(padded_len, self.cfg.entry_bytes)
        data = np.zeros((padded_len, self.cfg.entry_bytes), np.uint8)
        if entries:
            data[:len(entries)] = np.frombuffer(
                b"".join(p for _, p in entries), np.uint8
            ).reshape(len(entries), self.cfg.entry_bytes)
        return data

    def _step_down_leader(self, r: int, max_term: int) -> None:
        """A higher term exists: the leader reverts to follower
        (main.go:309-321); the device step already refused ingest/commit
        for the stale term."""
        self.roles[r] = FOLLOWER
        self.terms[r] = max_term
        self._persist_votes()   # adopt the term durably before acting on it
        if self.leader_id == r:
            self.leader_id = None
        if self.lease is not None:
            # hygiene, not load-bearing: lease_read_index already
            # refuses on the role/term checks this step-down just broke
            self.lease.break_(r)
        self.nodelog(r, "step down to follower")
        self._metric_inc("raft_term_adoptions_total")
        self._arm_follower(r)

    def submit_pipelined(self, payloads: List[bytes]) -> List[int]:
        """High-throughput ingest: replicate + commit many batches in
        chunked compiled scans (``transport.replicate_many``), syncing to
        host once per chunk instead of once per leader tick — the
        "(state, batch) -> (state, committed_upto), sync watermarks
        periodically" design SURVEY.md §7 hard part 1 calls for. A chunk is
        as many full batches as are *guaranteed* ring room before the scan
        starts (commits inside the scan free more; the bound is
        conservative, never lossy) — EXCEPT on the verified all-accept
        fast path with ``cfg.pipeline_max_laps > 1``, where a chunk may
        span several ring turnovers in one launch: there the turnover
        kernel commits every step before its slots are revisited, so
        room is created exactly as it is consumed (and the host buffers
        the whole chunk's bytes for the archive regardless).

        Requires a current leader. Returns the entries' sequence numbers;
        durability reporting matches ``submit`` (leadership loss mid-chunk
        re-queues refused entries for later ticks; they commit later or
        read as lost). Entries already queued via ``submit`` are folded in
        ahead of ``payloads`` so the two APIs never reorder."""
        cfg = self.cfg
        r = self.leader_id
        if r is None:
            raise RuntimeError("submit_pipelined requires a current leader")
        for p in payloads:  # validate all before assigning any seq
            if len(p) != cfg.entry_bytes:
                raise ValueError(
                    f"payload must be exactly {cfg.entry_bytes} bytes"
                )
        # the pipelined path owns the queue wholesale from here on
        # (swaps, re-queues, deferred splices): the staging mirror
        # cannot track it. Detach the driver around the intake so the
        # per-submit staging hook doesn't pay a device copy per batch
        # that the reset below would immediately discard.
        drv, self._fused_driver = self._fused_driver, None
        try:
            seqs = [self.submit(p) for p in payloads]
        finally:
            self._fused_driver = drv
        pending, self._queue = self._queue, []
        if self._fused_driver is not None:
            self._fused_driver.on_queue_replaced()
        # Configuration entries do not ride pipelined scans: a chunk would
        # keep committing batches beyond the entry under the stale member
        # mask. Stop the pipeline before the first config entry; the tick
        # path ingests it with the new mask (see _fire_leader_tick).
        cut = next((i for i, (q, _) in enumerate(pending)
                    if q in self._config_seqs), None)
        deferred: List[Tuple[int, bytes]] = []
        if cut is not None:
            deferred = pending[cut:]
            pending = pending[:cut]
        B = cfg.batch_size
        T_ring = cfg.log_capacity // B
        while pending:
            if self.leader_id != r or not self.alive[r]:
                break
            leader_last = int(self._fetch(self.state.last_index)[r])
            eff = self._reach(r)
            steps = (
                self.state.capacity - (leader_last - self.commit_watermark)
            ) // B
            if steps <= 0:
                # ring full of uncommitted entries — the regular tick path
                # must drain commits first; leave the rest queued
                break
            take = min(len(pending), steps * B)
            # Fixed scan length: pad the chunk with zero-count (heartbeat)
            # steps so every chunk compiles to the SAME [T, B, L] program —
            # a varying T would trigger a fresh XLA compile per chunk
            # length, dwarfing the scan itself.
            T = T_ring
            eligible = self._pipeline_eligible(r, take, T, leader_last, eff)
            # ALL rows in the gate's verified accept set — the kernel's
            # own turnover predicate evaluated on the same evidence. Only
            # the write-only turnover branch is certified across ring
            # laps, so the lap decision and allow_turnover below share
            # this one value: a quorum-but-not-all accept set must
            # neither take the lapped shape (the aliased fallback is
            # uncertified past one turnover) nor compile the turnover
            # branch it cannot reach.
            all_accept = bool(eligible and self._gate_accept.all())
            # Multi-lap fast path: the eligibility legs are T-independent
            # given take == T*B, and on an all-accept cluster the
            # write-only turnover kernel is valid across ring laps (each
            # step commits before its slots are revisited), so a backlog
            # covering pipeline_max_laps ring turnovers rides ONE launch.
            # All-or-nothing on the lap count keeps the compile set at
            # exactly two programs.
            if (
                all_accept and cfg.pipeline_max_laps > 1
                and len(pending) >= cfg.pipeline_max_laps * T_ring * B
            ):
                T = cfg.pipeline_max_laps * T_ring
                take = T * B
            chunk = pending[:take]
            used = -(-take // B)
            counts = np.zeros(T, np.int32)
            counts[:used] = B
            if used:
                counts[used - 1] = take - (used - 1) * B
            data = self._pack_entries(chunk, T * B)
            if cfg.ec_enabled:
                from raft_tpu.ec.kernels import encode_fold_device

                folded = encode_fold_device(self._code, jnp.asarray(data))
                payload_stack = folded.reshape(T, B, -1)
            else:
                payload_stack = fold_batch(data, cfg.rows).reshape(
                    T, B, -1
                )
            pre_lasts = self._pre_lasts()
            floor, fpt = self._floor_attest(r)
            dev_pre = self._dev_pre_chunk()
            if eligible:
                # The saturated fast path: the whole full-ring chunk as
                # ONE kernel launch (core.step_pallas.steady_pipeline_tpu
                # via the transport). The host gate below implies the
                # kernel's launch-feasibility predicate, so every step
                # ingests and commits a full batch — bookkeeping is the
                # contiguous mapping, verified by the commit assert.
                self.state, info = self.t.replicate_pipeline(
                    self.state, payload_stack, jnp.asarray(counts), r,
                    self.leader_term, jnp.asarray(eff),
                    jnp.asarray(self.slow),
                    # the pipeline kernel takes the bool VOTER plane
                    # directly (no packed-mask decomposition on this
                    # entry point — unlike replicate/scan_replicate)
                    member=(jnp.asarray(self.member)
                            if self.cfg.max_replicas is not None else None),
                    repair_floor=floor, floor_prev_term=fpt,
                    term_floor=self._term_floor,
                    # write-only turnover only when the host's verified
                    # accept set covers EVERY row (same value as the lap
                    # gate above — see its comment); with False the
                    # program is the plain pipeline-vs-scan two-way cond
                    allow_turnover=all_accept,
                )
                self._note_truncations(pre_lasts)
                self._dev_record_chunk(dev_pre, info, r, self.leader_term, T)
                final_commit = int(info.commit_index)
                if final_commit != leader_last + take:
                    # The host gate and the kernel's feasibility predicate
                    # are meant to be equivalent; a desync means mappings
                    # for the chunk cannot be trusted — fail loudly
                    # rather than mis-account durable entries. BUT first
                    # reconcile, so the exception is survivable: account
                    # the committed prefix (it is durable — its bytes must
                    # never be re-queued), then truncate the orphaned
                    # uncommitted suffix off the device log. Without the
                    # truncation the re-queued payloads would coexist with
                    # an unaccounted device copy, and a later repair tick
                    # could replicate and commit both.
                    done = min(max(final_commit - leader_last, 0), take)
                    self._account_chunk_prefix(
                        r, chunk, done, leader_last, eff
                    )
                    self._truncate_uncommitted_tail(
                        leader_last + done,
                        self._fetch(self.state.last_index),
                    )
                    # chunk[:done] is committed and stays accounted; the
                    # rest of the chunk re-queues for a later tick
                    self._queue = (
                        list(chunk[done:]) + pending[take:] + deferred
                        + self._queue
                    )
                    raise RuntimeError(
                        f"pipeline chunk shortfall: committed "
                        f"{final_commit}, expected {leader_last + take} "
                        "(host feasibility gate out of sync with the "
                        "kernel's launch predicate); device log "
                        "reconciled, uncommitted remainder re-queued"
                    )
                self._account_chunk_prefix(r, chunk, take, leader_last, eff)
                pending = pending[take:]
                self._confirm_reads(
                    r, self.leader_term, eff, int(info.max_term)
                )
                self._update_steady(r, info.match, eff)
                if int(info.max_term) > self.leader_term:
                    self._step_down_leader(r, int(info.max_term))
                    break
                continue
            self.state, infos = self.t.replicate_many(
                self.state, payload_stack, jnp.asarray(counts), r,
                self.leader_term, jnp.asarray(eff),
                jnp.asarray(self.slow),
                repair=self._repair_program(),
                member=self._member_arg(),
                repair_floor=floor,
                floor_prev_term=fpt,
                term_floor=self._term_floor,
            )
            self._note_truncations(pre_lasts)
            if dev_pre is not None:
                # the scanned path stacks per-step infos; the chunk
                # transition is judged against the final step's
                self._dev_record_chunk(
                    dev_pre, jax.tree.map(lambda a: a[-1], infos),
                    r, self.leader_term, T,
                )
            # ---- one host sync for the whole chunk ----
            frontier = np.asarray(infos.frontier_len)
            max_term = int(np.max(np.asarray(infos.max_term)))
            final_commit = int(np.asarray(infos.commit_index)[-1])
            idx = leader_last
            pos = 0
            refused: List[Tuple[int, bytes]] = []
            for t in range(T):
                cnt, ing = int(counts[t]), int(frontier[t])
                for i, (seq, p) in enumerate(chunk[pos:pos + cnt]):
                    if i < ing:
                        idx += 1
                        self._seq_at_index[idx] = seq
                        self._uncommitted[idx] = (p, self.leader_term)
                        self._note_config_ingest(idx, seq, self.leader_term)
                    else:
                        refused.append((seq, p))
                pos += cnt
            pending = refused + pending[take:]
            # Durability fence FIRST (same ordering as the tick path): the
            # chunk's term adoptions reach disk before any externally
            # observable action — _advance_commit archives entries and
            # advances the durability-visible watermark (ckpt.votelog:
            # "persist between the step and any such action").
            self.terms[eff] = np.maximum(self.terms[eff], self.leader_term)
            self._persist_votes()
            self._advance_commit(r, final_commit)
            self._confirm_reads(r, self.leader_term, eff, max_term)
            self._update_steady(r, infos.match[-1], eff)
            if max_term > self.leader_term:
                # deposed mid-chunk: hand the rest back to the queue
                self._step_down_leader(r, max_term)
                break
            if refused:
                break  # no progress is possible right now; don't spin
        self._queue = pending + deferred + self._queue
        if self.leader_id == r:
            self._reset_heard_timers(r)
        return seqs

    def _account_chunk_prefix(self, r: int, chunk, n: int,
                              leader_last: int, eff) -> None:
        """Durable accounting for the first ``n`` entries of a pipeline
        chunk at contiguous indices after ``leader_last``: stamp seq and
        payload bookkeeping, fence term durability to disk, then advance
        the commit watermark (archive + ack). Shared by the fast path's
        success and shortfall-reconcile branches so the two can never
        drift on what "durably accounted" means."""
        for i, (seq, p) in enumerate(chunk[:n]):
            idx = leader_last + 1 + i
            self._seq_at_index[idx] = seq
            self._uncommitted[idx] = (p, self.leader_term)
        self.terms[eff] = np.maximum(self.terms[eff], self.leader_term)
        self._persist_votes()
        self._advance_commit(r, leader_last + n)

    def _pipeline_eligible(self, r: int, take: int, T: int,
                           leader_last: int, eff) -> bool:
        """Host gate for the single-launch pipeline chunk: must IMPLY the
        kernel's launch-feasibility predicate (core.step_pallas), so the
        flight provably ingests and commits a full batch every step —
        the contract the simplified contiguous bookkeeping rests on.

        - the transport exposes the program and the shapes are
          kernel-eligible (ring._pallas_ok);
        - the chunk is exactly one full ring of full batches (counts all
          B — padding heartbeat steps would break the affine geometry);
        - the cluster is VERIFIED steady (every reachable non-slow
          member's match at the leader's tail — the kernel's launch-time
          accept set) and fully committed, with the start slot aligned;
        - the accept set meets the commit quorum, and no reachable row
          holds a higher term (those deny/depose instead of acking).

        The accept set is verified against the CURRENT device state (one
        fetch of the term/last/match vectors), not the ``_steady`` flag
        alone: ``_update_steady`` is vacuously True when the previous
        step's verified set was empty, and a flag can never prove the
        rows counted toward quorum are at the leader's tail *now*. A row
        counts only if its device log provably matches the leader's
        through ``leader_last`` (same tail index, match verified in the
        current term, no higher term) — a sufficient condition for the
        kernel's per-row accept predicate, so host-feasible implies
        kernel-feasible.
        """
        from raft_tpu.core.ring import _pallas_ok

        cfg = self.cfg
        B = cfg.batch_size
        if not (
            getattr(self.t, "replicate_pipeline", None) is not None
            and _pipeline_backend_ok()
            and take == T * B
            and _pallas_ok(cfg.log_capacity, B)
            and self._steady
            and self.commit_watermark == leader_last
        ):
            return False
        from raft_tpu.core.step_pallas import _pick_br

        if leader_last % _pick_br(B, cfg.log_capacity) != 0:
            return False
        if np.any(self.terms[eff] > self.leader_term):
            return False
        lasts, matches, mterms, dterms = np.asarray(self._fetch(jnp.stack([
            self.state.last_index, self.state.match_index,
            self.state.match_term, self.state.term,
        ])))
        verified = (
            (lasts == leader_last) & (dterms <= self.leader_term)
            & (
                (leader_last == 0)   # empty prefix: no prev point to
                #                      verify (the kernel's ws0==1 clause)
                | ((mterms == self.leader_term) & (matches >= leader_last))
            )
        )
        # the leader's own row accepts its own frontier; it needs no
        # verified match, only a current term and the expected tail
        verified[r] = (
            lasts[r] == leader_last and dterms[r] <= self.leader_term
        )
        accept = eff & ~self.slow & verified
        # stashed for the caller: the multi-lap gate and allow_turnover
        # must see the SAME per-row accept set this gate counted —
        # all-rows-accept is the kernel's turnover predicate, and only
        # the turnover branch is certified across ring laps
        self._gate_accept = accept
        if cfg.max_replicas is not None:
            # mirror core.step_pallas._params_and_masks EXACTLY: member
            # majority, clamped to the static commit_quorum only under EC
            # (the k+margin durability floor); for non-EC the member
            # majority alone governs, matching the general XLA path
            quorum = int(self.member.sum()) // 2 + 1
            if cfg.ec_enabled:
                quorum = max(quorum, cfg.commit_quorum)
        else:
            quorum = cfg.commit_quorum
        if cfg.max_replicas is not None:
            # the kernel counts acks over VOTERS (alive & member on
            # device); learner rows in the accept set replicate but must
            # not be counted toward the host's quorum feasibility either
            return int((accept & self.member).sum()) >= quorum
        return int(accept.sum()) >= quorum

    @property
    def in_flight_count(self) -> int:
        """Entries ingested into the leader's log but not yet committed
        (they commit on a later tick; neither durable nor lost)."""
        return sum(
            1 for seq in self._seq_at_index.values()
            if seq not in self.commit_time
        )

    # ------------------------------------------------- batched ReadIndex
    def submit_read(self, r: Optional[int] = None) -> int:
        """Queue a linearizable read (the dissertation's batched
        ReadIndex optimization over §6.4): note the current watermark
        NOW, and let the next successful quorum round — a write
        replication tick, a pipelined chunk, or an explicit
        ``read_linearizable`` confirmation — confirm leadership for
        every queued read at once. Under sustained write load a read
        therefore costs ZERO extra replication rounds (the write
        traffic IS the confirmation evidence); a dedicated empty round
        is only ever paid on an idle cluster, and one such round serves
        the whole queue. Returns a ticket for ``read_confirmed``.

        Refusal semantics match ``read_linearizable``: not a live
        leader / deposed / quorum unreachable raise immediately;
        leadership loss while queued is detected lazily — the ticket's
        (row, term) binding can no longer confirm, and the next poll
        raises (the split-brain guarantee — a minority-side stale
        leader can never confirm, so its queued reads never serve).

        With admission configured (``cfg.admission_max_reads``), an
        arrival beyond the outstanding-ticket bound raises
        ``admission.Overloaded("read_depth")`` instead of minting a
        ticket that would silently FIFO-evict someone else's. The
        abandoned-ticket backstop at this bound is AGE, not count: the
        2^16 FIFO cap can never be reached under a smaller admission
        bound, so tickets idle for ``READ_TICKET_TTL_FACTOR`` max
        election timeouts (far beyond any live client's poll cadence)
        are evicted first — they poll as ``TicketEvicted``, the same
        re-issue contract as the legacy cap — and only then is the
        survivor count held against the bound. Without this, ``max_
        reads`` abandoned tickets would refuse every future read
        forever."""
        if self.admission is not None:
            ttl = self.READ_TICKET_TTL_FACTOR * self.cfg.follower_timeout[1]
            # tickets mint monotonically and dict order survives
            # deletes, so the front of the dict is the oldest — stop at
            # the first young ticket (amortized O(1) per admission)
            for tk in list(self._reads):
                if self.clock.now - self._reads[tk][4] < ttl:
                    break
                self._drop_read_ticket(tk)
                self._read_evict_floor = max(self._read_evict_floor, tk + 1)
            try:
                self.admission.admit_read(len(self._reads))
            except Overloaded as ex:
                if self.spans is not None:
                    self.spans.note_refusal(ex.reason, self.clock.now)
                self._metric_inc("raft_sheds_total", reason=ex.reason)
                raise
        if r is None:
            r = self.leader_id
        lease_idx = None
        try:
            if r is None or self.roles[r] != LEADER or not self.alive[r]:
                raise LinearizableReadRefused("not a live leader")
            if int(self.terms[r]) > int(self.lead_terms[r]):
                self._step_down_leader(r, int(self.terms[r]))
                raise LinearizableReadRefused("deposed (higher term seen)")
            # lease fast path BEFORE the reach check: the lease's whole
            # point is serving with no knowledge of the cluster beyond
            # the drift-bounded clock — a real lease-holding leader does
            # not know it is partitioned, and the simulation must not
            # leak the fault masks into a path a deployment could not
            # consult (the quorum check below is the CLASSIC path's
            # simulation-framing shortcut; see read_linearizable)
            lease_idx = self.lease_read_index(r)
            if lease_idx is None:
                voters = self._voter_reach(r)
                if int(voters.sum()) <= int(self.member.sum()) // 2:
                    raise LinearizableReadRefused(
                        f"quorum unreachable ({int(voters.sum())} of "
                        f"{int(self.member.sum())} members)"
                    )
        except LinearizableReadRefused as ex:
            if self.spans is not None:
                self.spans.note_read_refused(None, str(ex), self.clock.now)
            raise
        tk = self._next_read_ticket
        self._next_read_ticket += 1
        bind = (r, int(self.lead_terms[r]))
        if lease_idx is not None:
            # zero-round lease serve (docs/READS.md): the ticket is
            # minted already confirmed at r's OWN commit view — a pure
            # host receipt; no replication round will ever touch it, so
            # it joins no (row, term) confirmation bucket. The poll
            # contract is unchanged: read_confirmed returns the index
            # on the very next call.
            self._reads[tk] = [
                r, lease_idx, bind[1], "ready", self.clock.now, "lease",
            ]
        else:
            self._reads[tk] = [
                r, self.commit_watermark, bind[1], "pending",
                self.clock.now, "read_index",
            ]
            self._read_buckets.setdefault(bind, set()).add(tk)
        n_evict = len(self._reads) - self.READ_TICKET_CAP
        if n_evict > 0:
            # abandoned-ticket bound: tickets are poll-once, so a client
            # that stops polling would otherwise leak records forever —
            # evict the OLDEST tickets (FIFO) beyond the cap. An evicted
            # ticket that IS later polled (a slow, not abandoned, client
            # — multi-group fan-out multiplies outstanding tickets) reads
            # as TicketEvicted via the floor below, never a bare KeyError.
            # Tickets mint monotonically and dict order survives deletes,
            # so the first n keys ARE the oldest — no sort at the cap.
            from itertools import islice

            for old in list(islice(iter(self._reads), n_evict)):
                self._drop_read_ticket(old)
                self._read_evict_floor = max(self._read_evict_floor, old + 1)
        if self.spans is not None:
            self.spans.note_read_ticket(tk, self.clock.now)
        return tk

    def read_ticket_class(self, ticket: int) -> Optional[str]:
        """Served class of an outstanding ticket ("lease" for a
        zero-round local serve, "read_index" otherwise); None once the
        ticket was consumed/evicted. Lets a caller that must serve a
        lease read from the LEADER'S OWN applied view (not the global
        state) tell the two apart — the chaos harness's honesty hook."""
        rec = self._reads.get(ticket)
        if rec is None:
            return None
        return rec[5] if len(rec) > 5 else "read_index"

    def _drop_read_ticket(self, ticket: int) -> None:
        """Remove a ticket from the queue AND its (row, term) bucket."""
        rec = self._reads.pop(ticket, None)
        if rec is None:
            return
        bucket = self._read_buckets.get((rec[0], rec[2]))
        if bucket is not None:
            bucket.discard(ticket)
            if not bucket:
                del self._read_buckets[(rec[0], rec[2])]

    def read_confirmed(self, ticket: int) -> Optional[int]:
        """Poll a ``submit_read`` ticket: the confirmed read index once
        a quorum round has run (serve from state applied to AT LEAST
        that index), None while pending, ``LinearizableReadRefused`` if
        leadership was lost first. Terminal outcomes pop the ticket.

        Refusal is detected lazily from the ticket's bound (row, term):
        a pending ticket whose row no longer leads in that term can
        never be confirmed (``_confirm_reads`` requires an exact term
        match), so no step-down path needs a hook here."""
        rec = self._reads.get(ticket)
        if rec is None:
            if 0 <= ticket < self._read_evict_floor:
                raise TicketEvicted(
                    f"ticket {ticket} was evicted at the outstanding-read "
                    "cap before confirmation; re-issue the read"
                )
            raise KeyError(f"unknown or already-consumed ticket {ticket}")
        row, idx, tterm, st = rec[:4]
        if st == "ready":
            cls = rec[5] if len(rec) > 5 else "read_index"
            self._drop_read_ticket(ticket)
            if self.spans is not None:
                self.spans.note_read_confirmed(
                    ticket, idx, self.clock.now, cls=cls,
                    rounds=0 if cls == "lease" else None,
                )
            if self.slo is not None:
                # read latency = ticket mint -> confirmation (rec[4] is
                # the mint time; the serve itself is applied-state local)
                self.slo.observe(
                    "read", self.clock.now - rec[4], self.clock.now
                )
            self._note_read_served(cls, self.clock.now - rec[4])
            return idx
        if (self.roles[row] != LEADER or not self.alive[row]
                or int(self.lead_terms[row]) != tterm
                or int(self.terms[row]) > tterm):
            self._drop_read_ticket(ticket)
            if self.spans is not None:
                self.spans.note_read_refused(
                    ticket, "leadership lost before confirmation",
                    self.clock.now,
                )
            raise LinearizableReadRefused(
                "leadership lost before confirmation"
            )
        return None

    def _lease_renew(self, r: int, term: int, eff, max_term: int) -> None:
        """A quorum round sourced at ``r`` completed: renew its leader
        lease when the round is lease-grade evidence — it reached a
        member MAJORITY (the same voters whose §9.6 stickiness clocks
        this very round resets), surfaced no higher term, and no
        membership change is in flight (the quorum-overlap argument is
        only clean over a settled configuration). Guarded no-op with the
        lease plane off."""
        if self.lease is None or max_term > term:
            return
        if int((eff & self.member).sum()) <= int(self.member.sum()) // 2:
            return
        if (self._pending_config is not None or self._staged_config
                or self._config_seqs or self.learner.any()):
            return
        self.lease.grant(r, term, self.clock.now)

    def lease_read_index(self, r: int) -> Optional[int]:
        """Zero-round local read index for row ``r``, or None when the
        lease cannot serve (plane off, lease expired/absent, higher
        term seen, membership in flight, or no current-term commit yet
        — §6.4's fresh-leader gate). Callers have already established
        ``r`` is a live leader. The index returned is ``r``'s OWN
        commit view (``_row_commit``), never the global watermark."""
        if self.lease is None:
            return None
        term = int(self.lead_terms[r])
        if int(self.terms[r]) > term:
            return None
        if (self._pending_config is not None or self._staged_config
                or self._config_seqs or self.learner.any()):
            return None
        if int(self._lease_ok_term[r]) != term:
            return None
        if not self.lease.valid(r, term, self.clock.now):
            return None
        return int(self._row_commit[r])

    def set_lease_rate(self, r: int, rate: float) -> None:
        """Clock-skew injection surface (chaos nemesis): row ``r``'s
        lease clock runs at ``rate`` local seconds per true second.
        No-op without the lease plane."""
        if self.lease is not None:
            self.lease.set_rate(r, rate)

    def _note_read_served(self, cls: str, latency_s: float) -> None:
        """One read served under class ``cls`` (lease / read_index):
        host counter + ``raft_reads_total{class}`` + the per-class SLO
        latency digest. Pure host arithmetic, determinism-neutral."""
        self.read_class_counts[cls] = (
            self.read_class_counts.get(cls, 0) + 1
        )
        self._metric_inc("raft_reads_total", "reads served by class",
                         **{"class": cls})
        if self.admission is not None:
            self.admission.note_read_class(cls)
        if self.slo is not None:
            self.slo.observe(f"read_{cls}", latency_s, self.clock.now)

    def _confirm_reads(self, r: int, term: int, eff, max_term: int) -> None:
        """A quorum round sourced at ``r`` just completed: it confirms
        leadership for every read queued on ``r`` IN THIS TERM when it
        reached a member majority and surfaced no higher term — §6.4's
        confirmation, shared by every round flavor (write tick,
        pipelined chunk, explicit read round). The same evidence renews
        ``r``'s leader lease (``_lease_renew`` — zero-round reads ride
        every round the write path already pays for).

        Pending tickets are indexed by their (row, term) binding, so the
        sweep pops exactly the confirming bucket — O(confirmed), not a
        walk of all (up to 2^16) outstanding tickets per tick. Tickets
        in OTHER buckets need no visit: a dead binding is detected
        lazily by ``read_confirmed``'s own predicate, and total volume
        stays bounded by the FIFO eviction cap."""
        self._lease_renew(r, term, eff, max_term)
        if not self._reads:
            return
        # quorum is counted over reachable VOTERS: the replication reach
        # mask also carries learners, whose acks confirm nothing
        if max_term > term or (
            int((eff & self.member).sum()) <= int(self.member.sum()) // 2
        ):
            return
        bucket = self._read_buckets.pop((r, term), None)
        if not bucket:
            return
        for tk in bucket:
            rec = self._reads.get(tk)
            if rec is not None and rec[3] == "pending":
                rec[3] = "ready"

    def read_linearizable(self, r: Optional[int] = None) -> int:
        """ReadIndex (dissertation §6.4): confirm leadership with a quorum
        round, then return the commit index the read may be served at.

        The leader notes its commit index (the *read index*), runs one
        empty replication round, and only if (a) no reachable replica
        reports a higher term and (b) the round reached a strict majority
        of the current configuration does the read proceed — a
        minority-side stale leader can never satisfy (b), so it cannot
        serve a linearizable read while the majority commits elsewhere
        (the split-brain hazard ``ReplicatedKV.get``'s local-applied
        contract does not guard against). Raises
        ``LinearizableReadRefused`` otherwise.

        Returns the read index; a linearizable read serves from state
        applied to AT LEAST that index (``committed_entries`` up to it,
        or ``ReplicatedKV.linearizable_get``). §6.4's "leader must have
        committed an entry in its term first" exists because a fresh
        leader's commit index may lag reality; here ``commit_watermark``
        is the control plane's global monotone watermark, so the note
        taken before confirmation already covers every acknowledged
        write. ``r`` defaults to the routed leader; pass an explicit row
        to probe a specific (possibly stale split-brain) leader.

        Simulation-framing note: the quorum-reachability check (b)
        reads the engine's injected fault/partition masks — the ground
        truth a real deployment would instead discover as a failed or
        timed-out confirmation round. The refusal SEMANTICS are
        identical; only the discovery latency differs.

        Reads queued via ``submit_read`` share this round's
        confirmation (batched ReadIndex — see ``submit_read``)."""
        if r is None:
            r = self.leader_id
        if r is None or self.roles[r] != LEADER or not self.alive[r]:
            raise LinearizableReadRefused("not a live leader")
        term = int(self.lead_terms[r])
        if int(self.terms[r]) > term:
            self._step_down_leader(r, int(self.terms[r]))
            raise LinearizableReadRefused("deposed (higher term seen)")
        lease_idx = self.lease_read_index(r)
        if lease_idx is not None:
            # leader-lease fast path (docs/READS.md): ZERO replication
            # rounds, no device dispatch — the lease's drift-bounded
            # validity IS the leadership confirmation. Falls through to
            # the classic round below whenever the lease is stale.
            if self.spans is not None:
                self.spans.note_read_served(
                    "lease", self.clock.now, index=lease_idx, rounds=0,
                )
            self._note_read_served("lease", 0.0)
            return lease_idx
        read_index = self.commit_watermark
        eff = self._reach(r)
        # (b) first — it needs no device round and a minority-side leader
        # must be refused even while its own side is quiet. The quorum is
        # counted over reachable VOTERS (eff also carries learners, which
        # hear the confirmation round but confirm nothing).
        confirmed = int((eff & self.member).sum())
        if confirmed <= int(self.member.sum()) // 2:
            raise LinearizableReadRefused(
                f"quorum unreachable ({confirmed} of "
                f"{int(self.member.sum())} members)"
            )
        # (a): one empty round over the current reach — any reachable row
        # at a higher term deposes this leader here, exactly as a
        # heartbeat tick would (main.go:312-321)
        info = self._empty_round(r, term, eff)
        max_term = int(info.max_term)
        if max_term > term:
            self._step_down_leader(r, max_term)
            raise LinearizableReadRefused("deposed during confirmation")
        self.terms[eff] = np.maximum(self.terms[eff], term)
        self._persist_votes()
        self._advance_commit(r, int(info.commit_index))
        self._confirm_reads(r, term, eff, max_term)  # the round is shared
        self._reset_heard_timers(r)
        if self.spans is not None:
            self.spans.note_read_served(
                "read_index", self.clock.now, index=read_index, rounds=1,
            )
        self._note_read_served("read_index", 0.0)
        return read_index

    def _empty_round(self, r: int, term: int, eff) -> "RepInfo":
        """One zero-entry replication round sourced at ``r`` — the device
        half of a heartbeat, shared by the read-confirmation path (and
        mirroring the tick's take==0 branch in ``_fire_leader_tick``; a
        protocol-argument change there must land here too)."""
        cfg = self.cfg
        if self._hb_payload is None:
            self._hb_payload = jnp.zeros(
                (cfg.batch_size, cfg.rows * cfg.shard_words), jnp.int32
            )
        pre_lasts = self._pre_lasts()
        floor, fpt = self._floor_attest(r)
        if self._dev_ring is not None:
            self.state, info, self._dev_ring = self.t.replicate(
                self.state, self._hb_payload, 0, r, term,
                jnp.asarray(eff), jnp.asarray(self.slow),
                repair=self._repair_program(), member=self._member_arg(),
                repair_floor=floor, floor_prev_term=fpt,
                term_floor=self._term_floor, ring=self._dev_ring,
            )
            self._flush_device_obs()
        else:
            self.state, info = self.t.replicate(
                self.state, self._hb_payload, 0, r, term,
                jnp.asarray(eff), jnp.asarray(self.slow),
                repair=self._repair_program(), member=self._member_arg(),
                repair_floor=floor, floor_prev_term=fpt,
                term_floor=self._term_floor,
            )
        self._note_truncations(pre_lasts)
        return info

    # ------------------------------------------------------------- membership
    def _member_arg(self):
        """The membership mask for device steps — None on fixed-membership
        clusters (their programs compile the static quorum), the bool
        voter mask while no learner is attached (bit-exact legacy), the
        packed voter|learner mask (core.state.pack_membership) otherwise
        — the step decomposes it back to the voter plane at the kernel
        boundary. The dtype flip (bool <-> int32) retraces the replicate
        programs once per learner-attach/drain transition — a deliberate
        cost: the packed mask is the device-visible record of the full
        configuration, so the core/step learner support stays exercised
        end to end rather than test-only. replicate_pipeline is the one
        entry point that takes the bool voter plane directly (see the
        submit_pipelined call site)."""
        if self.cfg.max_replicas is None:
            return None
        if self.learner.any():
            from raft_tpu.core.state import pack_membership

            return jnp.asarray(pack_membership(self.member, self.learner))
        return jnp.asarray(self.member)

    def _config_payload(self, member: np.ndarray, learner: np.ndarray) -> bytes:
        """Configuration entries ride the log like data (the §4 approach:
        a config change IS a log entry): magic + the voter bitmap, plus a
        learner bitmap when (and only when) the NEW configuration
        carries learners — an omitted bitmap means an empty learner
        set, so voter-only entries stay byte-identical to every
        pre-learner configuration entry."""
        bits = int(sum(1 << i for i in np.flatnonzero(member)))
        body = b"RCFG" + bits.to_bytes(8, "little")
        if np.asarray(learner, bool).any():
            lbits = int(sum(1 << i for i in np.flatnonzero(learner)))
            body += lbits.to_bytes(8, "little")
        if len(body) > self.cfg.entry_bytes:
            raise ValueError(
                "entry_bytes too small to carry a configuration entry"
            )
        return body + bytes(self.cfg.entry_bytes - len(body))

    def _change_membership(self, new_member: np.ndarray,
                           new_learner: np.ndarray) -> int:
        if self.cfg.max_replicas is None:
            raise ValueError(
                "membership change needs max_replicas headroom in RaftConfig"
            )
        if (np.asarray(new_member, bool) & np.asarray(new_learner, bool)).any():
            raise ValueError("a row cannot be both voter and learner")
        if self._pending_config is not None or any(
            q in self._config_seqs for q, _ in self._queue
        ):
            # one at a time (dissertation §4.1's single-server rule) —
            # including a change still queued before its ingest tick,
            # whose mask capture would otherwise go stale
            raise RuntimeError(
                "a configuration change is already in flight; one at a "
                "time (dissertation §4.1's single-server rule)"
            )
        if self.leader_id is None:
            raise RuntimeError("membership change needs a current leader")
        seq = self.submit(self._config_payload(new_member, new_learner))
        self._config_seqs[seq] = (
            (tuple(bool(x) for x in self.member),
             tuple(bool(x) for x in self.learner)),
            (tuple(bool(x) for x in new_member),
             tuple(bool(x) for x in new_learner)),
        )
        return seq

    def add_learner(self, r: int) -> int:
        """Attach row ``r`` as a NON-VOTING learner (dissertation §4.2.1):
        it receives replication, repair and snapshot install like any
        member but is excluded from vote reach, commit counting and
        CheckQuorum — so a fresh, far-behind row can never shrink the
        effective quorum. Returns the config entry's seq. ``promote``
        makes it a voter once caught up."""
        if not (0 <= r < self.cfg.rows):
            raise ValueError(f"replica {r} out of range (rows={self.cfg.rows})")
        if self.member[r]:
            raise ValueError(f"replica {r} is already a voter")
        if self.learner[r]:
            raise ValueError(f"replica {r} is already a learner")
        new_l = self.learner.copy()
        new_l[r] = True
        return self._change_membership(self.member.copy(), new_l)

    def _promote_lag_bound(self) -> int:
        lag = self.cfg.promote_max_lag
        return lag if lag is not None else 2 * self.cfg.batch_size

    def promote(self, r: int) -> int:
        """Promote learner ``r`` to a voter — one configuration entry
        swapping its learner bit for the voter bit. Refuses with
        ``LearnerLagging`` while the learner's current-term verified
        match is more than ``cfg.promote_max_lag`` entries behind the
        leader's last index (the §4.2.1 catch-up gate): the whole point
        of the learner phase is that the voter set only ever grows by a
        row that can immediately pull its quorum weight."""
        if not self.learner[r]:
            raise ValueError(f"replica {r} is not a learner")
        lead = self.leader_id
        if lead is None:
            raise RuntimeError("promotion needs a current leader")
        if not self.alive[r]:
            # a dead learner trivially "satisfies" any lag bound on a
            # short log (its match is 0 and so is everyone's gap), but
            # promoting a row that cannot ack is exactly the quorum
            # shrink this phase exists to prevent
            raise LearnerLagging(
                f"learner {r} is down; promotion requires a live, "
                "caught-up learner"
            )
        lasts_matches = self._fetch(jnp.stack([
            self.state.last_index, self.state.match_index,
            self.state.match_term,
        ]))
        leader_last = int(lasts_matches[0, lead])
        eff_match = (
            int(lasts_matches[1, r])
            if int(lasts_matches[2, r]) == int(self.lead_terms[lead]) else 0
        )
        lag = leader_last - eff_match
        if lag > self._promote_lag_bound():
            raise LearnerLagging(
                f"learner {r} is {lag} entries behind the leader "
                f"(bound {self._promote_lag_bound()}); promote once "
                "replication / snapshot install has caught it up"
            )
        new_m = self.member.copy()
        new_m[r] = True
        new_l = self.learner.copy()
        new_l[r] = False
        return self._change_membership(new_m, new_l)

    def add_server(self, r: int) -> int:
        """Grow the cluster by one server, learner-first (dissertation
        §4.2.1): row ``r`` joins as a non-voting learner (this call's
        returned seq is the learner config entry — durable via
        ``is_durable``), is healed by the repair window / snapshot
        install, and is promoted to voter AUTOMATICALLY by the leader
        tick once its match is within ``cfg.promote_max_lag`` of the
        leader's tail. The voter set therefore never gains a row that
        would shrink the effective quorum; poll ``engine.member[r]`` (or
        ``run_until_voter``) for full-join completion. The legacy
        immediate-voter path remains as ``add_voter``."""
        seq = self.add_learner(r)
        self._staged_config.append(("promote", r))
        return seq

    def add_voter(self, r: int) -> int:
        """Grow the cluster by one IMMEDIATE voter (dissertation §4: a
        log-committed configuration entry; the new config takes effect
        when APPENDED, commits under its own majority). Returns the
        config entry's seq. The new row joins empty and is healed by the
        repair window / snapshot install — and until it catches up it
        counts against the commit quorum, which is exactly the
        availability hazard ``add_server``'s learner-first flow avoids;
        prefer that unless the joiner is known to be caught up."""
        if not (0 <= r < self.cfg.rows):
            raise ValueError(f"replica {r} out of range (rows={self.cfg.rows})")
        if self.member[r]:
            raise ValueError(f"replica {r} is already a member")
        new = self.member.copy()
        new[r] = True
        new_l = self.learner.copy()
        new_l[r] = False   # promoting a learner directly is allowed
        return self._change_membership(new, new_l)

    def remove_server(self, r: int) -> int:
        """Shrink the cluster by one server (voter or learner). Removing
        the current leader is allowed: it keeps leading until the entry
        commits, then steps down (dissertation §4.2.2). Removing a
        learner never changes any quorum."""
        if self.learner[r]:
            new_l = self.learner.copy()
            new_l[r] = False
            return self._change_membership(self.member.copy(), new_l)
        if not self.member[r]:
            raise ValueError(f"replica {r} is not a member")
        new = self.member.copy()
        new[r] = False
        if int(new.sum()) < 1:
            raise ValueError("cannot remove the last member")
        if self.cfg.ec_enabled and int(new.sum()) < self.cfg.commit_quorum:
            # the k+margin durability quorum must stay satisfiable: fewer
            # members than commit_quorum could never commit again
            raise ValueError(
                f"removing replica {r} leaves {int(new.sum())} members, "
                f"below the EC commit quorum ({self.cfg.commit_quorum})"
            )
        return self._change_membership(new, self.learner.copy())

    def replace(self, dead: int, spare: int) -> int:
        """Replace a DEAD voter with ``spare`` (node replacement, the
        wipe-rejoin runbook of docs/MEMBERSHIP.md): remove ``dead`` from
        the configuration now (returns that entry's seq), then — staged,
        one change at a time — admit ``spare`` as a learner, heal it
        from nothing via repair / snapshot install, and promote it once
        caught up. ``spare == dead`` re-admits the same row under a
        FRESH identity, which is the only safe way back in for a row
        whose durable state was lost (``wipe``): its old votes and acks
        are gone, so it must not resume its old voter identity."""
        if not self.member[dead]:
            raise ValueError(f"replica {dead} is not a member")
        if self.alive[dead]:
            raise ValueError(
                f"replica {dead} is alive; replace() is for dead servers "
                "(fail() it first, or use remove_server/add_server)"
            )
        if not (0 <= spare < self.cfg.rows):
            # range first: a mask read on an out-of-range (or negative)
            # row would raise IndexError / probe the wrong row
            raise ValueError(f"spare {spare} out of range")
        if spare != dead and (self.member[spare] or self.learner[spare]):
            raise ValueError(f"spare {spare} is already configured")
        seq = self.remove_server(dead)
        self._staged_config.extend(
            [("add_learner", spare), ("promote", spare)]
        )
        return seq

    def _drive_staged_config(self, r: int) -> None:
        """Advance the head of the staged single-server ladder
        (``add_server`` auto-promotion, ``replace``) when no change is
        in flight. Runs on the routed leader's tick; a lagging learner's
        promote just waits (retried next tick)."""
        if not self._staged_config:
            return
        if self._pending_config is not None or any(
            q in self._config_seqs for q, _ in self._queue
        ):
            return
        kind, row = self._staged_config[0]
        if kind == "add_learner":
            if self.member[row] or self.learner[row]:
                self._staged_config.pop(0)   # already in — ladder advances
                return
            try:
                self.add_learner(row)
            except (RuntimeError, ValueError, Overloaded):
                return   # no leader yet / admission shedding: retry later
            self._staged_config.pop(0)
        elif kind == "promote":
            if self.member[row] or not self.learner[row]:
                # already a voter, or the learner was removed/rolled back
                # out from under the ladder: the staged step is moot
                self._staged_config.pop(0)
                return
            try:
                self.promote(row)
            except LearnerLagging:
                return                       # still catching up: retry
            except (RuntimeError, ValueError, Overloaded):
                return
            self._staged_config.pop(0)

    def run_until_voter(self, r: int, limit: float = 600.0) -> None:
        """Drive the event loop until row ``r`` is a VOTER — the
        completion point of ``add_server``'s learner-then-promote flow
        (and of a ``replace`` ladder's final step)."""
        end = self.clock.now + limit
        while not self.member[r] and self.clock.now < end and self._q:
            self.step_event()
        assert self.member[r], (
            f"replica {r} not promoted to voter within {limit}s "
            f"(learner={bool(self.learner[r])}, "
            f"staged={self._staged_config})"
        )

    def _note_config_ingest(self, idx: int, seq: int, term: int) -> None:
        """A configuration entry reached the leader's log: activate the
        new configuration NOW (append-time activation, dissertation §4.1 —
        the entry then commits under the NEW majority)."""
        ch = self._config_seqs.pop(seq, None)   # consumed exactly once
        if ch is None:
            return
        old, new = ch
        self._pending_config = (idx, old, new, term)
        #   (index, old (member, learner), new (member, learner), ingest
        #   term) — the term makes the keep-if-held check self-contained
        #   across later elections
        self._apply_membership(np.array(new[0], bool), np.array(new[1], bool))

    def _rollback_pending_config(self, r: int, reason: str) -> None:
        """Roll an in-flight (uncommitted) configuration change back to
        its old masks — the entry no longer survives in the relevant log
        (election winner doesn't hold it / truncation removed it from
        every row). Its seq never reads durable; the operator retries."""
        _, old_masks, _, _ = self._pending_config
        self._pending_config = None
        self._apply_membership(
            np.array(old_masks[0], bool), np.array(old_masks[1], bool)
        )
        self.nodelog(r, reason)

    def _apply_membership(self, new: np.ndarray,
                          new_learner: np.ndarray) -> None:
        added = new & ~self.member
        removed = self.member & ~new
        l_added = new_learner & ~self.learner
        l_removed = self.learner & ~new_learner
        self.member = new
        self.learner = new_learner
        self._steady = False
        for p in np.flatnonzero(added):
            p = int(p)
            self.roles[p] = FOLLOWER
            if l_removed[p]:
                self.nodelog(p, "promoted from learner to voter")
            else:
                self.nodelog(p, "added to configuration")
            self._arm_follower(p)
        for p in np.flatnonzero(removed):
            p = int(p)
            self.nodelog(p, "removed from configuration")
            # NOTE: _wiped is deliberately NOT cleared here — this runs
            # at APPEND-time activation, which can still roll back. A
            # wiped voter may only restart once the removal is DURABLE
            # (_advance_commit clears the flag at config commit);
            # clearing on an uncommitted removal would let a rollback
            # resurrect a live amnesiac voter — the double-vote hazard.
            # a removed LEADER keeps serving until the entry commits
            # (the _advance_commit hook demotes it); everyone else's
            # timers simply stop firing (gated on member)
            if self.roles[p] != LEADER:
                self.roles[p] = FOLLOWER
        for p in np.flatnonzero(l_added):
            p = int(p)
            self.roles[p] = FOLLOWER
            self.nodelog(p, "added to configuration as learner")
            # learners arm no election timers: they never campaign
        for p in np.flatnonzero(l_removed & ~added):
            p = int(p)
            self.nodelog(p, "learner removed from configuration")

    # ---------------------------------------------------------- fault toggles
    def fail(self, r: int) -> None:
        """Silence a replica (crash). Its timers stop; the device step masks
        it out. The reference has no equivalent hook (no node ever fails,
        SURVEY.md §5) — this is the fault-injection surface."""
        self._steady = False
        self.alive[r] = False
        if self.leader_id == r:
            self.leader_id = None
        self.roles[r] = FOLLOWER
        if self.lease is not None:
            self.lease.break_(r)   # a dead row's grant is dead evidence
        self.nodelog(r, "killed")

    def recover(self, r: int) -> None:
        if self._wiped[r]:
            # A wiped row whose voter identity has not durably LEFT the
            # configuration must not run again: its durable (term,
            # votedFor) and acked entries are gone, so restarting it
            # amnesiac could double-vote in a term it already voted in
            # (two leaders, split-brain commits) or silently un-ack
            # committed data. The flag clears only when a removal
            # COMMITS (_advance_commit) — an append-time activation can
            # still roll back, so `not member[r]` alone is not evidence
            # the identity is gone. The only safe path back is
            # replace(): remove the identity, let it commit, rejoin as a
            # fresh learner. Refusal is a quiet no-op so seeded fault
            # schedules stay executable.
            self.nodelog(
                r, "recover refused: wiped voter must rejoin via replace()"
            )
            return
        self._steady = False
        self.alive[r] = True
        self.roles[r] = FOLLOWER
        self.nodelog(r, "recovered")
        self._arm_follower(r)

    def wipe(self, r: int) -> None:
        """Destroy a DEAD row's entire durable and volatile state — log,
        term, vote, match, commit — modeling total disk loss. The row's
        bytes are zeroed on device and its host mirrors reset; if it was
        a configured VOTER it is marked wiped and ``recover`` refuses to
        restart it until ``replace`` has removed the old identity from
        the configuration (the double-vote hazard — see ``recover``).
        Rejoin is then from nothing: learner admission + snapshot
        install. The chaos 'wipe' fault composes this with
        ``MirroredStore.wipe_node`` so the loss covers the simulated
        disk too."""
        if self.alive[r]:
            raise ValueError(
                f"replica {r} is alive; wipe() models disk loss of a "
                "crashed server (fail() it first)"
            )
        w = self.state.words_per_entry
        self.state = self.state.replace(
            term=self.state.term.at[r].set(0),
            voted_for=self.state.voted_for.at[r].set(NO_VOTE),
            last_index=self.state.last_index.at[r].set(0),
            commit_index=self.state.commit_index.at[r].set(0),
            match_index=self.state.match_index.at[r].set(0),
            match_term=self.state.match_term.at[r].set(0),
            log_term=self.state.log_term.at[r].set(0),
            log_payload=self.state.log_payload.at[
                :, r * w:(r + 1) * w
            ].set(0),
        )
        self.terms[r] = 0
        self.lead_terms[r] = 0
        self.roles[r] = FOLLOWER
        self._ring_floor[r] = 1
        self._match_stall[r] = 0
        self._last_heard[r] = -1e18
        self._persisted_terms[r] = 0
        self._persisted_vf[r] = NO_VOTE
        self._quorum_contact_at.pop(r, None)
        self._lasts_snapshot = None
        self._match_snapshot = None
        self._steady = False
        if self.member[r]:
            self._wiped[r] = True
        if self.auditor is not None:
            # a wipe legally resets the row's term to 0: the auditor's
            # per-node term-monotonicity watermark resets with it
            self.auditor.note_wipe(f"Server{r}")
        self.nodelog(r, "wiped (durable state destroyed)")

    def set_slow(self, r: int, is_slow: bool) -> None:
        """Induced-slow follower: receives traffic, appends nothing (stale
        matchIndex — BASELINE config 4)."""
        self._steady = False
        self.slow[r] = is_slow

    def force_campaign(self, r: int) -> None:
        """Disruptive candidacy regardless of a live leader: term bump +
        vote round (the election-storm injection, BASELINE config 5)."""
        if not self.alive[r] or not self.member[r]:
            return
        if self.roles[r] == LEADER and self.leader_id == r:
            return  # a leader bumping itself is a no-op disruption
        if self.cfg.prevote and not self._prevote_wins(r):
            # §9.6 is exactly the defense against this injection: the
            # stickiness clause refuses the disruption while a live
            # leader is heartbeating, so the storm costs no terms
            self.nodelog(r, "injected candidacy suppressed by pre-vote")
            return
        self.roles[r] = CANDIDATE
        self.terms[r] += 1
        self.nodelog(r, "state changed to candidate (injected)")
        self._campaign(r)  # every _campaign outcome re-arms the right timer

    def _reach(self, src: int) -> np.ndarray:
        """Effective alive mask for a REPLICATION step sourced at
        ``src``: a voter or learner, live, AND link-reachable from it
        (``src`` itself included — a just-removed leader is the one
        non-member source; its row rides ingest_row on device, not this
        mask). Learners hear windows and heal through this mask; every
        QUORUM computation must intersect with ``self.member`` (or use
        ``_voter_reach``) so they never count."""
        return (
            self.alive & self.connectivity[src]
            & (self.member | self.learner)
        )

    def _voter_reach(self, src: int) -> np.ndarray:
        """Reachable live VOTERS from ``src`` — the mask every vote
        round, CheckQuorum lease and read-quorum check counts over
        (learners are excluded: non-voting by definition)."""
        return self.alive & self.connectivity[src] & self.member

    def _pre_lasts(self):
        """last_index as of the previous step's end — the cached copy
        from _note_truncations when no host-side mutation touched
        last_index since (installs/abandons invalidate it), else one
        fresh fetch. Keeps truncation detection to a single extra sync
        per step on the steady path."""
        if self._lasts_snapshot is not None:
            return self._lasts_snapshot
        return self._fetch(self.state.last_index)

    def _floor_attest(self, r: int):
        """(repair_floor, attested term of floor-1) for leader ``r``.
        The attested term comes from the archive — the device must not
        read a below-floor ring slot for the prev-check (junk tags can
        collide). 0 when unattestable: followers at the boundary then
        stall into snapshot install rather than accept on a junk match.

        The floor is the truncation floor (``_ring_floor``) raised to
        the LAP horizon, ``last - capacity + 1``: a leader that legally
        wrapped its ring over committed slots holds another entry's
        bytes below the horizon, so the prev-check for a repair window
        STARTING exactly at the horizon must come from the archive too.
        Without the raise, a follower sitting precisely one entry below
        a fully-wrapped leader wedges forever: the repair window reads
        the wrapped slot's term for its prev-check (mismatch, refused
        every tick) while ``_snapshot_heal`` sees ``match + 1 ==
        horizon`` and keeps deferring to that same repair window —
        found by the overload harness (sustained saturation runs the
        ring at full uncommitted depth, parking followers at the
        horizon across elections)."""
        cap = self.state.capacity
        lap = int(self._pre_lasts()[r]) - cap + 1
        floor = max(int(self._ring_floor[r]), lap)
        if (self.recorder is not None and floor > 1
                and floor > self._floor_event_hwm.get(r, 0)):
            # previously-silent transition: the repair floor rose (ring
            # lap horizon or truncation) — recorder-only, no nodelog
            # line (the legacy stream must not drift)
            self._floor_event_hwm[r] = floor
            self._record_event(
                r, "repair_floor_raise", floor=floor, lap_horizon=lap,
                ring_floor=int(self._ring_floor[r]),
            )
        if floor <= 1:
            return floor, 0
        ent = self.store.get(floor - 1)
        return floor, (ent[1] if ent is not None else 0)

    def _note_truncations(self, pre_lasts) -> None:
        """Bump a row's ring-validity floor when a step truncated its log
        (§5.3 conflict). A row that ever wrapped its ring past committed
        slots while leading (legal: committed = consumed) and is later
        truncated keeps WRAPPED-GENERATION bytes in slots below its new
        tail — with term tags that can collide with the true entries'.
        Indices above ``pre_last - capacity`` were provably never
        overwritten by that generation, so the floor lands at
        ``pre_last - capacity + 1`` (<= commit+1 by the row's own ingest
        backpressure, so snapshot installs always bridge the gap). Every
        read path and the device repair window respect the floor; a
        net-grown row needs no bump — its junk sits below the ordinary
        lap horizon already."""
        post = self._fetch(self.state.last_index)
        shrunk = np.flatnonzero(post < np.asarray(pre_lasts))
        for q in shrunk:
            q = int(q)
            self._ring_floor[q] = max(
                self._ring_floor[q],
                int(pre_lasts[q]) - self.state.capacity + 1,
            )
        self._lasts_snapshot = post
        self._match_snapshot = None   # the step moved match state

    def partition(self, groups) -> None:
        """Install a link-level partition: replicas exchange messages only
        within their group (every replica in exactly one group). The
        classic Raft split-brain adversary — a quorum-side group keeps
        electing and committing; a minority group cannot commit and its
        leader, if any, keeps ticking in its own term until heal deposes
        it. The reference cannot express this (its channels always
        deliver, SURVEY §5)."""
        n = self.cfg.rows
        listed = sorted(x for g in groups for x in g)
        if len(set(listed)) != len(listed) or not all(
            0 <= x < n for x in listed
        ):
            raise ValueError("groups must not repeat or exceed row range")
        missing = [x for x in range(n) if x not in set(listed)]
        if any(self.member[x] for x in missing):
            raise ValueError(
                f"groups must cover every member; missing {missing}"
            )
        # spare non-member rows are auto-isolated (they carry no traffic)
        groups = list(groups) + [[x] for x in missing]
        self._steady = False
        self.connectivity = np.zeros((n, n), bool)
        for g in groups:
            for a in g:
                for b in g:
                    self.connectivity[a, b] = True
        self.nodelog(0, f"partition installed: {[sorted(g) for g in groups]}")

    def heal_partition(self) -> None:
        n = self.cfg.rows
        self._steady = False
        self.connectivity = np.ones((n, n), bool)
        self.nodelog(0, "partition healed")

    def schedule_faults(self, plan) -> None:
        """Merge a ``faults.FaultPlan`` into the event heap; events fire at
        their absolute virtual-clock times, interleaved deterministically
        with protocol timers."""
        base = len(self._fault_events)
        self._fault_events.extend(plan.events)
        for i, ev in enumerate(plan.events):
            self._push(ev.t, f"f:{base + i}", ev.replica)

    # ------------------------------------------------------------- event loop
    def step_event(self, horizon: Optional[float] = None) -> bool:
        """Advance the clock to the next timer and handle it.

        ``horizon`` (set by ``run_for``) is the caller's drive window
        end: with K-tick fusion enabled (``fuse_k > 1``), a popped
        leader tick whose next K-1 successors provably fit before both
        the horizon and the next non-ignorable heap event is handled as
        ONE fused window (raft.steady.FusedDriver) instead of K
        separate events. Without a horizon the engine cannot know how
        far the caller meant to drive, so fusion never engages — every
        direct ``step_event()`` caller sees the legacy one-tick-per-
        event cadence unchanged."""
        if not self._q:
            return False
        hp = self.hostprof
        if hp is not None:
            hp.tick_begin()
        t, _, kind, r = heapq.heappop(self._q)
        self.clock.now = max(self.clock.now, t)
        tag, _, gen = kind.partition(":")
        stale = tag in ("e", "c") and int(gen) != self._timer_gen[r]
        #   stale timer generation (reset since armed): no action — but
        #   the pop still counts toward the mirror digest below, or a
        #   generation divergence would desynchronize the decision COUNT
        #   and cross-pair the digest exchange itself
        if hp is not None:
            hp.mark("heap_pop")
        if not stale:
            if tag == "e":
                self._fire_follower(r)
            elif tag == "c":
                self._fire_candidate(r)
            elif tag == "l":
                if not (
                    self._fused_driver is not None
                    and horizon is not None
                    and self._fused_driver.fire(r, horizon)
                ):
                    self._fire_leader_tick(r)
            elif tag == "f":
                ev = self._fault_events[int(gen)]
                {
                    "kill": self.fail,
                    "recover": self.recover,
                    "slow": lambda p: self.set_slow(p, True),
                    "unslow": lambda p: self.set_slow(p, False),
                    "campaign": self.force_campaign,
                    "partition": lambda p: self.partition(ev.groups),
                    "heal_partition": lambda p: self.heal_partition(),
                }[ev.action](ev.replica)
        if self.cfg.mirror_check_every:
            self._mirror_digest_step(
                t, kind + ("|stale" if stale else ""), r
            )
        # ---- online plane (docs/OBSERVABILITY.md "Online plane") ----
        # Per-tick/launch flush boundary: invariant scan over host
        # mirrors, SLO window evaluation, and the lock-free status
        # snapshot publish. Pure host work (no device fetch, no rng);
        # detached costs three None checks. Runs BEFORE hp.tick_end so
        # the attribution columns still tile the tick honestly.
        if self.auditor is not None:
            self.auditor.note_state(
                self.terms, self.commit_watermark, self.clock.now
            )
        if self.slo is not None:
            self.slo.maybe_evaluate(self.clock.now)
        if self.status_board is not None:
            self.status_board.publish(self._status_snapshot())
        if hp is not None:
            hp.tick_end()
        return True

    def _status_snapshot(self) -> dict:
        """The ``/status`` snapshot (obs.serve): host mirrors only —
        leader map, watermarks, replication lag (ingested-uncommitted
        depth), queue depths, audit summary. Built fresh per publish so
        the server thread always reads an immutable dict."""
        lead = self.leader_id
        snap = {
            "t_virtual": self.clock.now,
            "groups": 1,
            "leaders": {
                "0": (
                    {"replica": lead, "term": int(self.lead_terms[lead])}
                    if lead is not None else None
                )
            },
            "terms": [int(x) for x in self.terms],
            "roles": list(self.roles),
            "alive": [bool(a) for a in self.alive],
            "commit_watermark": {"0": int(self.commit_watermark)},
            "applied_index": {"0": int(self.applied_index)},
            "replication_lag": {"0": len(self._seq_at_index)},
            "queue_depth": {"0": len(self._queue)},
            "reads_pending": len(self._reads),
            "committed_total": self.committed_total,
            "fused": {
                "launches": self.fused_launches,
                "ticks": self.fused_ticks,
            },
        }
        if self.admission is not None:
            snap["shedding"] = bool(
                getattr(self.admission, "shedding", False)
            )
        if self.lease is not None or self.read_class_counts:
            reads = {"by_class": dict(self.read_class_counts)}
            if self.lease is not None and lead is not None:
                reads["lease"] = self.lease.summary(
                    lead, int(self.lead_terms[lead]), self.clock.now
                )
            snap["reads"] = reads
        if self._tiered_store is not None:
            # tiered-store section: seal/spill tallies, host bytes, RS
            # reconstructs — plus the shipper's live catch-up streams
            snap["tiered"] = self._tiered_store.tier_summary()
        if self._shipper.streams or self._shipper.chunks_total:
            snap["catchup"] = self._shipper.summary()
        if self.auditor is not None:
            snap["audit"] = self.auditor.summary()
        return snap

    # ------------------------------------------------ mirror desync guard
    def _mirror_digest_step(self, t: float, kind: str, r: int) -> None:
        """Fold one decision — the popped heap event plus the action's
        observable outcome (role, leader, watermark) — into the rolling
        digest; every ``cfg.mirror_check_every``-th decision, exchange
        digests across processes and FAIL-STOP on mismatch. The mirrored
        multihost control plane's only correctness argument is 'same
        inputs, same decisions, identical collective launches'
        (transport/multihost.py); any divergence that slips past it — a
        float compare, an OS-timing-dependent branch — would otherwise
        surface as a silently wrong collective or a hang. This converts
        it to a clean, attributable raise."""
        import zlib

        rec = (
            f"{t:.9f}|{kind}|{r}|{self.commit_watermark}|"
            f"{self.leader_id}|{','.join(self.roles)}|"
            f"{self._timer_gen}|"
            f"{sorted(self._quorum_contact_at.items())}"
        ).encode() + self.terms.tobytes() + self._last_heard.tobytes()
        #   the WHOLE host mirror — terms/roles AND the timer state that
        #   drives future fire decisions (_timer_gen, _last_heard,
        #   _quorum_contact_at) — not just the popped row's fields: a
        #   divergence must enter the digest at the very next decision,
        #   while the processes' collective launches still align — once
        #   launches themselves diverge, cross-paired collectives are
        #   undefined behavior no digest exchange can reliably report
        self._mirror_digest = zlib.crc32(rec, self._mirror_digest)
        self._mirror_decisions += 1
        if self._mirror_decisions % self.cfg.mirror_check_every == 0:
            self._verify_mirror_digest()

    def _verify_mirror_digest(self) -> None:
        """One tiny cross-process allgather of the digest scalar (rides
        the same fabric as every other collective — and, like them, is
        itself issued in lockstep because the decision COUNT is part of
        the mirrored stream). Single-process: no-op.

        The exchange itself is BOUNDED (``cfg.mirror_exchange_timeout_s``,
        ADVICE r5 #4): a digest comparison only happens at aligned
        decision counts, so a peer that stalls, dies, or diverges in
        COUNT between checks leaves this process blocked inside the
        allgather — the exact indefinite hang the guard exists to
        prevent. The collective therefore runs on a worker thread with a
        wall-clock bound; a stall or a transport error raises
        ``MirrorDesyncError`` exactly like a value mismatch. The stuck
        daemon thread is deliberately abandoned: the raise is a
        fail-stop and the process is expected to terminate (recovery is
        a process-group restart, transport.reform)."""
        if jax.process_count() == 1:
            return
        import threading

        from jax.experimental import multihost_utils

        # write-before-block (obs.blackbox): if this exchange wedges —
        # a peer died, diverged in count, or the fabric hung — the
        # journal's last line names this barrier, its decision count and
        # tick count, which is exactly what the stall bundle needs
        blackbox.mark(
            "barrier_enter", barrier="mirror_digest",
            decisions=self._mirror_decisions, tick=self._tick_count,
            digest=int(self._mirror_digest),
        )
        box: dict = {}

        def _exchange() -> None:
            try:
                box["digests"] = np.asarray(
                    multihost_utils.process_allgather(
                        np.int64(self._mirror_digest)
                    )
                ).ravel()
            except BaseException as ex:   # surfaced on the engine thread
                box["error"] = ex

        th = threading.Thread(
            target=_exchange, daemon=True, name="mirror-digest-exchange"
        )
        th.start()
        th.join(self.cfg.mirror_exchange_timeout_s)
        if "digests" not in box:
            err = box.get("error")
            why = (
                f"failed ({err!r})" if err is not None else
                f"did not complete within "
                f"{self.cfg.mirror_exchange_timeout_s:g}s — a peer "
                "process stalled, died, or diverged in decision count"
            )
            raise MirrorDesyncError(
                f"mirror digest exchange at decision "
                f"{self._mirror_decisions} {why}. The mirrored control "
                "planes can no longer be trusted to issue matching "
                "collectives — failing stop instead of hanging."
            )
        blackbox.mark(
            "barrier_exit", barrier="mirror_digest",
            decisions=self._mirror_decisions,
        )
        digests = box["digests"]
        if not (digests == digests[0]).all():
            raise MirrorDesyncError(
                f"mirrored control planes diverged at decision "
                f"{self._mirror_decisions}: per-process digests "
                f"{[int(d) for d in digests]} (this process: "
                f"{int(self._mirror_digest)}). A decision stream "
                "divergence means collective launches can no longer be "
                "trusted to match — failing stop instead of hanging."
            )

    def next_event_time(self) -> Optional[float]:
        """Virtual-clock time of the next pending event, or None when the
        heap is empty. Live drivers (raft_tpu.demo) pace this against wall
        time instead of calling ``run_for``."""
        return self._q[0][0] if self._q else None

    def run_for(self, seconds: float, max_events: int = 100_000) -> None:
        end = self.clock.now + seconds
        for _ in range(max_events):
            if not self._q or self._q[0][0] > end:
                break
            self.step_event(horizon=end)
        self.clock.now = max(self.clock.now, end)

    def run_until_leader(self, limit: float = 600.0) -> int:
        end = self.clock.now + limit
        while self.leader_id is None and self.clock.now < end and self._q:
            self.step_event()
        assert self.leader_id is not None, "no leader elected within limit"
        return self.leader_id

    def run_until_committed(self, seq: int, limit: float = 600.0) -> None:
        """Run until client entry ``seq`` is durable (see ``submit``)."""
        end = self.clock.now + limit
        while not self.is_durable(seq) and self.clock.now < end and self._q:
            self.step_event()
        assert self.is_durable(seq), (
            f"seq {seq} not committed (watermark {self.commit_watermark})"
        )

    # ----------------------------------------------------------- role actions
    def _fire_follower(self, r: int) -> None:
        """Election timeout (main.go:171-177): follower -> candidate."""
        if not self.alive[r] or self.roles[r] != FOLLOWER or not self.member[r]:
            return
        # A live current leader keeps resetting follower timers via its
        # heartbeats (main.go:124-127); replicate steps re-arm heard
        # followers, so a firing timer here means no current leader reached
        # this replica — campaign.
        if self.cfg.prevote and not self._prevote_wins(r):
            # §9.6: a would-be loser neither bumps its term nor disturbs
            # anyone — it stays a follower and tries again later. A
            # partitioned replica's term therefore stops inflating.
            self.nodelog(r, "pre-vote failed; staying follower")
            self._arm_follower(r)
            return
        self.roles[r] = CANDIDATE
        self.terms[r] += 1
        self.nodelog(r, "state changed to candidate")
        self._campaign(r)

    def _fire_candidate(self, r: int) -> None:
        """Candidate re-election timeout (main.go:248-251): term+1, retry."""
        if not self.alive[r] or self.roles[r] != CANDIDATE or not self.member[r]:
            return
        if self.cfg.prevote and not self._prevote_wins(r):
            # the retry would lose too (a leader re-emerged, or the
            # partition holds): demote without spending another term
            self.roles[r] = FOLLOWER
            self.nodelog(r, "pre-vote failed; state changed to follower")
            self._arm_follower(r)
            return
        self.terms[r] += 1
        self._campaign(r)

    def _prevote_wins(self, r: int) -> bool:
        """§9.6 PreVote round, host-side and NON-BINDING: would a member
        majority grant ``r`` a vote at term+1? A grantor refuses when it
        already sits at/above that term, when its log is more up to date
        (the device vote round's §5.4.1 check, mirrored here), or when
        it heard a live leader within the minimum election timeout
        (leader stickiness — the clause that makes a rejoining
        partitioned node harmless). Nothing is persisted and no device
        state changes: a losing pre-vote leaves the cluster exactly as
        it was, which is the entire point."""
        eff = self._voter_reach(r)   # learners cannot grant (§4.2.1)
        if not hasattr(self, "_last_keys_jit"):
            cap = self.state.capacity

            def _keys(state):
                lasts = state.last_index
                slots = (jnp.maximum(lasts, 1) - 1) % cap
                lt = jnp.take_along_axis(
                    state.log_term, slots[:, None], 1
                )[:, 0]
                return jnp.stack([lasts, jnp.where(lasts > 0, lt, 0)])

            self._last_keys_jit = jax.jit(_keys)
        lasts, last_terms = np.asarray(
            self._fetch(self._last_keys_jit(self.state))
        )
        cand_key = (int(last_terms[r]), int(lasts[r]))
        cand_term = int(self.terms[r]) + 1
        stick = self.cfg.follower_timeout[0]
        grants = 0
        for p in np.flatnonzero(eff):
            p = int(p)
            if int(self.terms[p]) >= cand_term:
                continue
            if (int(last_terms[p]), int(lasts[p])) > cand_key:
                continue
            if p != r and self.clock.now - self._last_heard[p] < stick:
                continue
            grants += 1
        return grants > int(self.member.sum()) // 2

    def _campaign(self, r: int) -> None:
        """One collective vote round (replaces the serial poll,
        main.go:253-284)."""
        cand_term = int(self.terms[r])
        eff = self._voter_reach(r)
        #   votes travel only inside the partition, and only to VOTERS:
        #   a learner neither grants nor counts (§4.2.1 non-voting)
        if self._dev_ring is not None:
            self.state, info, self._dev_ring = self.t.request_votes(
                self.state, r, cand_term, jnp.asarray(eff),
                ring=self._dev_ring, quorum=int(self.member.sum()) // 2,
            )
            self._flush_device_obs()
        else:
            self.state, info = self.t.request_votes(
                self.state, r, cand_term, jnp.asarray(eff)
            )
        votes = int(info.votes)
        max_term = int(info.max_term)
        self.terms[eff] = np.maximum(self.terms[eff], cand_term)
        # Durability fence: every replica's (term, votedFor) transition
        # from this vote round reaches disk before the engine acts on the
        # outcome (promotion, timers, further steps) — ckpt.votelog.
        self._persist_votes(self._fetch(self.state.voted_for))
        if max_term > cand_term:
            # someone is ahead; fall back to follower in the newer term
            self.terms[r] = max_term
            self._persist_votes()
            self.roles[r] = FOLLOWER
            self._arm_follower(r)
            return
        if votes > int(self.member.sum()) // 2:   # main.go:273, over members
            # A different leader's log may differ above the commit watermark,
            # so index->seq mappings for uncommitted entries are no longer
            # trustworthy: drop them (their seqs read as lost — conservative;
            # the reference silently loses such entries too, main.go:330).
            # The same replica re-winning keeps its own log, mappings intact.
            if self.leader_id != r:
                if (self._pending_config is not None
                        and self._pending_config[0] > self.commit_watermark):
                    # Raft rule: a server uses the latest configuration
                    # entry IN ITS LOG, committed or not. If the winner's
                    # log still holds the in-flight entry (same slot,
                    # same ingest term), the change stays active and
                    # commits later under the winner (Leader
                    # Completeness); only an entry the winner does NOT
                    # hold is rolled back (its seq never reads durable;
                    # the operator retries).
                    cidx, old_mask, _, cterm = self._pending_config
                    cslot = (cidx - 1) % self.state.capacity
                    holds = bool(
                        int(self._fetch(self.state.last_index)[r]) >= cidx
                        and int(self._fetch(
                            self.state.log_term)[r, cslot]) == cterm
                    )
                    if not holds:
                        self._rollback_pending_config(
                            r, "uncommitted configuration rolled back"
                        )
                kept_cfg = (
                    self._pending_config[0]
                    if self._pending_config is not None else None
                )
                self._seq_at_index = {
                    i: s for i, s in self._seq_at_index.items()
                    if i <= self.commit_watermark or i == kept_cfg
                }
                # Drop ingest-buffer entries no replica's log still holds
                # (every row's slot overwritten in a different term, or past
                # every row's tail) — those can never commit and would
                # otherwise be re-scanned by the EC heal every tick. An
                # entry ANY row still holds is KEPT even if that row is
                # currently dead: it can recover, win a later election
                # (longest log), and need the bytes re-served — the
                # stranded-suffix scenario tests/test_ec_integration
                # exercises.
                above = sorted(
                    i for i in self._uncommitted if i > self.commit_watermark
                )
                if above:
                    idx = np.asarray(above)
                    slots = (idx - 1) % self.state.capacity
                    # host-side fetch + numpy index: jnp fancy indexing
                    # would JIT-compile a gather per distinct slot-vector
                    # shape (seconds each through the tunnel)
                    terms_all = self._fetch(self.state.log_term)[:, slots]
                    lasts = self._fetch(self.state.last_index)
                    for col, i in enumerate(above):
                        buf_t = self._uncommitted[i][1]
                        held = (
                            (lasts >= i) & (terms_all[:, col] == buf_t)
                        ).any()
                        if not held:
                            del self._uncommitted[i]
            self.roles[r] = LEADER
            self.leader_id = r
            self.leader_term = cand_term
            self.lead_terms[r] = cand_term
            self._quorum_contact_at[r] = self.clock.now  # CheckQuorum lease
            self._steady = False   # matches reset per term; repair re-verifies
            # §5.4.2 floor for the fused steady program: everything this
            # leader appends from here on carries cand_term
            self._term_floor = int(self._pre_lasts()[r]) + 1
            # demote any stale leader bookkeeping (device already denied
            # it) — but only leaders this election could REACH: across a
            # partition a deposed-in-name leader keeps ticking in its own
            # term (true split-brain) until heal lets a step depose it
            for p in range(self.cfg.rows):
                if p != r and self.roles[p] == LEADER and self.connectivity[r, p]:
                    self.roles[p] = FOLLOWER
                    self._arm_follower(p)
            self.nodelog(r, "state changed to leader")
            if self.auditor is not None:
                # Election Safety, online: at most one winner per term
                self.auditor.note_elect(
                    f"Server{r}", cand_term, self.clock.now
                )
            self._metric_inc("raft_elections_total")
            if self.metrics is not None:
                self.metrics.gauge(
                    "raft_term", "highest term seen", ("group",),
                ).set_max(int(self.terms.max()), group="0")
            self._push(self.clock.now, f"l:{self._timer_gen[r]}", r)
        else:
            self._arm_candidate(r)

    def _fire_leader_tick(self, r: int) -> None:
        """One leader tick (main.go:332-395): batch ingest + replicate +
        commit, then re-arm. Also the followers' heartbeat: every heard
        replica's election timer resets.

        Ticks fire for ANY replica in the leader role, in ITS OWN term:
        under a partition a stale leader keeps ticking on its side of the
        split (heartbeating its group, committing nothing without quorum)
        until a heal lets a step report the higher term and depose it.
        Only the engine's routed leader (``leader_id`` — where ``submit``
        sends traffic) drains the client queue and runs heal bookkeeping;
        a stale leader's ticks are heartbeats."""
        if not self.alive[r] or self.roles[r] != LEADER:
            return
        term = int(self.lead_terms[r])
        if int(self.terms[r]) > term:
            # heard a higher term since winning (adoption rode another
            # source's step or vote round): step down instead of ticking
            self._step_down_leader(r, int(self.terms[r]))
            return
        cfg = self.cfg
        self._tick_count += 1
        self._metric_inc("raft_heartbeat_ticks_total")
        if cfg.check_quorum:
            # §9.6 CheckQuorum: renew the lease while a VOTER majority
            # is reachable (learners keep nobody in office); a leader cut
            # off for a full minimum election timeout demotes ITSELF
            # (same term — nothing was heard), silencing the minority
            # side of a partition instead of heartbeating a stale
            # leadership forever.
            if int(self._voter_reach(r).sum()) > int(self.member.sum()) // 2:
                self._quorum_contact_at[r] = self.clock.now
            elif (self.clock.now
                    - self._quorum_contact_at.setdefault(r, self.clock.now)
                    >= cfg.follower_timeout[0]):
                self.roles[r] = FOLLOWER
                if self.leader_id == r:
                    self.leader_id = None
                self.nodelog(
                    r, "step down to follower (lost quorum contact)"
                )
                self._arm_follower(r)
                return
        B = cfg.batch_size
        routed = self.leader_id == r
        eff = self._reach(r)
        if routed and (self.admission is not None or self.slo is not None):
            # Feed the delay controller the head-of-queue sojourn (0 on
            # an empty queue, which is what exits the shedding state).
            # Ticks are the drain cadence, so this is also the natural
            # observation cadence — the SLO tracker's queue-delay series
            # samples the same value.
            head_delay = 0.0
            if self._queue:
                head_delay = self.clock.now - self.submit_time.get(
                    self._queue[0][0], self.clock.now
                )
            if self.slo is not None:
                self.slo.observe("queue_delay", head_delay, self.clock.now)
        if routed and self.admission is not None:
            transition = self.admission.observe_delay(head_delay)
            if transition == "shed_start":
                self.nodelog(
                    r, f"admission shedding ON (head delay "
                    f"{head_delay:.1f}s >= target "
                    f"{self.admission.target_delay_s:g}s for a full "
                    f"interval)"
                )
            elif transition == "shed_stop":
                self.nodelog(r, "admission shedding OFF (delay back "
                                "under target)")
        if routed:
            # staged single-server ladders (add_server auto-promotion,
            # replace) advance first: they queue at most one config
            # entry, which the batch clamp below then handles like any
            # operator-submitted change
            self._drive_staged_config(r)
            # must run BEFORE the batch is taken from the queue: it may
            # prepend re-queued entries, and the post-step bookkeeping
            # maps self._queue[:ingested] to the appended indices
            self._make_room_for_current_term(r, term)
        take = min(len(self._queue), B) if routed else 0
        step_member = None
        if take:
            for qi, (qseq, _) in enumerate(self._queue[:take]):
                ch = self._config_seqs.get(qseq)
                if ch is not None:
                    # §4.1 append-time activation, for real: the step that
                    # APPENDS a configuration entry must already decide
                    # commits under the NEW configuration. Clamp the batch
                    # so the entry is its last element and hand the device
                    # step the new mask (host-side activation follows in
                    # _note_config_ingest once the append is confirmed).
                    # If ring backpressure would REFUSE the append this
                    # tick, the entry stays queued and the step keeps the
                    # old mask — the new quorum must never govern a step
                    # whose logs do not hold the entry.
                    last0 = int(self._fetch(self.state.last_index)[r])
                    commit0 = int(self._fetch(self.state.commit_index)[r])
                    room = self.state.capacity - (last0 - commit0)
                    if room >= qi + 1:
                        take = qi + 1
                        # the NEW configuration's VOTER mask — the only
                        # plane the device step counts quorums over (a
                        # learner change leaves it equal to the old one,
                        # so the quorum provably never moves on a
                        # learner add/remove)
                        step_member = np.array(ch[1][0], bool)
                    else:
                        take = qi    # everything before the entry only
                    break
        hp = self.hostprof
        if hp is not None:
            # pre-dispatch bookkeeping up to here is host_pre; the
            # payload build below is the ingest-batching (pack) phase
            hp.mark("host_pre")
        if take == 0:
            if self._hb_payload is None:
                self._hb_payload = jnp.zeros(
                    (B, cfg.rows * cfg.shard_words), jnp.int32
                )
            payload = self._hb_payload
        elif cfg.ec_enabled:
            # RS-encode the batch: shard row r is what replica r stores (the
            # scatter of the north star). Encode rides the platform-dispatched
            # kernel (ec.kernels: Pallas on TPU, bit-decomposition XLA
            # elsewhere); the shard rows fold into the device layout without
            # leaving the device.
            from raft_tpu.ec.kernels import encode_fold_device

            data = self._pack_entries(self._queue[:take], B)
            payload = encode_fold_device(self._code, jnp.asarray(data))
        else:
            # pack only the real entries; fold_batch pads to B in the int32
            # buffer (one copy of `take` rows, not B)
            payload = fold_batch(
                self._pack_entries(self._queue[:take], take),
                cfg.rows, B,
            )
        if hp is not None:
            hp.mark("pack")
        pre_lasts = self._pre_lasts()
        floor, fpt = self._floor_attest(r)
        repair = self._repair_program()
        if repair:
            self._metric_inc("raft_repair_rounds_total")
        if hp is not None:
            # the floor-attest / cached-lasts fetches above are part of
            # the per-tick host round-trip the attribution exists to
            # expose — charged to host_pre, not device_wait
            hp.mark("host_pre")
        member_arg = (jnp.asarray(step_member) if step_member is not None
                      else self._member_arg())
        # launch-boundary annotation (obs.profiling): nullcontext
        # unless an on-demand profiler capture is in flight
        with _profiling.launch_annotation("leader_tick", self._tick_count):
            if self._dev_ring is not None:
                self.state, info, self._dev_ring = self.t.replicate(
                    self.state, payload, take, r, term, jnp.asarray(eff),
                    jnp.asarray(self.slow), repair=repair,
                    member=member_arg,
                    repair_floor=floor, floor_prev_term=fpt,
                    term_floor=self._term_floor, ring=self._dev_ring,
                )
            else:
                self.state, info = self.t.replicate(
                    self.state, payload, take, r, term, jnp.asarray(eff),
                    jnp.asarray(self.slow), repair=repair,
                    member=member_arg,
                    repair_floor=floor, floor_prev_term=fpt,
                    term_floor=self._term_floor,
                )
        if hp is not None:
            hp.mark("dispatch")
            hp.sync(self.state, info)
        # device-obs flush AFTER the profiler's dispatch/device_wait
        # marks: its packed fetch forces a sync, and running it inside
        # the dispatch window would misattribute flush cost to the step
        self._flush_device_obs()
        self._note_truncations(pre_lasts)
        max_term = int(info.max_term)
        if max_term > term:
            # nothing was consumed from the queue: the device step refused
            # ingest/commit for the stale term
            self._step_down_leader(r, max_term)
            return
        # Heard replicas adopted the leader's term on device (core.step);
        # keep the host mirror in sync so post-failover campaigns start from
        # the real term, not a stale one.
        self.terms[eff] = np.maximum(self.terms[eff], term)
        self._persist_votes()   # term adoptions reach disk before commit acts
        # Ring backpressure: the device step ingests at most `room` entries
        # (never overwriting uncommitted slots); anything it left behind
        # stays queued for a later tick.
        ingested = int(info.frontier_len)
        if ingested:
            last = int(self._fetch(self.state.last_index)[r])  # post-ingest
            base = last - ingested
            chunk = self._queue[:ingested]
            if self._config_seqs or self.spans is not None:
                for i, (seq, p) in enumerate(chunk):
                    idx = base + 1 + i
                    self._seq_at_index[idx] = seq
                    self._uncommitted[idx] = (p, term)
                    self._note_config_ingest(idx, seq, term)
                    if self.spans is not None:
                        self.spans.note_ingest(
                            seq, idx, self.clock.now, self._tick_count
                        )
            else:
                # host_post micro-fix (docs/PERF.md attribution table):
                # the per-entry seq→index mapping is two bulk dict
                # updates instead of a Python loop with per-item index
                # arithmetic — same mappings, ~5x less host time at the
                # headline batch
                self._seq_at_index.update(
                    zip(range(base + 1, last + 1), (s for s, _ in chunk))
                )
                self._uncommitted.update(
                    (base + 1 + i, (p, term))
                    for i, (_, p) in enumerate(chunk)
                )
            self._queue = self._queue[ingested:]
            if self._fused_driver is not None:
                self._fused_driver.on_consumed(ingested)
        self._advance_commit(r, int(info.commit_index))
        self._confirm_reads(r, term, eff, max_term)
        #   every successful tick round doubles as the §6.4 read
        #   confirmation: queued reads ride the write traffic for free
        if routed:
            # heal bookkeeping and the shared steady flag belong to the
            # routed leader only — a stale split-brain leader must not
            # poison either with its own group's view
            if cfg.ec_enabled:
                self._ec_heal(r, info)
            else:
                self._snapshot_heal(r, info)
            self._update_steady(r, info.match, eff)
        self._reset_heard_timers(r)
        self._push(self.clock.now + cfg.heartbeat_period, "l:x", r)

    def _truncate_uncommitted_tail(self, cut: int, lasts) -> int:
        """Shared truncation machinery: drop every row's uncommitted
        entries above ``cut`` (re-queuing the bytes the host still holds
        so they commit at fresh indices), bump ring-validity floors for
        every truncated row, clamp device last/match everywhere, and
        invalidate the lasts cache. ``lasts`` is the pre-truncation
        last_index vector. Returns the number of re-queued entries.
        Callers guarantee cut >= commit_watermark (never touches
        committed entries)."""
        assert cut >= self.commit_watermark
        cap = self.state.capacity
        old_max = int(np.max(np.asarray(lasts)))
        # An in-flight configuration entry inside the truncated range is
        # leaving EVERY row's log (last_index clamps to cut below). Raft's
        # rule — a server uses the latest configuration entry in its log —
        # then demands the previous configuration: roll the membership
        # back and drop the RCFG bytes (its seq reads as lost, like the
        # campaign holds-check rollback; the operator retries). Re-queuing
        # them as a plain data entry would leave ``_pending_config``
        # pointing at an index a DIFFERENT entry later occupies, and
        # ``_advance_commit`` would then "commit" the configuration off
        # the wrong entry.
        cfg_idx = None
        if self._pending_config is not None and \
                cut < self._pending_config[0] <= old_max:
            cfg_idx = self._pending_config[0]
            self._rollback_pending_config(
                self.leader_id if self.leader_id is not None else 0,
                "uncommitted configuration rolled back (entry truncated)",
            )
        requeue = []
        for i in range(cut + 1, old_max + 1):
            ent = self._uncommitted.pop(i, None)
            seq = self._seq_at_index.pop(i, None)
            if ent is not None and seq is not None and i != cfg_idx:
                requeue.append((seq, ent[0]))
        self._queue = requeue + self._queue
        if self._fused_driver is not None:
            # a prepend breaks the staging ring's queue mirror
            self._fused_driver.on_queue_replaced()
        for q in range(self.cfg.rows):
            if int(lasts[q]) > cut:
                self._ring_floor[q] = max(
                    self._ring_floor[q], int(lasts[q]) - cap + 1
                )
        cut_arr = jnp.asarray(cut, self.state.last_index.dtype)
        self.state = self.state.replace(
            last_index=jnp.minimum(self.state.last_index, cut_arr),
            match_index=jnp.minimum(self.state.match_index, cut_arr),
        )
        self._lasts_snapshot = None
        self._match_snapshot = None
        self._steady = False
        # re-appends land at cut+1 under the current term: the §5.4.2
        # floor must never sit above the first current-term index
        self._term_floor = min(self._term_floor, cut + 1)
        return len(requeue)

    def _make_room_for_current_term(self, r: int, term: int) -> None:
        """Escape the bounded-log §5.4.2 deadlock: when the ring is FULL
        of uncommitted OLD-term entries, nothing can commit (only
        current-term entries commit directly) and nothing can be appended
        (no room) — a wedge standard Raft avoids with a term-start no-op,
        which this engine skips to keep committed logs byte-identical to
        the oracle. The leader truncates one batch of its never-acked
        tail cluster-wide (every row's verified match clamps with it, so
        stale matches over the old tail can never count toward a commit
        of the replacement entries) and re-queues the bytes it still
        holds; they commit at fresh indices under the current term.
        Safety: the dropped entries were uncommitted and no client ever
        saw them durable."""
        cap = self.state.capacity
        lasts = self._pre_lasts()
        last = int(lasts[r])
        if last - self.commit_watermark < cap:
            return                        # room exists: no deadlock
        tail_term = int(
            self._fetch(self.state.log_term)[r, (last - 1) % cap]
        )
        if tail_term >= term:
            return                        # current-term tail commits normally
        drop = min(self.cfg.batch_size, last - self.commit_watermark)
        cut = last - drop
        n = self._truncate_uncommitted_tail(cut, lasts)
        self.nodelog(
            r, f"old-term tail ({cut}, {last}] truncated to unwedge "
            f"the full ring; {n} entries re-queued"
        )

    def _repair_program(self) -> bool:
        """Which step program the next replicate runs: the repair-capable
        one unless the cluster is verified steady AND the config opts into
        the steady-dispatch fast path (cfg.steady_dispatch)."""
        if self.cfg.steady_dispatch == "off":
            return True
        return not self._steady

    def _effective_match(self, term: int, match) -> np.ndarray:
        """Host view of the step's verified match vector with LEARNER
        rows filled in from device state. ``RepInfo.match`` is masked by
        the device ack mask (voters only — the §4.2.2 guarantee that a
        non-voter ack never counts toward commit), so a learner's
        progress reads 0 there; the heal and steady consumers need the
        real value or they would snapshot-install a caught-up learner
        forever. No extra fetch on learner-free clusters, and at most
        ONE per step otherwise: the (match_index, match_term) fetch is
        cached like ``_lasts_snapshot`` (same invalidation points), so
        the heal pass and the steady update of one tick share it."""
        match = np.asarray(match).copy()
        if self.learner.any():
            if self._match_snapshot is None:
                self._match_snapshot = np.asarray(self._fetch(jnp.stack(
                    [self.state.match_index, self.state.match_term]
                )))
            mi_mt = self._match_snapshot
            lr = self.learner
            match[lr] = np.where(mi_mt[1][lr] == term, mi_mt[0][lr], 0)
        return match

    def _update_steady(self, r: int, match, eff=None) -> None:
        """After a replicate step: every live non-slow follower verified up
        to the leader's tail -> the next step may run the steady-state
        (repair-free) program. ``match`` arrives as the un-materialized
        device array so the "off" mode really skips the host sync.
        ``eff`` is the step's effective reach (partition-aware); rows the
        leader cannot reach are not the repair window's business.
        Learners count: a lagging learner keeps the repair program
        dispatched (its catch-up IS repair traffic)."""
        if self.cfg.steady_dispatch == "off":
            return  # _repair_program never reads _steady
        match = self._effective_match(int(self.lead_terms[r]), match)
        others = (self.alive if eff is None else eff) & ~self.slow
        others[r] = False
        leader_last = int(self._fetch(self.state.last_index)[r])
        self._steady = bool((match[others] >= leader_last).all())

    def _advance_commit(self, r: int, commit: int) -> None:
        """Host bookkeeping for a device-reported commit advance: stamp
        durable seqs, archive to the checkpoint store, prune buffers."""
        if commit > self._row_commit[r]:
            # r's own view of its commit index — maintained for EVERY
            # round (even no-advance ones) so the lease read plane
            # serves the leader's local knowledge, never the global
            # watermark a partitioned stale leader could not possess
            self._row_commit[r] = commit
        if commit <= self.commit_watermark:
            return
        if (self.roles[r] == LEADER
                and int(self.terms[r]) == int(self.lead_terms[r])):
            # a watermark advance riding r's own round commits a
            # CURRENT-term entry (§5.4.2: only current-term entries
            # commit directly) — the §6.4 lease-serve precondition
            self._lease_ok_term[r] = int(self.lead_terms[r])
        old_wm = self.commit_watermark
        slo_lat = [] if self.slo is not None else None
        now = self.clock.now
        sq_get = self._seq_at_index.get
        st_get = self.submit_time.get
        ct = self.commit_time
        need_lat = self.metrics is not None or slo_lat is not None
        for idx in range(self.commit_watermark + 1, commit + 1):
            seq = sq_get(idx)
            if seq is not None and seq not in ct:
                ct[seq] = now
                self.committed_total += 1
                lat = (now - st_get(seq, now)) if need_lat else 0.0
                if self.spans is not None:
                    self.spans.note_commit(seq, now, self._tick_count)
                if self.metrics is not None:
                    self._metric_inc("raft_commits_total")
                    self.metrics.histogram(
                        "raft_commit_latency_seconds",
                        "submit -> durable, virtual seconds", ("group",),
                    ).observe(lat, group="0")
                if slo_lat is not None:
                    slo_lat.append(lat)
        if slo_lat:
            # one vectorized digest/window update per advance, not one
            # Python call per entry (the <= 5% overhead contract)
            self.slo.observe_batch("commit", slo_lat, now)
        self._archive_committed(r, self.commit_watermark + 1, commit)
        self.commit_watermark = commit
        if self.auditor is not None:
            self.auditor.note_commit(commit, self.clock.now)
        self.nodelog(r, f"commit index changed to {commit}")
        if self._pending_config is not None and self._pending_config[0] <= commit:
            idx = self._pending_config[0]
            self._pending_config = None
            self.nodelog(r, f"configuration committed at {idx}")
            # A wiped voter's old identity is gone for good only now
            # that its removal is DURABLE: clear the wiped flag for rows
            # the committed configuration no longer counts as voters, so
            # they may restart (as fresh learners via replace's ladder).
            self._wiped &= self.member
            lead = self.leader_id
            if lead is not None and not self.member[lead]:
                # the leader managed itself out of the cluster; now that
                # the change is durable it steps down (dissertation
                # §4.2.2) and the remaining members elect
                self.roles[lead] = FOLLOWER
                self.leader_id = None
                self.nodelog(lead, "step down to follower (removed)")
        # host_post micro-fix: prune by the known just-committed RANGE
        # instead of scanning the whole dict per commit (both maps hold
        # only indices above the previous watermark, all > old_wm, and
        # anything <= commit is in [old_wm+1, commit] by construction)
        for idx in range(old_wm + 1, commit + 1):
            self._uncommitted.pop(idx, None)
            self._seq_at_index.pop(idx, None)
        self._evict_commit_stamps()
        self._drain_apply()

    def _reset_heard_timers(self, r: int) -> None:
        """Replication traffic is the heartbeat: every heard follower's
        election timer resets (main.go:124-127) and a candidate hearing a
        current leader steps down (main.go:204-217)."""
        self._last_heard[r] = self.clock.now
        #   the source hears itself: a live leader must refuse pre-votes
        #   against its own leadership (§9.6 stickiness)
        for p in range(self.cfg.rows):
            if p == r or not self.alive[p] or not self.connectivity[r, p]\
                    or not (self.member[p] or self.learner[p]):
                continue   # unreachable replicas hear nothing
            self._last_heard[p] = self.clock.now   # §9.6 stickiness clock
            if not self.member[p]:
                continue   # learners run no election timers: non-voting
            if self.roles[p] == FOLLOWER:
                self._arm_follower(p)
            elif self.roles[p] == CANDIDATE:
                self.roles[p] = FOLLOWER
                self._arm_follower(p)
            elif self.roles[p] == LEADER and self.lead_terms[r] > self.lead_terms[p]:
                # a stale leader hearing a newer leader's traffic steps
                # down (main.go:309-321); its device row already adopted
                self.roles[p] = FOLLOWER
                self.nodelog(p, "step down to follower")
                self._arm_follower(p)

    def _archive_committed(self, leader: int, lo: int, hi: int) -> None:
        """Move the just-committed range [lo, hi] into the checkpoint store.

        Primary source is the host ingest buffer; entries missing from it
        (e.g. pruned across a leadership change but committed anyway by the
        new leader, per Leader Completeness) are read back from the
        leader's device log — the just-committed window is inside the ring
        by construction. Under EC the device holds only shards, so missing
        entries are reconstructed from the leader + any k-1 live holders;
        if that fails the range is left unarchived (a later snapshot for it
        is simply not offered)."""
        from raft_tpu.core.state import log_entries

        # The buffer entry is only trustworthy if its ingest term matches
        # the committing leader's log at that index — a suffix superseded
        # across leadership changes can leave a stale (bytes, term) pair at
        # an index the new leader committed DIFFERENT bytes for (the same
        # guard the EC re-serve path applies). Mismatches fall through to
        # the device read below.
        slots_all = (np.arange(lo, hi + 1) - 1) % self.state.capacity
        # whole-row fetch + numpy index (not jnp fancy indexing: that
        # compiles a fresh gather per slot-vector shape)
        lead_terms = self._fetch(self.state.log_term)[leader, slots_all]
        missing = []
        aud = self.auditor
        fed = [] if aud is not None else None
        for i, idx in enumerate(range(lo, hi + 1)):
            ent = self._uncommitted.get(idx)
            if ent is not None and ent[1] == int(lead_terms[i]):
                self.store.put(idx, ent[0], ent[1])
                if fed is not None:
                    fed.append((idx, ent[0], ent[1]))
            else:
                missing.append(idx)
        if fed:
            # committed-prefix immutability feed: fresh contiguous runs
            # record as one lazy span (O(1)); a re-archive of an
            # already-recorded index is compared byte-for-byte
            aud.note_entries(fed, self.clock.now)
        if not missing:
            return
        mlo, mhi = min(missing), max(missing)
        slots = (np.arange(mlo, mhi + 1) - 1) % self.state.capacity
        terms = self._fetch(self.state.log_term)[leader, slots]
        try:
            if self.cfg.ec_enabled:
                from raft_tpu.ec.reconstruct import reconstruct

                commits = self._fetch(self.state.commit_index)
                # A donor's ring must actually HOLD the range: slots below
                # its ring floor were never written (snapshot installs).
                donors = [
                    q
                    for q in ([leader] + [
                        p for p in range(self.cfg.rows) if p != leader
                    ])
                    if self.alive[q] and int(commits[q]) >= mhi
                    and int(self._ring_floor[q]) <= mlo
                    and self.connectivity[leader, q]
                ]
                if len(donors) < self.cfg.rs_k:
                    return
                data = reconstruct(
                    self.state, self._code, donors[: self.cfg.rs_k], mlo, mhi
                )
            else:
                if int(self._ring_floor[leader]) > mlo:
                    return  # ring never held the range; archive stays short
                data = log_entries(self.state, leader, mlo, mhi,
                                   fetch=self._fetch)
        except ValueError:
            return
        for idx in missing:
            payload = data[idx - mlo].tobytes()
            self.store.put(idx, payload, int(terms[idx - mlo]))
            if self.auditor is not None:
                self.auditor.note_entry(
                    idx, int(terms[idx - mlo]), payload, self.clock.now
                )

    def _catchup_budget(self) -> int:
        """Chunks the catch-up lane may ship this tick: the admission
        gate's background-lane decision (throttled to 1 while the write
        lane is congested), or the configured maximum when admission is
        disabled."""
        mx = self.cfg.catchup_max_chunks_per_tick
        if self.admission is None:
            return mx
        return self.admission.catchup_chunks(len(self._queue), mx)

    def _stream_snapshot(self, replica: int, lo: int, hi: int) -> Optional[int]:
        """Ship this tick's budget of snapshot chunks toward installing
        the committed range [lo, hi] (clamped to one ring capacity) into
        ``replica`` from the checkpoint store. Returns the index the
        replica is installed through after this tick (None when nothing
        could ship — store gap, or range empty). Incremental install
        (ckpt.ship): each chunk advances the replica's device match, so
        the stream RESUMES from the last acked chunk across kills,
        leader changes and restarts — and the admission gate's catch-up
        lane throttles it under foreground load instead of letting one
        rejoining replica stall commits."""
        from raft_tpu.ckpt import install_snapshot

        lo = max(lo, hi - self.state.capacity + 1, 1)
        if hi < lo:
            return None
        streaming = self._shipper.is_streaming(replica)
        prev_next = (
            self._shipper.streams[replica].next if streaming else None
        )
        raise_floor = not streaming
        chunks = self._shipper.plan(
            replica, lo, hi, self._catchup_budget()
        )
        if prev_next is not None and chunks and chunks[0][0] > prev_next:
            # the ring-tail clamp overtook the acked cursor mid-stream
            # (a throttled stream chasing a moving watermark): indices
            # [prev_next, new cursor) were SKIPPED, not installed —
            # the validity floor must rise past the gap or donor/read
            # checks would trust lap-stale slots above the old base
            raise_floor = True
        reached = None
        for clo, chi in chunks:
            if not self.store.covers(clo, chi):
                break      # archive gap: the replica keeps waiting
            self.state = install_snapshot(
                self.state, replica, self.store.snapshot(clo, chi),
                self.leader_term, self.cfg.batch_size, self._code,
            )
            if raise_floor:
                # Only [clo, ...] onward is being written; slots below
                # this stream segment's start keep whatever they held
                # (junk, for a lapped ring). Later contiguous chunks
                # extend the valid range upward, so the floor rises
                # once per (re)based stream.
                self._ring_floor[replica] = max(
                    self._ring_floor[replica], clo
                )
                raise_floor = False
            self._shipper.acked(replica, chi)
            self._metric_inc(
                "raft_snapshot_chunks_total",
                "incremental snapshot-install chunks shipped",
            )
            reached = chi
        if reached is not None:
            self._lasts_snapshot = None  # last_index moved outside a step
            self._match_snapshot = None  # ...and so did match_index
            self.nodelog(replica, f"snapshot chunk installed to {reached}")
            if reached >= hi:
                self._metric_inc("raft_snapshot_installs_total")
                self._shipper.finish(replica)
                self.nodelog(
                    replica, f"snapshot stream complete at {hi}"
                )
        return reached

    def _snapshot_heal(self, leader: int, info) -> None:
        """Snapshot-install for ring-lapped replicas (plain replication).

        The repair window cannot heal a replica whose next needed index is
        below the leader's ring horizon (core.step clamps it — accepting
        wrapped bytes would corrupt). Such a replica's verified match stays
        pinned while everyone else progresses; after two stalled ticks
        (one leadership-change transient is forgiven — matches reset per
        term and re-verify via the repair window within a tick), STREAM a
        snapshot of the committed prefix from the checkpoint store —
        ``_stream_snapshot`` ships an admission-budgeted number of chunks
        per tick, resuming from the device match cursor — until the
        replica is back inside the repair window's reach, which then
        covers (snapshot, leader_last]."""
        cap = self.state.capacity
        match = self._effective_match(int(self.lead_terms[leader]), info.match)
        leader_last = int(self._fetch(self.state.last_index)[leader])
        # the repair window cannot serve below the leader's ring-validity
        # floor either (truncated-after-wrap slots hold junk): such
        # followers also need a snapshot install from the archive
        horizon = max(leader_last - cap + 1, int(self._ring_floor[leader]))
        for p in range(self.cfg.rows):
            if (p == leader or not self.alive[p] or self.slow[p]
                    or not (self.member[p] or self.learner[p])
                    or not self.connectivity[leader, p]):
                # learners heal exactly like members: snapshot install is
                # how a wiped/fresh learner rejoins from nothing. A dead
                # replica KEEPS its stream — resume-on-recover is the
                # kill-mid-stream contract — but a deconfigured row's is
                # abandoned.
                self._match_stall[p] = 0
                if not (self.member[p] or self.learner[p]):
                    self._shipper.finish(p)
                continue
            if int(match[p]) + 1 >= horizon:
                self._match_stall[p] = 0
                self._shipper.finish(p)
                continue
            self._match_stall[p] += 1
            if self._match_stall[p] < 2:
                continue
            self._stream_snapshot(
                p, int(match[p]) + 1, self.commit_watermark
            )

    def _ec_heal(self, leader: int, info) -> None:
        """Two-phase repair for erasure-coded logs.

        With EC on there is no leader-log repair window (the leader holds
        only its own shard row), so a live replica that missed a window can
        never re-join via AppendEntries. Heal it instead:

        - committed range: reconstruct from k shard-holders and install the
          replica's re-encoded shards (heal_replica — the EC
          InstallSnapshot); refuses ring-lapped donors (ValueError -> the
          replica waits for the checkpoint subsystem).
        - uncommitted suffix: re-serve full entries from the host
          ``_uncommitted`` buffer (fewer than commit_quorum replicas hold
          those shards, so reconstruction can't; without this path two
          recovered followers would stall commit forever at the k+margin
          quorum). Terms are verified against the current leader's log so a
          buffer entry superseded across leadership changes is never
          installed."""
        from raft_tpu.ec.reconstruct import heal_replica, install_entries

        match = self._effective_match(int(self.lead_terms[leader]), info.match)
        n, k = self.cfg.rows, self.cfg.rs_k
        leader_last = int(self._fetch(self.state.last_index)[leader])
        hi_rec = self.commit_watermark
        for p in range(n):
            if (p == leader or not self.alive[p] or self.slow[p]
                    or not self.connectivity[leader, p]
                    or not (self.member[p] or self.learner[p])):
                # spare (non-member) rows idle unhealed until added; a
                # REMOVED row's committed shards still serve as donor
                # material below (donor criteria are data-based).
                # Learners heal like members — catch-up is the learner
                # phase's whole job.
                continue
            if match[p] >= leader_last:
                continue
            lo = int(match[p]) + 1
            if lo <= hi_rec:
                # Donor criterion is the replica's own committed prefix, NOT
                # current-term match: committed entries are immutable, so a
                # replica whose commit_index covers the range holds valid
                # shards even if its term-scoped match was reset by a
                # leadership change (otherwise healing wedges after failover:
                # every follower's match is 0 in the new term although all
                # of them hold the committed shards).
                commits = self._fetch(self.state.commit_index)
                donors = [
                    q for q in range(n)
                    if self.alive[q] and int(commits[q]) >= hi_rec
                    and self.connectivity[leader, q]
                ]
                if len(donors) < k:
                    continue
                try:
                    self.state = heal_replica(
                        self.state, self._code, p, donors[:k], lo, hi_rec,
                        self.leader_term, hi_rec, self.cfg.batch_size,
                    )
                    self._lasts_snapshot = None
                    self._match_snapshot = None
                    self.nodelog(p, f"healed by reconstruction to {hi_rec}")
                except ValueError:
                    # Below every donor's ring horizon: reconstruction would
                    # decode lapped slots into garbage. Stream a snapshot
                    # of the committed prefix from the checkpoint store
                    # instead (the EC InstallSnapshot proper) — chunked
                    # like the plain path; the uncommitted-suffix re-serve
                    # below waits until the stream completes.
                    reached = self._stream_snapshot(p, lo, hi_rec)
                    if reached is None or reached < hi_rec:
                        continue
                lo = hi_rec + 1
            if lo <= leader_last:
                idx = list(range(lo, leader_last + 1))
                missing = [i for i in idx if i not in self._uncommitted]
                if missing:
                    # The host buffer lost these bytes across leadership
                    # changes, but every replica whose CURRENT-term
                    # verified match covers the suffix holds consistent
                    # shards (Log Matching) — k of those reconstruct the
                    # full entries and refill the buffer. Without this, a
                    # single unservable index wedges the quorum forever
                    # (found by the EC chaos sweep).
                    self._refill_uncommitted_from_shards(leader, missing)
                    missing = [i for i in idx if i not in self._uncommitted]
                if missing:
                    # Still unservable. If an index's shards survive on
                    # fewer than k rows ANYWHERE (dead included), its
                    # bytes are gone for good and the whole suffix above
                    # it can never commit — abandon it (it was never
                    # acked durable) instead of wedging the quorum
                    # forever. Otherwise a dead holder may recover: wait.
                    if self._ec_abandon_lost_suffix(leader, missing):
                        return
                    continue  # transient: dead shard holders may recover
                slots = (np.asarray(idx) - 1) % self.state.capacity
                log_terms = self._fetch(self.state.log_term)[leader, slots]
                if any(
                    self._uncommitted[i][1] != int(t)
                    for i, t in zip(idx, log_terms)
                ):
                    continue  # superseded across a leadership change
                data = np.frombuffer(
                    b"".join(self._uncommitted[i][0] for i in idx), np.uint8
                ).reshape(len(idx), self.cfg.entry_bytes)
                shards = self._code.encode_host(data)[p]
                self.state = install_entries(
                    self.state, p, lo, shards, log_terms,
                    self.leader_term, self.commit_watermark,
                    self.cfg.batch_size,
                )
                self._lasts_snapshot = None
                self._match_snapshot = None
                self.nodelog(p, f"suffix re-served to {leader_last}")

    def _ec_abandon_lost_suffix(self, leader: int, missing) -> bool:
        """Liveness escape for permanently unrecoverable UNCOMMITTED
        entries: if some missing index's shards survive on fewer than k
        rows in total (aliveness aside), RS decode can never rebuild its
        bytes, no follower can ever pass the prev-check above it, and the
        k+margin quorum is wedged for good. The leader abandons the
        suffix from the first such index: truncates every row's tail
        back, drops the mappings (those seqs read as lost — they were
        never durable), and re-queues the dropped entries whose bytes the
        host still holds so they commit at fresh indices. Returns True if
        a truncation happened."""
        cap = self.state.capacity
        lasts = self._fetch(self.state.last_index)
        lterms = self._fetch(self.state.log_term)
        first_lost = None
        for i in sorted(missing):
            slot = (i - 1) % cap
            want = int(lterms[leader, slot])
            holders = sum(
                1 for q in range(self.cfg.rows)
                if int(lasts[q]) >= i
                and int(lterms[q, slot]) == want
                and int(lasts[q]) - cap + 1 <= i
                and int(self._ring_floor[q]) <= i
            )
            if holders < self.cfg.rs_k:
                first_lost = i
                break
        if first_lost is None:
            return False
        cut = first_lost - 1
        old_last = int(lasts[leader])
        n = self._truncate_uncommitted_tail(cut, lasts)
        self.nodelog(
            leader,
            f"unrecoverable uncommitted suffix [{first_lost}, {old_last}] "
            f"abandoned (< {self.cfg.rs_k} shard holders); "
            f"{n} entries re-queued",
        )
        return True

    def _refill_uncommitted_from_shards(self, leader: int, indices) -> None:
        """Rebuild lost ingest-buffer bytes for UNCOMMITTED indices from
        k replicas whose current-term verified match covers them (their
        shards are consistent with the leader's log by Log Matching).
        Quietly does nothing when fewer than k such holders exist — the
        caller's give-up path handles that."""
        from raft_tpu.ec.reconstruct import reconstruct

        k = self.cfg.rs_k
        lo, hi = min(indices), max(indices)
        matches = self._fetch(self.state.match_index)
        mterms = self._fetch(self.state.match_term)
        lasts = self._fetch(self.state.last_index)
        donors = [
            q for q in range(self.cfg.rows)
            if self.alive[q] and self.connectivity[leader, q]
            and int(mterms[q]) == self.leader_term
            and int(matches[q]) >= hi
            # the donor's ring must still HOLD the range: neither lapped
            # (slot overwritten past one capacity) nor below its install
            # floor — gather_shard_window itself checks nothing
            and int(lasts[q]) - self.state.capacity + 1 <= lo
            and int(self._ring_floor[q]) <= lo
        ]
        if len(donors) < k:
            return
        data = reconstruct(self.state, self._code, donors[:k], lo, hi)
        slots = (np.arange(lo, hi + 1) - 1) % self.state.capacity
        terms = self._fetch(self.state.log_term)[leader, slots]
        for i in indices:
            self._uncommitted[i] = (
                data[i - lo].tobytes(), int(terms[i - lo])
            )
        self.nodelog(
            leader, f"uncommitted suffix [{lo}, {hi}] rebuilt from shards"
        )

    # ---------------------------------------------------- state machine
    def register_apply(
        self, fn: Callable[[int, bytes], None], replay: bool = False
    ) -> int:
        """Register a state-machine apply callback: ``fn(index, payload)``
        is invoked for every committed entry, in log order, exactly once
        per engine lifetime. The reference stores values and never applies
        them (no state machine exists, SURVEY §2); this hook completes the
        replicated-state-machine story.

        ``replay=True`` first replays the archived committed tail (from
        the oldest contiguously archived index up to the watermark) —
        the restart path: after ``RaftEngine.restore`` a fresh state
        machine rebuilds from the restored log. Returns the first index
        the callback will have seen (1 = full history). The archive
        retains ``2 * log_capacity`` entries, so a log longer than that
        replays PARTIALLY (returns > 1, with a nodelog warning) — an
        application needing full history beyond that must snapshot its own
        state-machine state, the standard Raft compaction contract. If the
        watermark entry itself is unarchived (the EC archive's give-up
        path) a replay cannot even anchor and raises. With
        ``replay=False`` the callback sees only entries committed after
        registration."""
        # Replay ends where the shared stream takes over: the watermark for
        # the first registrant (which also sets the cursor there), the
        # current cursor for later registrants — the shared stream then
        # delivers everything past it exactly once, in order, so a late
        # joiner never sees duplicates even while the cursor is paused
        # behind an archive gap.
        end = self.commit_watermark if not self._apply_fns else self.applied_index
        if replay and end == 0 and self.commit_watermark > 0:
            # Non-first registrant while the shared cursor is still at 0
            # (the first registrant joined pre-commit and _drain_apply is
            # paused at an archive gap): silently downgrading to no-replay
            # would skip indices 1..watermark for this registrant forever.
            # Anchor the replay at the watermark instead — the shared
            # stream only delivers indices >= this registrant's start, so
            # no duplicates when the gap later heals, and the replay probe
            # below may even backfill the gap.
            end = self.commit_watermark
        if replay and end > 0:
            lo = self.store.covered_lo(end)
            # A gap below the covered range may be a *transient* archive
            # give-up rather than compaction — recoverable from the device
            # log; extend coverage downward before declaring history lost.
            # (quiet probe: hitting the compaction floor here is expected,
            # not an apply-stream wedge)
            while lo > 1 and self._backfill_archive(lo - 1, quiet=True):
                lo = self.store.covered_lo(end)
            if lo > end:
                raise ValueError(
                    f"cannot replay: committed entry {end} is not archived"
                )
            if lo > 1:
                self.nodelog(
                    0, f"apply replay is partial: history starts at {lo} "
                    "(older entries compacted or unrecoverable)"
                )
            for idx in range(lo, end + 1):
                fn(idx, self.store.get(idx)[0])
            start = end + 1
        else:
            # without replay the callback sees only entries committed
            # after registration — even ones currently paused behind an
            # archive gap must not be delivered to it later
            start = self.commit_watermark + 1
            lo = start
        if not self._apply_fns:
            self.applied_index = max(self.applied_index, self.commit_watermark)
        self._apply_fns.append((fn, start))
        if self._tiered_store is not None:
            # with apply consumers registered, the tiered store may only
            # seal history the apply stream has consumed ("committed,
            # below the apply cursor") — the hot path never pays a
            # segment read for the next apply index
            self._tiered_store.apply_cursor = self.applied_index
        return lo

    def _drain_apply(self) -> None:
        """Feed newly committed entries to the apply callbacks, in order.
        Bytes come from the archive (populated by ``_archive_committed``);
        a gap (the EC archive's documented give-up path) pauses the
        cursor. Each drain retries the gap by re-running the archive
        fallback (device read / reconstruction — donors that were short
        may have recovered since); a gap below the leader's ring horizon
        is unrecoverable and is reported loudly once."""
        if not self._apply_fns:
            return
        while self.applied_index < self.commit_watermark:
            nxt = self.applied_index + 1
            ent = self.store.get(nxt)
            if ent is None:
                if not self._backfill_archive(nxt):
                    break
                ent = self.store.get(nxt)  # backfill True => present
            # Advance first, then deliver to every eligible callback even
            # if one raises (collect + re-raise): a raising callback must
            # not make OTHER registrants miss this index, and must not
            # cause re-delivery to them on the next drain.
            self.applied_index += 1
            if self.spans is not None:
                self.spans.note_apply(self.applied_index, self.clock.now)
            err: Optional[BaseException] = None
            for fn, fn_start in self._apply_fns:
                if self.applied_index >= fn_start:
                    try:
                        fn(self.applied_index, ent[0])
                    except Exception as ex:
                        err = err if err is not None else ex
            if err is not None:
                raise err
        if self._tiered_store is not None and self._apply_fns:
            self._tiered_store.apply_cursor = self.applied_index

    def _backfill_archive(self, idx: int, quiet: bool = False) -> bool:
        """Try to fill an archive gap at committed index ``idx`` from the
        current leader's log (or shard reconstruction under EC). False if
        still unavailable this tick; permanently-lost gaps (below the ring
        horizon) get one loud nodelog — unless ``quiet`` (the replay
        probe, where hitting the compaction floor is expected)."""
        r = self.leader_id
        if r is None:
            return False
        # A replica's ring can serve ``idx`` only between its floor (below
        # it the slot was never written — snapshot installs seed only from
        # the snapshot base) and its horizon (below it the slot was
        # overwritten). Under EC recovery needs k such shard holders that
        # also committed the entry; plain replication reads the leader.
        lasts = self._fetch(self.state.last_index)

        def serves(q: int) -> bool:
            return idx >= max(
                int(lasts[q]) - self.state.capacity + 1,
                int(self._ring_floor[q]),
            )

        if self.cfg.ec_enabled:
            commits = self._fetch(self.state.commit_index)
            holders = sum(
                1 for q in range(self.cfg.rows)
                if self.alive[q] and int(commits[q]) >= idx and serves(q)
                and self.connectivity[r, q]
            )
            recoverable = holders >= self.cfg.rs_k
        else:
            recoverable = serves(r)
        if not recoverable:
            if not quiet and idx not in self._lost_gaps:
                self._lost_gaps.add(idx)
                self.nodelog(
                    r, f"apply stream gap at {idx} is outside every "
                    "serving ring range and was never archived: "
                    "unrecoverable; apply is wedged at this index"
                )
            return False
        hi = idx
        while hi + 1 <= self.commit_watermark and self.store.get(hi + 1) is None:
            hi += 1
        self._archive_committed(r, idx, hi)
        return self.store.get(idx) is not None

    def committed_entries(self, lo: int, hi: int) -> np.ndarray:
        """Read committed entries [lo, hi] (1-based, inclusive) as
        u8[hi-lo+1, entry_bytes] — the client read API the reference never
        offers (its values are stored and never read back, SURVEY.md §2
        "there is no state machine").

        Plain replication reads straight from a live replica's log; under
        EC the window is decoded from any k live shard rows
        (reconstruction-on-read, BASELINE config 3). Indices must be
        committed and still within the ring horizon; older history lives in
        the checkpoint store (``save_checkpoint``)."""
        if not (1 <= lo <= hi <= self.commit_watermark):
            raise ValueError(
                f"range [{lo}, {hi}] not committed "
                f"(watermark {self.commit_watermark})"
            )
        from raft_tpu.core.state import log_entries

        # A holder can only serve indices its ring still retains: slot
        # (i-1) % capacity is overwritten once last_index passes
        # i + capacity - 1, so reading below last_index - capacity + 1
        # would silently return a NEWER entry's bytes for an old index.
        commits = self._fetch(self.state.commit_index)
        lasts = self._fetch(self.state.last_index)
        holders = [
            r for r in range(self.cfg.rows)
            if self.alive[r]
            and int(commits[r]) >= hi
            and int(lasts[r]) - self.state.capacity + 1 <= lo
            # A snapshot-installed ring is only seeded from the snapshot
            # base: slots below self._ring_floor[r] hold init zeros /
            # pre-install leftovers, NOT old entries (after
            # RaftEngine.restore every replica's floor is the checkpoint's
            # base_index).
            and int(self._ring_floor[r]) <= lo
        ]
        if not holders:
            raise ValueError(
                f"no live replica both committed {hi} and still retains "
                f"index {lo} in its ring; read the checkpoint store for "
                "compacted history"
            )
        if not self.cfg.ec_enabled:
            return log_entries(self.state, holders[0], lo, hi,
                               fetch=self._fetch)
        from raft_tpu.ec.reconstruct import reconstruct

        if len(holders) < self.cfg.rs_k:
            raise ValueError(
                f"need {self.cfg.rs_k} live shard holders to decode, "
                f"have {len(holders)}"
            )
        return reconstruct(
            self.state, self._code, holders[: self.cfg.rs_k], lo, hi
        )

    # -------------------------------------------------------- persistence
    def save_checkpoint(self, path: str) -> None:
        """Write the cluster's durable state to one file: per-replica term
        and votedFor plus the archived committed tail — the persistence
        the reference comments (永続データ, main.go:18-21) but never does.
        ``RaftEngine.restore`` rebuilds a working cluster from it after a
        whole-process restart."""
        from raft_tpu.ckpt import EngineCheckpoint, Snapshot

        hi = self.commit_watermark
        # checkpoint_floor, not first: the tiered store's coverage
        # reaches arbitrarily deep into sealed segments, but checkpoints
        # must stay O(ring capacity) — and byte-identical to an untiered
        # engine's (the chaos determinism pin). Deep history restores
        # from the segment tier, not from a checkpoint that would grow
        # with it. For the plain store the two floors coincide. The
        # floor also BOUNDS the coverage walk (covered_lo pages segments
        # through the decode cache — an unbounded walk would read the
        # whole cold tier per checkpoint just to clamp it away).
        floor = max(1, self.store.checkpoint_floor)
        lo = self.store.covered_lo(hi, floor)
        # An interior archive hole (the EC archive path gives up when
        # donors are short; later ranges archive fine) would make the
        # contiguous coverage start ABOVE the hole — snapshotting just
        # [lo, hi] would silently drop acked-durable entries below it.
        # Probe downward first (holes are often transient: donors may have
        # recovered), then refuse loudly if committed entries above the
        # compaction floor are still missing.
        while lo > floor and self._backfill_archive(lo - 1, quiet=True):
            lo = self.store.covered_lo(hi, floor)
        if hi == 0:  # nothing committed yet: empty snapshot
            snap = Snapshot(
                1, 0,
                np.zeros((0, self.cfg.entry_bytes), np.uint8),
                np.zeros(0, np.int32),
            )
        elif lo > hi:
            # The watermark itself is missing from the archive. Writing an
            # empty checkpoint here would silently drop committed,
            # client-acknowledged entries across a restart — refuse loudly
            # instead; the caller can retry after the archive catches up.
            raise RuntimeError(
                f"committed entry {hi} is not archived; refusing to write "
                "a checkpoint that would lose committed entries"
            )
        elif lo > floor:
            holes = [
                i for i in range(floor, lo) if self.store.get(i) is None
            ]
            shown = ", ".join(map(str, holes[:8])) + (
                f", ... ({len(holes)} total)" if len(holes) > 8 else ""
            )
            raise RuntimeError(
                f"committed entries {{{shown}}} are not archived and could "
                "not be recovered; refusing to write a checkpoint that "
                "would lose committed entries"
            )
        else:
            # lo == compaction floor: everything below was evicted by the
            # archive's retention sweep — recorded explicitly as compacted
            # history via the snapshot's base_index, not silent loss.
            snap = self.store.snapshot(lo, hi)
        EngineCheckpoint(
            snap=snap,
            terms=self._fetch(self.state.term).astype(np.int32),
            voted_for=self._fetch(self.state.voted_for).astype(np.int32),
            member=self.member.copy(),
            learner=self.learner.copy(),
        ).save(path)
        if self._votelog is not None:
            # WAL rotation: the checkpoint just captured (term, votedFor),
            # so the accumulated transition records are redundant.
            self._votelog.truncate()

    @classmethod
    def restore(
        cls,
        cfg: RaftConfig,
        path: str,
        transport: Optional[Transport] = None,
        trace: Optional[Callable[[str], None]] = None,
        vote_log: Optional[str] = None,
        recorder=None,
    ) -> "RaftEngine":
        """Rebuild an engine from ``save_checkpoint`` output: every replica
        restarts as a follower holding the archived committed tail (RS
        shards re-encoded when the cluster is erasure-coded) with its
        persisted term and votedFor, then the normal election path takes
        over. Uncommitted entries are lost, as they are for the reference's
        restarting process (nothing was ever durable there, main.go:18-21)."""
        from raft_tpu.ckpt import EngineCheckpoint, install_snapshot_all

        ck = EngineCheckpoint.load(path)
        if ck.terms.shape != (cfg.rows,):
            raise ValueError(
                f"checkpoint has {ck.terms.shape[0]} replica rows, "
                f"config has {cfg.rows}"
            )
        if ck.snap.entries.size and ck.snap.entries.shape[1] != cfg.entry_bytes:
            raise ValueError(
                f"checkpoint entry size {ck.snap.entries.shape[1]} != "
                f"config entry_bytes {cfg.entry_bytes}"
            )
        eng = cls(cfg, transport, trace=trace, recorder=recorder)
        snap = ck.snap
        if snap.last_index >= snap.base_index:
            # History below the snapshot base was compacted before the
            # checkpoint was written; record that so a later
            # save_checkpoint treats the absence as compaction, not as a
            # hole to backfill from ring slots that never held it.
            eng.store.set_floor(snap.base_index)
            for i in range(snap.base_index, snap.last_index + 1):
                eng.store.put(
                    i,
                    snap.entries[i - snap.base_index].tobytes(),
                    int(snap.terms[i - snap.base_index]),
                )
            # Verified-for term 0: the next real leader's repair window
            # re-verifies matches in its own term.
            eng.state = install_snapshot_all(
                eng.state, snap, 0, cfg.batch_size, eng._code
            )
            eng.commit_watermark = snap.last_index
            # Rings are seeded only from the snapshot tail that fits one
            # capacity; reads below that start must go to the checkpoint
            # store, not the (zero-filled) ring slots.
            eng._ring_floor[:] = max(
                snap.base_index, snap.last_index - eng.state.capacity + 1
            )
        # persisted term + votedFor (the Raft durability obligation: a
        # restarted replica must not vote twice in a term it voted in).
        # A vote log holds transitions NEWER than the checkpoint (crash
        # between a vote and the next save_checkpoint): overlay them.
        from raft_tpu.ckpt import merge_restored

        terms = ck.terms.astype(np.int64).copy()
        vf = ck.voted_for.astype(np.int64).copy()
        terms, vf = merge_restored(cfg.rows, terms, vf, vote_log)
        eng.state = eng.state.replace(
            term=jnp.asarray(terms, eng.state.term.dtype),
            voted_for=jnp.asarray(vf, eng.state.voted_for.dtype),
        )
        eng.terms = terms
        if vote_log is not None:
            eng._attach_votelog(vote_log)
        if ck.member is not None and ck.member.shape == (cfg.rows,):
            # the committed configuration outranks cfg.n_replicas: a
            # server removed before the checkpoint must NOT resurrect as
            # a voting member on restore
            eng.member = ck.member.copy()
            for r in range(cfg.rows):
                # rows that joined after the initial config need timers
                if eng.member[r] and r >= cfg.n_replicas:
                    eng._arm_follower(r)
        if ck.learner is not None and ck.learner.shape == (cfg.rows,):
            # learners resume as learners (non-voting, no timers): their
            # catch-up restarts from the restored snapshot like any row
            eng.learner = ck.learner.copy() & ~eng.member
        for r in range(cfg.rows):
            if eng.member[r]:
                eng.nodelog(r, f"restored from checkpoint to {eng.commit_watermark}")
        return eng

    def commit_latencies(self) -> np.ndarray:
        """Per-entry commit latency (seconds) for every durable entry."""
        return np.array(
            [self.commit_time[s] - self.submit_time[s] for s in self.commit_time]
        )
