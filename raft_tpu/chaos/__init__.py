"""Jepsen-style torture harness: client-history linearizability under a
randomized nemesis.

The package closes the verification gap the Raft-internal suites leave
open: ``tests/test_properties.py`` / ``tests/test_chaos.py`` prove what
the *replicas* agree on; this harness records what the *clients* were
told — every submit and linearizable read as an invoke/ok/fail/info
interval on the virtual clock — and checks the history against the
sequential KV model (Raft §8's client contract, the property users
actually observe).

- ``chaos.history``   — the event model (History / OpRecord).
- ``chaos.checker``   — Wing–Gong/Lowe linearizability search with
  P-compositional per-key decomposition and a step budget
  (``UNDETERMINED`` instead of a hang).
- ``chaos.nemesis``   — the seeded adversary: FaultPlan process faults,
  transport drop/dup/delay windows, crash cycles with storage faults.
- ``chaos.transport`` — ``ChaosTransport``, message faults at the
  Transport seam.
- ``chaos.storage``   — ``MirroredStore``, the simulated durable disk
  set (mirrored checkpoints + vote WAL) the storage faults target.
- ``chaos.runner``    — ``torture_run`` / ``torture_run_multi``: the
  end-to-end loop, reported with a one-line seed repro; plus the
  deterministic ``overload_run`` (anti-metastability),
  ``reconfig_run`` (reconfiguration availability) and ``wire_run``
  (torture traffic over a real loopback TCP server — the
  ``raft_tpu.net`` serving tier with leader-kill and overload
  composed, docs/NETWORK.md) drills.

Opt-in nemesis planes (existing seeds replay byte-identically with
them off): ``overload`` (open-loop arrival storms, round 8) and
``membership`` (grow / shrink / remove-the-leader / wipe-replace under
fire, round 9 — docs/CHAOS.md).

One-command repro of any run: ``python -m raft_tpu.chaos --seed N``.
"""

from raft_tpu.chaos.checker import (
    LINEARIZABLE,
    UNDETERMINED,
    VIOLATION,
    CheckResult,
    check_history,
)
from raft_tpu.chaos.history import History, OpRecord
from raft_tpu.chaos.nemesis import MembershipView, Nemesis, NemesisAction
from raft_tpu.chaos.runner import (
    OverloadReport,
    ReconfigReport,
    TortureReport,
    WireReport,
    overload_run,
    poisson,
    reconfig_run,
    torture_run,
    torture_run_multi,
    wire_run,
)
from raft_tpu.chaos.storage import MirroredStore
from raft_tpu.chaos.transport import ChaosTransport

__all__ = [
    "LINEARIZABLE",
    "UNDETERMINED",
    "VIOLATION",
    "CheckResult",
    "check_history",
    "History",
    "OpRecord",
    "MembershipView",
    "Nemesis",
    "NemesisAction",
    "OverloadReport",
    "ReconfigReport",
    "TortureReport",
    "WireReport",
    "overload_run",
    "poisson",
    "reconfig_run",
    "torture_run",
    "torture_run_multi",
    "wire_run",
    "MirroredStore",
    "ChaosTransport",
]
