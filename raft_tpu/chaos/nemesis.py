"""The nemesis: a seed-driven adversary schedule generator.

One ``random.Random(seed)`` stream decides everything the adversary
does, so a torture run is replayed exactly by its seed + config — the
one-line repro the runner prints on failure. The vocabulary composes
three fault planes:

- **process faults** — the existing ``faults.FaultPlan`` vocabulary
  (kill/recover, slow windows, disruptive candidacies, link
  partitions), emitted as real ``FaultPlan`` fragments and merged into
  the engine's event heap through ``schedule_faults`` after the plan's
  own strict majority validation (``FaultPlan.validate``);
- **message faults** — windows of transport-level drop/dup/delay
  toggled on a ``chaos.ChaosTransport``;
- **crash cycles** — whole-process crash + checkpoint-restore +
  restart, optionally composed with a storage fault against the
  durability stack (``chaos.MirroredStore``: torn vote-WAL append,
  checkpoint bit-flip, stale-file rollback);
- **overload windows** (opt-in, ``allow_overload=True`` — off by
  default so existing seeds' rng streams replay unchanged) — open-loop
  Poisson arrival storms at 2-10x the cluster's measured ingest
  capacity, composable with every other plane. The runner converts the
  rate into open-loop client traffic; admission-shed arrivals are
  recorded as sound no-effect failures, so the linearizability verdict
  must stay ACCEPT through the storm (docs/OVERLOAD.md).
- **clock-skew plane** (opt-in, ``allow_clock=True`` — off by default
  for the same replay reason) — per-replica LEASE-clock rate skew
  inside the configured drift band ``[1/clock_drift_bound,
  clock_drift_bound]``: the exact envelope the leader-lease safety
  math claims to absorb (raft.lease). A correct lease plane stays
  linearizable across every draw in the band; the
  ``broken="lease_skew"`` variant (drift bound ignored) is what a
  stale read looks like when the claim is false (docs/READS.md).
- **membership plane** (opt-in, ``allow_membership=True`` — off by
  default for the same replay reason) — seeded reconfiguration under
  fire: grow (learner-then-promote ``add_server``), shrink, removal of
  the current LEADER, and wipe-replace cycles (kill + total durable
  loss + ``replace`` rejoin-from-nothing through snapshot install as a
  learner), composed with every other plane. Every choice is gated so
  a strict majority of the *current* voter set stays alive through the
  op — the quorum-liveness rule applied to the post-change
  configuration (docs/CHAOS.md round 9).

Liveness discipline: every choice is gated so the run can quiesce —
kills never leave fewer than a majority of members alive (the same rule
``FaultPlan.validate`` enforces, applied adaptively here), partitions
always leave a majority side, and storage faults never touch the last
healthy mirror. The nemesis makes the runs *mean* something: a torture
sweep that wedges proves nothing about linearizability.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional

from raft_tpu.faults.plan import FaultPlan

STORAGE_FAULTS = ("none", "tear_votelog", "flip_bit", "rollback")


@dataclasses.dataclass
class MembershipView:
    """The runner's live configuration snapshot for membership
    decisions: voter rows, learner rows, unconfigured spare rows, the
    routed leader (None between leaderships), and whether any
    configuration change is in flight (pending, queued or staged)."""

    voters: List[int]
    learners: List[int]
    spares: List[int]
    leader: Optional[int]
    in_flight: bool


@dataclasses.dataclass
class NemesisAction:
    """One adversary decision for the runner to execute."""

    kind: str                      # see Nemesis.KINDS
    replica: int = 0
    plan: Optional[FaultPlan] = None        # kind == "plan"
    groups: Optional[list] = None           # kind == "partition"
    drop: float = 0.0                       # kind == "msg_on"
    dup: float = 0.0
    delay: float = 0.0
    storage: str = "none"                   # kind == "crash_restart"
    rate_mult: float = 0.0                  # kind == "overload_on"
    spare: int = 0                          # kind == "mem_replace"
    rate: float = 1.0                       # kind == "skew_on" (lease
    #                                         clock rate, local s/true s)

    def describe(self) -> str:
        if self.kind == "skew_on":
            return f"skew_on({self.replica}, rate={self.rate:.3f})"
        if self.kind == "skew_off":
            return f"skew_off({self.replica})"
        if self.kind == "msg_on":
            return (f"msg_on(drop={self.drop:.2f}, dup={self.dup:.2f}, "
                    f"delay={self.delay:.2f})")
        if self.kind == "overload_on":
            return f"overload_on(rate={self.rate_mult:.1f}x capacity)"
        if self.kind == "crash_restart":
            return f"crash_restart(storage={self.storage})"
        if self.kind == "partition":
            return f"partition({self.groups})"
        if self.kind == "mem_replace":
            return f"mem_replace({self.replica} -> {self.spare})"
        if self.kind == "plan":
            return f"plan({[(e.t, e.action, e.replica) for e in self.plan.events]})"
        return f"{self.kind}({self.replica})"


class Nemesis:
    """Seeded adversary policy over a live cluster view.

    ``view`` duck-type (the runner adapts either engine): ``members()``
    -> list of member rows, ``alive(r)`` -> bool, ``partitioned`` flag
    maintained by the runner, ``now`` -> virtual clock.
    """

    KINDS = (
        "kill", "recover", "slow", "unslow", "campaign",
        "partition", "heal", "plan", "msg_on", "msg_off",
        "crash_restart", "overload_on", "overload_off",
        "mem_grow", "mem_shrink", "mem_remove_leader", "mem_replace",
        "skew_on", "skew_off",
        "none",
    )

    def __init__(
        self,
        seed: int,
        n_rows: int,
        allow_crash: bool = True,
        allow_msg: bool = True,
        allow_storage: bool = True,
        allow_overload: bool = False,
        allow_membership: bool = False,
        allow_clock: bool = False,
        clock_drift_bound: float = 2.0,
    ):
        self.rng = random.Random(f"nemesis:{seed}")
        self.n_rows = n_rows
        self.allow_crash = allow_crash
        self.allow_msg = allow_msg
        self.allow_storage = allow_storage
        self.allow_overload = allow_overload
        self.allow_membership = allow_membership
        self.allow_clock = allow_clock
        self.clock_drift_bound = clock_drift_bound
        #   skew_on draws lease-clock rates inside the drift band the
        #   lease plane's config CLAIMS to absorb — the adversary probes
        #   exactly the assumption, never outside it (outside it the
        #   deployment lied about its clocks, which is what the
        #   broken="lease_skew" variant models instead)
        #   off by default: adding kinds to the choice pool perturbs the
        #   decision stream, and existing pinned seeds must replay
        #   byte-identically
        self.msg_window = False
        self.overload_window = False
        self.cut: List[int] = []
        #   minority side of the active partition; kill gating consults
        #   it so kill x partition can never strand BOTH sides below
        #   quorum (see _kill_ok)
        self.log: List[str] = []

    # ------------------------------------------------------------- policy
    def _kill_ok(self, members: List[int], dead: int,
                 victim: int, partitioned: bool) -> bool:
        # mirror tests/test_chaos.py's rule: a strict majority of the
        # CURRENT membership stays alive after one more kill — and while
        # a partition is up, only minority-cut members may die: a kill
        # on the majority side would compose with the cut into no live
        # quorum on EITHER side (every write then stalls until a random
        # heal, collapsing the run's discriminating power)
        if partitioned and victim not in self.cut:
            return False
        return dead + 1 <= (len(members) - 1) // 2

    def _shrink_ok(self, victim: int, voters: List[int],
                   alive: Dict[int, bool]) -> bool:
        """A removal is admissible iff the POST-change voter set keeps a
        live strict majority — the quorum-liveness rule counted over the
        configuration the cluster is about to be in, not the initial
        ``n`` (the FaultPlan.validate membership-timeline rule, applied
        adaptively)."""
        new = [v for v in voters if v != victim]
        if len(new) < 2:
            return False
        live = sum(1 for v in new if alive.get(v, False))
        return live >= len(new) // 2 + 1

    def next_action(
        self, members: List[int], alive: Dict[int, bool],
        partitioned: bool, now: float,
        membership: Optional[MembershipView] = None,
    ) -> NemesisAction:
        rng = self.rng
        if not partitioned:
            self.cut = []   # heal or crash-restart dissolved the split
        kinds = ["kill", "recover", "slow", "unslow", "campaign",
                 "partition", "heal", "plan", "none"]
        if self.allow_msg:
            kinds += ["msg_on", "msg_off"]
        if self.allow_crash:
            kinds.append("crash_restart")
        if self.allow_overload:
            kinds += ["overload_on", "overload_off"]
        if self.allow_membership and membership is not None:
            kinds += ["mem_grow", "mem_shrink", "mem_remove_leader",
                      "mem_replace"]
        if self.allow_clock:
            kinds += ["skew_on", "skew_off"]
        kind = rng.choice(kinds)
        dead = sum(1 for r in members if not alive[r])
        victim = rng.randrange(self.n_rows)
        act = NemesisAction("none")
        if kind == "kill":
            if (victim in members and alive[victim]
                    and self._kill_ok(members, dead, victim, partitioned)):
                act = NemesisAction("kill", victim)
        elif kind == "recover":
            if not alive[victim]:
                act = NemesisAction("recover", victim)
        elif kind == "slow":
            if victim in members and alive[victim]:
                act = NemesisAction("slow", victim)
        elif kind == "unslow":
            act = NemesisAction("unslow", victim)
        elif kind == "campaign":
            act = NemesisAction("campaign", victim)
        elif kind == "partition" and not partitioned:
            # cut one LIVE member; the rest side must keep a live
            # majority of the membership or no side could ever commit
            live = [r for r in members if alive[r]]
            if len(live) - 1 > len(members) // 2:
                cut = [rng.choice(live)]      # minority side of one member
                rest = [r for r in range(self.n_rows) if r not in cut]
                self.cut = cut
                act = NemesisAction("partition", groups=[cut, rest])
        elif kind == "heal" and partitioned:
            act = NemesisAction("heal")
        elif kind == "plan":
            act = self._compose_plan(members, alive, dead, partitioned, now)
        elif kind == "msg_on" and self.allow_msg:
            self.msg_window = True
            act = NemesisAction(
                "msg_on",
                drop=rng.uniform(0.05, 0.35),
                dup=rng.uniform(0.0, 0.3),
                delay=rng.uniform(0.0, 0.25),
            )
        elif kind == "msg_off" and self.msg_window:
            self.msg_window = False
            act = NemesisAction("msg_off")
        elif kind == "crash_restart" and self.allow_crash:
            pool = STORAGE_FAULTS if self.allow_storage else ("none",)
            act = NemesisAction(
                "crash_restart", storage=rng.choice(pool)
            )
        elif kind == "overload_on" and not self.overload_window:
            # open-loop arrival storm: the ISSUE's 2-10x band over the
            # cluster's measured ingest capacity (the runner converts
            # the multiplier into a Poisson rate)
            self.overload_window = True
            act = NemesisAction(
                "overload_on", rate_mult=rng.uniform(2.0, 10.0)
            )
        elif kind == "overload_off" and self.overload_window:
            self.overload_window = False
            act = NemesisAction("overload_off")
        elif kind == "skew_on" and self.allow_clock:
            # lease-clock rate inside the configured drift band (log-
            # uniform so slow and fast clocks are equally likely; the
            # band EDGES are the interesting draws and stay reachable)
            import math

            lo = math.log(1.0 / self.clock_drift_bound)
            hi = math.log(self.clock_drift_bound)
            act = NemesisAction(
                "skew_on", victim,
                rate=math.exp(rng.uniform(lo, hi)),
            )
        elif kind == "skew_off" and self.allow_clock:
            act = NemesisAction("skew_off", victim)
        elif kind.startswith("mem_") and membership is not None:
            act = self._membership_action(
                kind, membership, alive, partitioned
            )
        self.log.append(f"t={now:.1f} {act.describe()}")
        return act

    def _membership_action(
        self, kind: str, mv: MembershipView, alive: Dict[int, bool],
        partitioned: bool,
    ) -> NemesisAction:
        """Gate and parameterize one reconfiguration op. Ops only start
        with no change in flight and no active partition (a change may
        still be MID-FLIGHT when a later partition/kill/crash lands —
        that interleaving is the point of the plane); every choice keeps
        a live strict majority of the post-change voter set."""
        rng = self.rng
        none = NemesisAction("none")
        if mv.in_flight or partitioned or mv.leader is None:
            return none
        if kind == "mem_grow":
            if not mv.spares:
                return none
            return NemesisAction("mem_grow", rng.choice(mv.spares))
        if kind == "mem_shrink":
            # learners are removable for free; voters only under the
            # post-change quorum-liveness gate (never the leader here —
            # that is mem_remove_leader's job, kept distinct so coverage
            # of the removed-leader path is seed-addressable)
            cands = list(mv.learners) + [
                v for v in mv.voters
                if v != mv.leader and self._shrink_ok(v, mv.voters, alive)
            ]
            if not cands or len(mv.voters) <= 2:
                return none
            return NemesisAction("mem_shrink", rng.choice(cands))
        if kind == "mem_remove_leader":
            if len(mv.voters) <= 2 or mv.leader not in mv.voters:
                return none
            if not self._shrink_ok(mv.leader, mv.voters, alive):
                return none
            return NemesisAction("mem_remove_leader", mv.leader)
        if kind == "mem_replace":
            # wipe-replace: kill (if needed) + total durable loss +
            # rejoin-from-nothing as a learner. Prefer an already-dead
            # voter; else a live non-leader voter the kill gate admits.
            dead_voters = [v for v in mv.voters if not alive.get(v, False)]
            if dead_voters:
                victim = rng.choice(dead_voters)
            else:
                dead = sum(1 for v in mv.voters if not alive.get(v, False))
                live = [
                    v for v in mv.voters
                    if alive.get(v, False) and v != mv.leader
                    and self._kill_ok(mv.voters, dead, v, partitioned)
                ]
                if not live:
                    return none
                victim = rng.choice(live)
            a2 = dict(alive)
            a2[victim] = False
            if not self._shrink_ok(victim, mv.voters, a2):
                return none
            spare = rng.choice(mv.spares) if mv.spares else victim
            return NemesisAction("mem_replace", victim, spare=spare)
        return none

    def _compose_plan(
        self, members: List[int], alive: Dict[int, bool], dead: int,
        partitioned: bool, now: float,
    ) -> NemesisAction:
        """A scheduled FaultPlan fragment over the next phase window —
        the classic vocabulary riding the engine's own heap, validated
        by the plan's strict majority check before it is handed over."""
        rng = self.rng
        flavor = rng.choice(["slow_window", "crash_recover", "storm"])
        live = [r for r in members if alive[r]]
        r = rng.choice(live) if live else 0
        if flavor == "slow_window" and live:
            plan = FaultPlan.slow_window(r, now + 1.0, now + rng.uniform(10, 30))
        elif flavor == "crash_recover" and live and self._kill_ok(
            members, dead, r, partitioned
        ):
            plan = FaultPlan.crash_recover(
                r, now + 1.0, now + rng.uniform(15, 40)
            )
        else:
            plan = FaultPlan.election_storm(
                len(members), now + 1.0, now + rng.uniform(10, 25),
                mean_interval=5.0, seed=rng.randrange(1 << 30),
            )
        # belt and braces: the fragment itself must pass the strict
        # majority validation (it schedules recover after kill, so the
        # adaptive gate above is the binding one). The validation counts
        # the CURRENT voter set, not the initial n — under the
        # membership plane the two diverge (FaultPlan.validate's
        # membership timeline).
        alive0 = [alive.get(r, True) for r in range(self.n_rows)]
        plan.validate(
            self.n_rows, alive=alive0, strict=True,
            membership=[(0.0, list(members))],
        )
        return NemesisAction("plan", plan=plan)
