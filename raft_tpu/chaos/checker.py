"""Linearizability checking over recorded client histories.

The algorithm is Wing & Gong's exhaustive search with Lowe's
memoization (the same family Knossos/Porcupine implement): depth-first
over the choices of "which outstanding operation linearizes next",
pruning configurations — a (set of linearized ops, model state) pair —
that have already failed. An operation may be chosen next only if no
OTHER un-linearized operation *completed strictly before* it was
invoked (the real-time order linearizability must respect); reads must
match the model state, writes/deletes advance it.

Status handling (see chaos.history):

- ``fail`` ops provably took no effect and are removed up front;
- ``info`` READS constrain nothing (no result was observed) and are
  removed;
- ``info`` WRITES/DELETES keep an unbounded interval ``[invoke, inf)``:
  the search may linearize them at any admissible point or never —
  success requires only that every *completed* op is linearized.

Tractability comes from P-compositionality (Herlihy–Wing locality): a
history over independent keys is linearizable iff each key's
subhistory is against a single-register model, so ``check_history``
checks each key independently — exponential worst cases shrink from
"all ops" to "ops per key". The whole-history mode (``per_key=False``,
one dict-shaped model) exists to *validate* that optimization
(tests pin per-key == whole-history verdicts on small cases), not for
production use.

The search is budgeted: every explored configuration costs one step,
and an exhausted budget returns ``UNDETERMINED`` instead of hanging —
a torture harness must never turn a hard history into a wedged CI run.
``UNDETERMINED`` means exactly "neither a witness nor a refutation was
found within the budget".
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

from raft_tpu.chaos.history import (
    DELETE,
    FAIL,
    INFO,
    OK,
    READ,
    History,
    OpRecord,
)

LINEARIZABLE = "LINEARIZABLE"
VIOLATION = "VIOLATION"
UNDETERMINED = "UNDETERMINED"
SERIALIZABLE = "SERIALIZABLE"        # check_serializable's passing verdict

_INF = float("inf")


@dataclasses.dataclass
class CheckResult:
    verdict: str                     # LINEARIZABLE | VIOLATION | UNDETERMINED
    steps: int                       # search configurations explored
    key: Optional[bytes] = None      # offending / exhausted key, if any
    detail: str = ""

    def __bool__(self) -> bool:
        return self.verdict == LINEARIZABLE


def _prepare(ops: Iterable[OpRecord]) -> Optional[List[OpRecord]]:
    """Drop constraint-free ops; None = history uses a PENDING op (the
    caller forgot ``History.close()``) — refuse rather than guess."""
    out = []
    for rec in ops:
        if rec.status == FAIL:
            continue                 # provably never took effect
        if rec.status == INFO and rec.op == READ:
            continue                 # result never observed: no constraint
        if rec.status not in (OK, INFO):
            return None
        out.append(rec)
    return out


def _search_key(
    ops: List[OpRecord],
    budget: int,
    state0,
    apply_op,
) -> Tuple[str, int]:
    """Budgeted WG/Lowe search over one object's subhistory.

    ``state0``/``apply_op`` parameterize the sequential model:
    ``apply_op(state, rec) -> (ok, new_state)`` — hashable states only
    (memoization keys on them). Returns (verdict, steps used)."""
    n = len(ops)
    if n == 0:
        return LINEARIZABLE, 0
    inv = [op.invoke_t for op in ops]
    ret = [op.complete_t if op.complete_t is not None else _INF
           for op in ops]
    must = 0                          # ops that MUST linearize (completed)
    for i, op in enumerate(ops):
        if op.status == OK:
            must |= 1 << i
    full = (1 << n) - 1

    seen = set()                      # failed (remaining_mask, state) configs
    steps = 0

    def candidates(remaining: int) -> List[int]:
        """Ops admissible as the next linearization point: no OTHER
        remaining op completed strictly before this one was invoked."""
        rem = [i for i in range(n) if remaining >> i & 1]
        out = []
        for i in rem:
            if all(ret[j] >= inv[i] for j in rem if j != i):
                out.append(i)
        return out

    # Explicit stack of (remaining_mask, state, candidate list, cursor):
    # recursion depth equals history length, and an explicit stack makes
    # the budget check one place instead of every call site.
    stack = [[full, state0, None, 0]]
    while stack:
        frame = stack[-1]
        remaining, state, cands, cur = frame
        if remaining & must == 0:
            return LINEARIZABLE, steps
        if cands is None:
            cands = candidates(remaining)
            frame[2] = cands
        advanced = False
        while frame[3] < len(cands):
            i = cands[frame[3]]
            frame[3] += 1
            okd, nstate = apply_op(state, ops[i])
            if not okd:
                continue
            nxt = remaining & ~(1 << i)
            if (nxt, nstate) in seen:
                continue
            steps += 1
            if steps > budget:
                return UNDETERMINED, steps
            stack.append([nxt, nstate, None, 0])
            advanced = True
            break
        if not advanced:
            seen.add((remaining, state))
            stack.pop()
    return VIOLATION, steps


def _prune_unobserved(kops: List[OpRecord]) -> List[OpRecord]:
    """Drop ``info`` writes/deletes of ONE key whose effect value no
    completed read of that key ever returned. Sound and complete for a
    register: an optional (info) op need never be linearized, and any
    valid schedule that DOES include such a write maps to a valid
    schedule without it — removing a last-writer-wins write can only
    invalidate reads that returned its value, and there are none. This
    is the pruning that keeps violation proofs tractable: without it
    every crash-lost write (unbounded interval, never observed)
    multiplies the configuration space for nothing."""
    seen = {rec.value for rec in kops if rec.op == READ and rec.status == OK}
    out = []
    for rec in kops:
        if rec.status == INFO:
            effect = None if rec.op == DELETE else rec.value
            if effect not in seen:
                continue
        out.append(rec)
    return out


def _register_apply(state, rec: OpRecord):
    """Single-key register model: state = current value (None = absent)."""
    if rec.op == READ:
        return state == rec.value, state
    if rec.op == DELETE:
        return True, None
    return True, rec.value            # WRITE


def _kv_apply(state, rec: OpRecord):
    """Whole-map model (validation mode): state = frozenset of items."""
    d = dict(state)
    if rec.op == READ:
        return d.get(rec.key) == rec.value, state
    if rec.op == DELETE:
        d.pop(rec.key, None)
    else:
        d[rec.key] = rec.value
    return True, frozenset(d.items())


def check_history(
    history,
    step_budget: int = 500_000,
    per_key: bool = True,
) -> CheckResult:
    """Check a recorded history against the KV register model.

    ``history`` is a ``chaos.History`` or a plain list of ``OpRecord``.
    ``per_key=True`` (default) exploits P-compositionality: each key's
    subhistory checks independently against a register, and the budget
    is shared across keys. Any key's violation fails the whole history;
    otherwise any budget exhaustion is ``UNDETERMINED``.
    """
    ops = history.ops if isinstance(history, History) else list(history)
    prepared = _prepare(ops)
    if prepared is None:
        raise ValueError(
            "history contains PENDING ops; call History.close() first"
        )
    total = 0
    sub: Dict[bytes, List[OpRecord]] = {}
    for rec in prepared:
        sub.setdefault(rec.key, []).append(rec)
    sub = {k: _prune_unobserved(kops) for k, kops in sub.items()}
    if not per_key:
        flat = [rec for kops in sub.values() for rec in kops]
        verdict, steps = _search_key(
            flat, step_budget, frozenset(), _kv_apply
        )
        return CheckResult(verdict, steps, detail="whole-history mode")
    exhausted: Optional[bytes] = None
    for key, kops in sorted(sub.items()):
        verdict, steps = _search_key(
            kops, step_budget - total, None, _register_apply
        )
        total += steps
        if verdict == VIOLATION:
            return CheckResult(
                VIOLATION, total, key=key,
                detail=f"key {key!r}: no linearization of "
                       f"{len(kops)} ops exists",
            )
        if verdict == UNDETERMINED and exhausted is None:
            exhausted = key
            if total >= step_budget:
                break
    if exhausted is not None:
        return CheckResult(
            UNDETERMINED, total, key=exhausted,
            detail=f"step budget ({step_budget}) exhausted",
        )
    return CheckResult(LINEARIZABLE, total)


# ------------------------------------------------- per-read-class grading
#: read classes whose contract IS linearizability: their reads enter the
#: Wing–Gong search together with every write/delete. ``session`` reads
#: deliberately do not — their contract is the weaker session model
#: below, and grading them as linearizable would either fail correct
#: runs (session reads may be stale) or, worse, grade them against
#: nothing at all.
LINEARIZABLE_READ_CLASSES = ("read_index", "lease", "follower")
SESSION_CLASS = "session"


def read_class_of(rec: OpRecord) -> Optional[str]:
    """The class a read was SERVED under (recorded by the harness on
    the OpRecord); non-reads return None, unlabeled reads default to
    ``read_index`` — the legacy single-class world."""
    if rec.op != READ:
        return None
    return getattr(rec, "read_class", None) or "read_index"


def check_read_classes(
    history,
    step_budget: int = 500_000,
) -> Dict[str, CheckResult]:
    """Grade each read class present in ``history`` against ITS OWN
    consistency model (docs/READS.md matrix) — weaker classes get their
    own verdicts, not a free pass, and stronger classes are not blamed
    for a weaker class's staleness:

    - ``read_index`` / ``lease`` / ``follower``: linearizability of the
      write history plus that class's reads (one Wing–Gong search per
      class, budget shared);
    - ``session``: per-(client, key) MONOTONE READS over the recorded
      serve indices, READ-YOUR-WRITES against the recorded session
      floor (``ryw_floor`` — the client's token at invoke time), and
      read-committed value justification (a returned value must have
      been written to that key by an op invoked before the read
      completed, or be the initial absence).

    Returns class -> :class:`CheckResult`; classes absent from the
    history are absent from the result."""
    ops = history.ops if isinstance(history, History) else list(history)
    present = {c for rec in ops
               for c in (read_class_of(rec),) if c is not None}
    results: Dict[str, CheckResult] = {}
    base = [rec for rec in ops if rec.op != READ]
    spent = 0
    for cls in [c for c in LINEARIZABLE_READ_CLASSES if c in present]:
        sub = base + [rec for rec in ops if read_class_of(rec) == cls]
        res = check_history(sub, step_budget=max(step_budget - spent, 1))
        spent += res.steps
        results[cls] = res
    if SESSION_CLASS in present:
        results[SESSION_CLASS] = _check_session(
            [rec for rec in ops
             if rec.op != READ or read_class_of(rec) == SESSION_CLASS]
        )
    return results


def _check_session(ops: List[OpRecord]) -> CheckResult:
    """The session model: completed session reads carry the harness's
    recorded ``serve_index`` (the applied index the value was read at)
    and ``ryw_floor`` (the client's session token when the read was
    invoked). Violations: a value never written to the key before the
    read completed (read-uncommitted), a serve below the client's own
    floor (read-your-writes broken), or a serve below an index the same
    client already observed for that key (monotone-reads inversion)."""
    written: Dict[bytes, List[Tuple[float, Optional[bytes]]]] = {}
    for rec in ops:
        if rec.op != READ and rec.status != FAIL:
            written.setdefault(rec.key, []).append(
                (rec.invoke_t, None if rec.op == DELETE else rec.value)
            )
    hwm: Dict[Tuple[int, bytes], int] = {}
    steps = 0
    for rec in ops:
        if rec.op != READ or rec.status != OK:
            continue
        steps += 1
        if rec.value is not None:
            # time-bounded justification: only a write INVOKED before
            # this read completed can explain the value — a value some
            # client writes later must not retroactively launder an
            # earlier dirty serve
            t_end = (rec.complete_t if rec.complete_t is not None
                     else _INF)
            if not any(v == rec.value and t_inv <= t_end
                       for t_inv, v in written.get(rec.key, ())):
                return CheckResult(
                    VIOLATION, steps, key=rec.key,
                    detail=f"session read of {rec.value!r} on key "
                           f"{rec.key!r}: value was never written "
                           "before the read completed",
                )
        idx = getattr(rec, "serve_index", None)
        if idx is None:
            continue            # value-only record: nothing more to grade
        floor = getattr(rec, "ryw_floor", 0)
        if idx < floor:
            return CheckResult(
                VIOLATION, steps, key=rec.key,
                detail=f"client {rec.client} session read served at "
                       f"index {idx} below its own token floor {floor} "
                       "(read-your-writes broken)",
            )
        mkey = (rec.client, rec.key)
        if idx < hwm.get(mkey, 0):
            return CheckResult(
                VIOLATION, steps, key=rec.key,
                detail=f"client {rec.client} session read served at "
                       f"index {idx} after already observing "
                       f"{hwm[mkey]} (monotone-reads inversion)",
            )
        hwm[mkey] = max(hwm.get(mkey, 0), idx)
    return CheckResult(LINEARIZABLE, steps,
                       detail="session model (monotone + RYW + "
                              "read-committed)")


# ------------------------------------------------- transactional checking
@dataclasses.dataclass
class TxnRecord:
    """One transaction as the serializability checker sees it.

    ``expects`` are the validation reads the coordinator certified
    UNDER THE LOCKS (key -> committed value observed, None = absent);
    ``writes`` are the staged intents (key -> new value, None =
    delete). ``status`` follows chaos.history: ``ok`` = committed with
    a known decision position, ``fail`` = aborted (provably no
    effect), ``info`` = outcome unknown (the drill resolves these from
    the replicated decision map at quiesce, so a clean run has none).
    ``pos`` is the decision record's apply position in the decision
    group — the commit-order witness."""

    txn_id: int
    writes: Dict[bytes, Optional[bytes]]
    expects: Dict[bytes, Optional[bytes]]
    status: str = OK
    pos: Optional[int] = None
    invoke_t: float = 0.0
    complete_t: Optional[float] = None


def check_serializable(
    txns: List[TxnRecord],
    final_state: Optional[Dict[bytes, bytes]] = None,
    initial: Optional[Dict[bytes, bytes]] = None,
) -> CheckResult:
    """Grade a cross-group transactional history against STRICT
    serializability by VERIFYING the system's own commit-order witness
    (the decision group's apply order) rather than searching for one.

    The witness obligates three things, and failing any is a
    ``VIOLATION`` — this checker can call the system wrong, which is
    the falsifiability contract (``--broken txn_*`` pins it):

    1. **Reads explained at the serial point** — replaying committed
       transactions in decision order, every transaction's certified
       ``expects`` must equal the model state at its position (a
       coordinator that commits after a failed prewrite, or validates
       against staged/dirty values, breaks here);
    2. **Real time respected** — a transaction that completed before
       another was invoked must hold the earlier decision position
       (strictness: the witness cannot reorder non-overlapping txns);
    3. **Atomicity at the end state** — when ``final_state`` (a
       quiesced read of every key) is supplied, the replay's end state
       must match it exactly: a half-applied commit or an aborted
       transaction's leaked write both surface as a mismatch.

    ``info`` transactions (outcome unknown) make a failed end-state
    comparison ``UNDETERMINED`` instead of ``VIOLATION`` — the missing
    effects might be theirs. A committed txn with no recorded position
    is an incomplete witness: ``UNDETERMINED``."""
    committed = [t for t in txns if t.status == OK]
    unknown = [t for t in txns if t.status == INFO]
    steps = 0
    for t in committed:
        if t.pos is None:
            return CheckResult(
                UNDETERMINED, steps,
                detail=f"txn {t.txn_id} committed without a decision "
                       "position: witness incomplete",
            )
    order = sorted(committed, key=lambda t: t.pos)
    for a, b in zip(order, order[1:]):
        if a.pos == b.pos:
            return CheckResult(
                VIOLATION, steps,
                detail=f"txns {a.txn_id} and {b.txn_id} share decision "
                       f"position {a.pos}: the witness is not an order",
            )
    # 2) strictness: completed-before implies decided-before
    for i, a in enumerate(order):
        for b in order[i + 1:]:
            steps += 1
            if (b.complete_t is not None
                    and b.complete_t < a.invoke_t):
                return CheckResult(
                    VIOLATION, steps,
                    detail=f"txn {b.txn_id} completed at "
                           f"{b.complete_t:.3f} before txn {a.txn_id} "
                           f"was invoked at {a.invoke_t:.3f}, but "
                           f"decided later (pos {b.pos} > {a.pos})",
                )
    # 1) replay the witness
    state: Dict[bytes, Optional[bytes]] = dict(initial or {})
    for t in order:
        for k in sorted(t.expects):
            steps += 1
            if state.get(k) != t.expects[k]:
                return CheckResult(
                    VIOLATION, steps, key=k,
                    detail=f"txn {t.txn_id} (pos {t.pos}) certified "
                           f"{t.expects[k]!r} for key {k!r} but the "
                           f"serial state holds {state.get(k)!r}",
                )
        for k, v in t.writes.items():
            if v is None:
                state.pop(k, None)
            else:
                state[k] = v
    # 3) atomicity at the end state
    if final_state is not None:
        model = {k: v for k, v in state.items() if v is not None}
        for k in sorted(set(model) | set(final_state)):
            steps += 1
            if model.get(k) != final_state.get(k):
                if unknown:
                    return CheckResult(
                        UNDETERMINED, steps, key=k,
                        detail=f"end state of key {k!r} is "
                               f"{final_state.get(k)!r}, replay says "
                               f"{model.get(k)!r}; {len(unknown)} "
                               "unresolved txn(s) could explain it",
                    )
                return CheckResult(
                    VIOLATION, steps, key=k,
                    detail=f"end state of key {k!r} is "
                           f"{final_state.get(k)!r} but replaying the "
                           f"commit order yields {model.get(k)!r} "
                           "(atomicity broken)",
                )
    return CheckResult(
        SERIALIZABLE, steps,
        detail=f"{len(order)} committed txn(s) replayed in decision "
               f"order; {len(txns) - len(committed)} aborted/unknown",
    )
