"""Message-level fault injection at the Transport seam.

``ChaosTransport`` wraps any device transport and perturbs the
collective rounds the engine dispatches through it — the transport-level
half of the nemesis vocabulary that ``faults.FaultPlan``'s process-level
actions (kill/slow/partition) cannot express:

- **drop** — an AppendEntries window lost in transit: the victim row is
  folded into the round's ``slow`` mask, so it hears the round (term
  adoption, heartbeat) but appends nothing and its ack is lost; the
  repair window re-serves it on a later round, exactly as a real leader
  re-sends after a lost ack. For vote rounds the victim is removed from
  the round's ``alive`` mask: a dropped RequestVote yields no grant and
  no term adoption on that row.
- **dup** — the same message delivered twice: the round is followed by a
  zero-entry echo round with identical (leader, term, masks). Raft's
  idempotence obligations make the echo a protocol no-op (AppendEntries
  re-delivery; a repeat RequestVote re-grants to the same candidate);
  the engine sees only the REAL round's info, so its bookkeeping is
  untouched — any state the echo does advance (e.g. commit off an extra
  quorum round) is reported by the next real round.
- **delay** — a message delivered late: the victim rows are dropped from
  the current round and a zero-entry echo (the stale window, in the
  ORIGINAL leader's original term) is queued to run just before a later
  round. By delivery time the cluster may have moved on — higher terms
  refuse the stale round, which is precisely the §5.1/§5.3 machinery a
  delayed message must exercise. Delivery masks are intersected with the
  delivering round's ``alive`` so a row that died in between hears
  nothing.

Why masks and echoes rather than a message queue: this engine has no
per-message plane — a "message" IS a row's participation in one
collective launch — so the faithful injection point is the per-round
mask, and a duplicated/delayed message is a re-issued round. Safety is
never at stake by construction (Raft tolerates arbitrary message loss,
duplication, and reordering); what drops/dups/delays perturb is
*progress and timing*, which is exactly what the linearizability
checker needs varied. Host-side quorum checks (read confirmation,
CheckQuorum) read the engine's fault masks as ground truth — the
documented simulation framing (see ``read_linearizable``) — so message
faults model data-plane loss, not control-plane partitions; use
``partition()`` for those.

The wrapper deliberately does NOT expose ``replicate_pipeline``: the
engine's eligibility gate then routes every chunk through the general
scan path, keeping one code path under fault injection.
"""

from __future__ import annotations

import random
from typing import List, Tuple

import jax.numpy as jnp
import numpy as np


class ChaosTransport:
    """Seeded drop/dup/delay fault injection around a base transport."""

    def __init__(self, base, seed: int = 0):
        self.t = base
        self.cfg = base.cfg
        self.rng = random.Random(seed)
        self.p_drop = 0.0
        self.p_dup = 0.0
        self.p_delay = 0.0
        self.delay_rounds: Tuple[int, int] = (1, 4)
        self._deferred: List[tuple] = []   # (due_round, leader, term, eff, slow, kw)
        self._round = 0
        self.stats = {"drop": 0, "dup": 0, "delay": 0, "delivered": 0}
        self._hb = None

    # ------------------------------------------------------------- control
    def set_message_faults(
        self,
        p_drop: float = 0.0,
        p_dup: float = 0.0,
        p_delay: float = 0.0,
        delay_rounds: Tuple[int, int] = (1, 4),
    ) -> None:
        self.p_drop, self.p_dup, self.p_delay = p_drop, p_dup, p_delay
        self.delay_rounds = delay_rounds

    def clear_message_faults(self) -> None:
        """Stop injecting AND drop undelivered delayed echoes (heal)."""
        self.p_drop = self.p_dup = self.p_delay = 0.0
        self._deferred.clear()

    # ------------------------------------------------------------ plumbing
    def init(self):
        return self.t.init()

    def fetch(self, x):
        f = getattr(self.t, "fetch", None)
        return f(x) if f is not None else np.asarray(x)

    def _victims(self, p: float, mask: np.ndarray, keep: int) -> np.ndarray:
        """Bernoulli(p) victim mask over rows active in ``mask``, never
        the source row ``keep`` (a leader always hears itself)."""
        out = np.zeros_like(mask)
        if p <= 0.0:
            return out
        for r in np.flatnonzero(mask):
            r = int(r)
            if r != keep and self.rng.random() < p:
                out[r] = True
        return out

    def _hb_payload(self):
        if self._hb is None:
            cfg = self.cfg
            self._hb = jnp.zeros(
                (cfg.batch_size, cfg.rows * cfg.shard_words), jnp.int32
            )
        return self._hb

    def _echo(self, state, leader, term, eff, slow, kw):
        """One zero-entry round — a re-delivered (dup) or late (delay)
        window. Info is discarded: the engine never saw this message."""
        state, _ = self.t.replicate(
            state, self._hb_payload(), 0, leader, term,
            jnp.asarray(eff), jnp.asarray(slow), **kw,
        )
        return state

    def _run_due(self, state, current_alive):
        """Deliver delayed echoes that have come due, gated on the rows
        still alive at delivery time."""
        now_alive = np.asarray(current_alive).astype(bool)
        still: List[tuple] = []
        for item in self._deferred:
            due, leader, term, eff, slow, kw = item
            if self._round < due:
                still.append(item)
                continue
            eff_now = eff & now_alive
            if eff_now[leader]:
                self.stats["delivered"] += 1
                state = self._echo(state, leader, term, eff_now, slow, kw)
        self._deferred = still
        return state

    # ---------------------------------------------------------- transport
    def replicate(
        self, state, client_payload, client_count, leader, leader_term,
        alive, slow, **kw,
    ):
        # the device-observability ring (obs.device) rides only the
        # PRIMARY delivery: echoes (dup / delayed re-delivery) replay a
        # message the engine already observed, so recording them would
        # double-count transitions — and the deferred-echo kw snapshot
        # must never capture a stale ring
        ring = kw.pop("ring", None)
        self._round += 1
        state = self._run_due(state, alive)
        alive_np = np.asarray(alive).astype(bool)
        slow_np = np.asarray(slow).astype(bool)
        leader_i = int(leader)
        dropped = self._victims(self.p_drop, alive_np, leader_i)
        delayed = self._victims(self.p_delay, alive_np & ~dropped, leader_i)
        self.stats["drop"] += int(dropped.sum())
        self.stats["delay"] += int(delayed.sum())
        slow_round = slow_np | dropped | delayed
        if ring is not None:
            state, info, ring = self.t.replicate(
                state, client_payload, client_count, leader, leader_term,
                alive, jnp.asarray(slow_round), ring=ring, **kw,
            )
        else:
            state, info = self.t.replicate(
                state, client_payload, client_count, leader, leader_term,
                alive, jnp.asarray(slow_round), **kw,
            )
        if delayed.any():
            due = self._round + self.rng.randint(*self.delay_rounds)
            self._deferred.append(
                (due, leader_i, int(leader_term), alive_np.copy(),
                 slow_np.copy(), dict(kw))
            )
        if self.p_dup > 0.0 and self.rng.random() < self.p_dup:
            self.stats["dup"] += 1
            state = self._echo(
                state, leader_i, int(leader_term), alive_np, slow_np, kw
            )
        if ring is not None:
            return state, info, ring
        return state, info

    def fusion_ready(self) -> bool:
        """Whether the K-tick fused path may run RIGHT NOW: only while
        no message-fault plane is armed and no delayed echo is pending.
        The chaos rng draws (and the deferred-echo due arithmetic) are
        keyed to per-round calls, so fusing rounds under an armed fault
        plane would fork the seeded stream — the engine falls back to
        tick-at-a-time whenever this is False, which is exactly what
        keeps seeded replays byte-identical with fusion on vs off."""
        return (
            self.p_drop == 0.0 and self.p_dup == 0.0
            and self.p_delay == 0.0 and not self._deferred
            and hasattr(self.t, "replicate_fused")
            and getattr(self.t, "fusion_ready", lambda: True)()
        )

    def replicate_fused(self, state, staging, start_slot, counts, n_run,
                        *a, **kw):
        """Fault-free fused window (``fusion_ready`` gated by the
        engine): forward to the base transport, advancing the round
        counter by the window's tick count so deferred-echo due rounds
        stay aligned with what K tick-at-a-time rounds would have
        produced."""
        self._round += int(n_run)
        return self.t.replicate_fused(
            state, staging, start_slot, counts, n_run, *a, **kw
        )

    def replicate_many(
        self, state, payloads, counts, leader, leader_term, alive, slow,
        **kw,
    ):
        """Chunked scans see one drop draw for the whole chunk (the
        chunk is one dispatch; per-step faults inside a compiled scan
        would need a device-side fault plane)."""
        self._round += 1
        state = self._run_due(state, alive)
        alive_np = np.asarray(alive).astype(bool)
        dropped = self._victims(self.p_drop, alive_np, int(leader))
        self.stats["drop"] += int(dropped.sum())
        slow_round = np.asarray(slow).astype(bool) | dropped
        return self.t.replicate_many(
            state, payloads, counts, leader, leader_term, alive,
            jnp.asarray(slow_round), **kw,
        )

    def request_votes(self, state, candidate, cand_term, alive,
                      ring=None, quorum=0):
        self._round += 1
        alive_np = np.asarray(alive).astype(bool)
        dropped = self._victims(self.p_drop, alive_np, int(candidate))
        self.stats["drop"] += int(dropped.sum())
        if ring is not None:
            state, info, ring = self.t.request_votes(
                state, candidate, cand_term,
                jnp.asarray(alive_np & ~dropped), ring=ring, quorum=quorum,
            )
        else:
            state, info = self.t.request_votes(
                state, candidate, cand_term, jnp.asarray(alive_np & ~dropped)
            )
        if self.p_dup > 0.0 and self.rng.random() < self.p_dup:
            # repeat RequestVote delivery: re-grants to the same
            # candidate in the same term (idempotent by §5.2's
            # one-vote-per-term rule); the first round's info stands
            self.stats["dup"] += 1
            state, _ = self.t.request_votes(
                state, candidate, cand_term, jnp.asarray(alive_np & ~dropped)
            )
        if ring is not None:
            return state, info, ring
        return state, info
