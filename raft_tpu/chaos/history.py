"""Client-visible operation histories — the Jepsen event model.

Every client operation is recorded as an interval on the engine's
VIRTUAL clock: an ``invoke`` event when the client issues it and exactly
one terminal event later —

- ``ok``   — the operation completed and its result is known (a write
  acknowledged durable; a read served with a confirmed read index).
- ``fail`` — the operation PROVABLY took no effect (a refused
  linearizable read, a submit rejected before queueing). The checker
  removes these outright; marking an op ``fail`` when it might have
  applied is unsound, so the recorders only use it where the engine
  guarantees no effect.
- ``info`` — the outcome is unknown (a write in flight across a crash,
  or still unresolved at the end of the run). The checker must consider
  BOTH worlds: the op may have taken effect at any point after its
  invocation, or never.

This is the half of the Jepsen methodology the Raft-internal invariant
suites (tests/test_properties.py, tests/test_chaos.py) cannot supply:
those check what the *replicas* agree on; a history checks what the
*clients* were told — the contract of Raft §8 that end users actually
observe. Histories are recorded per key (``per_key``), which is what
makes checking tractable: a sharded KV is linearizable iff every key's
subhistory is (Herlihy–Wing locality / P-compositionality), and the
multi-Raft ``Router`` guarantees a key never changes groups.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

WRITE = "write"
DELETE = "delete"
READ = "read"

OK = "ok"
FAIL = "fail"
INFO = "info"
PENDING = "pending"


@dataclasses.dataclass
class OpRecord:
    """One client operation's lifetime. ``value`` is the value written
    (write), ``None`` (delete), or the value RETURNED (read; ``None`` =
    key absent). The linearization point must lie in
    ``[invoke_t, complete_t]`` (``complete_t`` None = unbounded)."""

    client: int
    op: str                      # WRITE | DELETE | READ
    key: bytes
    value: Optional[bytes]
    invoke_t: float
    complete_t: Optional[float] = None
    status: str = PENDING        # PENDING -> OK | FAIL | INFO

    def ok(self, t: float, value: Optional[bytes] = None) -> "OpRecord":
        assert self.status == PENDING, f"terminal event on {self.status} op"
        self.status = OK
        self.complete_t = t
        if self.op == READ:
            self.value = value
        return self

    def fail(self, t: float) -> "OpRecord":
        """The op provably took no effect (see module docstring — never
        use for a write that may still commit)."""
        assert self.status == PENDING, f"terminal event on {self.status} op"
        self.status = FAIL
        self.complete_t = t
        return self

    def info(self) -> "OpRecord":
        """Outcome unknown: the op keeps an unbounded interval — it may
        have taken effect at any time after ``invoke_t``, or never."""
        assert self.status == PENDING, f"terminal event on {self.status} op"
        self.status = INFO
        return self


class History:
    """Append-only operation history with per-key projection.

    Timestamps are refined to a strictly monotone sequence
    (``stamp``): the virtual clock is coarse — a whole client round can
    share one instant — but the single-threaded harness really does
    execute those events in order, so sub-tick ordering IS real-time
    order and recording it is sound. Without it, same-instant events
    all read as concurrent and the checker loses exactly the ordering
    constraints that catch same-round stale reads."""

    EPS = 1e-6

    #: record class instantiated by ``invoke`` — subclasses substitute a
    #: richer record (e.g. the chaos runner's span-closing _SpannedOp)
    #: without re-implementing the stamp/append logic, so the timestamp
    #: discipline cannot drift between observed and plain runs
    REC_CLS = OpRecord

    def __init__(self) -> None:
        self.ops: List[OpRecord] = []
        self._last = 0.0

    def stamp(self, t: float) -> float:
        """Refine a virtual-clock reading to the next strictly-monotone
        instant (host execution order breaks clock ties)."""
        self._last = max(t, self._last + self.EPS)
        return self._last

    def invoke(
        self,
        client: int,
        op: str,
        key: bytes,
        value: Optional[bytes],
        t: float,
    ) -> OpRecord:
        rec = self.REC_CLS(client, op, key, value, invoke_t=self.stamp(t))
        self._on_invoke(rec)
        self.ops.append(rec)
        return rec

    def _on_invoke(self, rec: OpRecord) -> None:
        """Subclass hook, called after the record is stamped and before
        it is appended (e.g. to open an obs span for it)."""

    def close(self) -> None:
        """End of run: any op still pending resolves to ``info`` —
        its outcome was never observed, so the checker must allow both
        worlds."""
        for rec in self.ops:
            if rec.status == PENDING:
                rec.info()

    def per_key(self) -> Dict[bytes, List[OpRecord]]:
        out: Dict[bytes, List[OpRecord]] = {}
        for rec in self.ops:
            out.setdefault(rec.key, []).append(rec)
        return out

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for rec in self.ops:
            out[rec.status] = out.get(rec.status, 0) + 1
        return out

    def __len__(self) -> int:
        return len(self.ops)
