"""Simulated durable storage for torture runs, with fault injectors.

The engine's durability stack is a checkpoint file
(``ckpt.EngineCheckpoint`` — the archived committed tail + terms +
votedFor) plus a vote WAL (``ckpt.VoteLog``). In a real deployment that
state is replicated across R machines' disks; in this single-process
engine it is one file set, so a storage fault against "the" checkpoint
would be a correlated failure of every replica's disk at once — a
failure mode Raft does not claim to survive. ``MirroredStore``
therefore models the deployment's redundancy at the file level: each
checkpoint generation is written to M mirror slots, each with a CRC32
sidecar, and recovery picks the newest mirror that validates. The
nemesis may corrupt mirrors **as long as at least one stays healthy**
— the storage analogue of the "keep a majority alive" rule that lets
torture runs quiesce.

Fault vocabulary (applied between crash and restart):

- ``tear_votelog``  — append a torn partial record (a crash mid-append
  that never returned): ``VoteLog``'s open path must trim it, or replay
  framing silently garbles every later record.
- ``flip_bit``      — flip one random bit in one mirror's checkpoint
  file: recovery must *detect* the corruption (CRC mismatch) and fall
  back to another mirror, never load garbage as committed state.
- ``rollback``      — replace one mirror (file + sidecar) with the
  previous generation (a filesystem-level rollback / lost write): the
  stale mirror is internally VALID, so recovery must prefer the mirror
  with the higher committed watermark, not merely any valid one.
- ``wipe_node``     — total disk loss of ONE node: its (term, votedFor)
  slice is zeroed in every mirror generation (current and previous, so
  a later ``rollback`` cannot resurrect it) and its records dropped
  from the vote WAL. Unlike the corruptions above this is a *clean*
  loss the recovery path is allowed to load — the protocol-level
  defense is the engine's wiped-voter rule: a node whose durable
  identity is gone must rejoin through removal + learner re-admission
  (``RaftEngine.replace``), never resume as a voter.

``load_best`` is the recovery path under test: validate every mirror
(sidecar CRC over the raw bytes, then a real ``EngineCheckpoint.load``),
rank by committed watermark, refuse only when NO mirror survives.
"""

from __future__ import annotations

import os
import random
import zlib
from typing import Dict, List, Optional, Set, Tuple

from raft_tpu.ckpt import EngineCheckpoint


class MirroredStore:
    """M mirrored checkpoint slots + one vote WAL under ``root``."""

    def __init__(self, root: str, mirrors: int = 2):
        if mirrors < 2:
            raise ValueError(
                "need >= 2 mirrors: with one, any storage fault is a "
                "correlated total loss the harness must not inject"
            )
        self.root = root
        self.mirrors = mirrors
        os.makedirs(root, exist_ok=True)
        self.generation = 0
        # re-opening over existing mirrors must keep the generation
        # counter monotone, or fresh saves would rank BELOW stale files
        for i in range(mirrors):
            crc = self._crc_path(self.mirror_path(i))
            for side in (crc, self._prev_path(crc)):
                try:
                    with open(side) as f:
                        gen = int(f.read().split()[1])
                    self.generation = max(self.generation, gen + 1)
                except (OSError, ValueError, IndexError):
                    pass

    # -------------------------------------------------------------- paths
    @property
    def votelog_path(self) -> str:
        return os.path.join(self.root, "votes.wal")

    def mirror_path(self, i: int) -> str:
        return os.path.join(self.root, f"ckpt.m{i}.npz")

    def _crc_path(self, path: str) -> str:
        return path + ".crc"

    def _prev_path(self, path: str) -> str:
        return path + ".prev"

    # --------------------------------------------------------------- save
    def save(self, engine) -> None:
        """One ``save_checkpoint`` fanned out to every mirror with CRC
        sidecars; the previous generation is kept per mirror (it is what
        a rollback fault restores). The engine writes mirror 0 itself
        (its WAL-rotation side effect must run exactly once); the other
        mirrors are byte copies."""
        p0 = self.mirror_path(0)
        for i in range(self.mirrors):
            p = self.mirror_path(i)
            if os.path.exists(p):
                os.replace(p, self._prev_path(p))
                crc = self._crc_path(p)
                if os.path.exists(crc):
                    os.replace(crc, self._prev_path(crc))
        engine.save_checkpoint(p0)
        with open(p0, "rb") as f:
            blob = f.read()
        for i in range(self.mirrors):
            p = self.mirror_path(i)
            if i > 0:
                with open(p, "wb") as f:
                    f.write(blob)
            # sidecar: CRC + the save generation. The generation breaks
            # watermark ties in load_best: a rolled-back mirror can carry
            # the SAME watermark as the current one (no commits between
            # saves) while holding older terms — restoring those would
            # regress durable vote state (the double-vote hazard).
            with open(self._crc_path(p), "w") as f:
                f.write(f"{zlib.crc32(blob):08x} {self.generation}\n")
        self.generation += 1

    # ------------------------------------------------------------ recovery
    def _validate(self, path: str) -> Optional[Tuple[int, int]]:
        """(generation, watermark) if the mirror is healthy, else None."""
        crc_path = self._crc_path(path)
        try:
            with open(path, "rb") as f:
                blob = f.read()
            with open(crc_path) as f:
                crc_hex, gen_s = f.read().split()
            want, gen = int(crc_hex, 16), int(gen_s)
        except (OSError, ValueError):
            return None
        if zlib.crc32(blob) != want:
            return None
        try:
            ck = EngineCheckpoint.load(path)
        except Exception:
            return None
        return gen, int(ck.snap.last_index)

    def load_best(self) -> Tuple[str, int, List[int]]:
        """(path, watermark, rejected mirror ids) of the newest healthy
        mirror — newest by save generation, so an internally-valid but
        rolled-back mirror never outranks the current one. Raises when
        every mirror is corrupt — the correlated loss the nemesis is
        forbidden from injecting."""
        best: Optional[Tuple[Tuple[int, int], str]] = None
        rejected: List[int] = []
        for i in range(self.mirrors):
            p = self.mirror_path(i)
            rank = self._validate(p)
            if rank is None:
                rejected.append(i)
                continue
            if best is None or rank > best[0]:
                best = (rank, p)
        if best is None:
            raise RuntimeError(
                "no healthy checkpoint mirror survives; the nemesis "
                "violated the keep-one-healthy rule"
            )
        return best[1], best[0][1], rejected

    # --------------------------------------------------------- fault hooks
    def tear_votelog(self, rng: random.Random) -> None:
        """Crash mid-append: a partial trailing record (1-15 garbage
        bytes) that was never acted on — ``VoteLog.__init__`` must trim
        it before appending."""
        if not os.path.exists(self.votelog_path):
            return
        with open(self.votelog_path, "ab") as f:
            f.write(bytes(rng.getrandbits(8) for _ in range(rng.randint(1, 15))))

    def flip_bit(self, mirror: int, rng: random.Random) -> None:
        p = self.mirror_path(mirror)
        with open(p, "rb") as f:
            blob = bytearray(f.read())
        if not blob:
            return
        pos = rng.randrange(len(blob))
        blob[pos] ^= 1 << rng.randrange(8)
        with open(p, "wb") as f:
            f.write(bytes(blob))

    def rollback(self, mirror: int) -> bool:
        """Restore one mirror's previous generation (file + sidecar);
        False when no previous generation exists yet."""
        p = self.mirror_path(mirror)
        prev, prev_crc = self._prev_path(p), self._prev_path(self._crc_path(p))
        if not (os.path.exists(prev) and os.path.exists(prev_crc)):
            return False
        os.replace(prev, p)
        os.replace(prev_crc, self._crc_path(p))
        return True

    def wipe_node(self, r: int) -> None:
        """Destroy node ``r``'s durable identity across the whole store:
        zero its (term, votedFor) slice in EVERY mirror generation —
        current and ``.prev``, so neither recovery nor a later
        ``rollback`` fault can resurrect its votes — and drop its rows
        from the vote WAL. The mirrors stay internally VALID (fresh CRC
        sidecars, same generation rank): this is clean disk loss, not
        corruption, and pairs with ``RaftEngine.wipe``'s in-memory half
        during the chaos wipe-replace cycle."""
        from raft_tpu.ckpt import EngineCheckpoint
        from raft_tpu.ckpt.votelog import _MAGIC, _REC, VoteLog

        for i in range(self.mirrors):
            for path in (self.mirror_path(i),
                         self._prev_path(self.mirror_path(i))):
                crc_path = (
                    self._crc_path(path) if not path.endswith(".prev")
                    else self._prev_path(self._crc_path(self.mirror_path(i)))
                )
                if not (os.path.exists(path) and os.path.exists(crc_path)):
                    continue
                try:
                    ck = EngineCheckpoint.load(path)
                    with open(crc_path) as f:
                        gen = int(f.read().split()[1])
                except Exception:
                    continue   # already-corrupt mirrors stay corrupt
                if not (0 <= r < ck.terms.shape[0]):
                    continue
                ck.terms[r] = 0
                ck.voted_for[r] = -1
                ck.save(path)
                with open(path, "rb") as f:
                    blob = f.read()
                with open(crc_path, "w") as f:
                    f.write(f"{zlib.crc32(blob):08x} {gen}\n")
        # vote WAL: rewrite without r's records (a torn trailing record,
        # if any, is dropped with the rewrite — same as VoteLog's own
        # open-path trim)
        recs = VoteLog.replay(self.votelog_path)
        if r in recs:
            del recs[r]
            with open(self.votelog_path, "wb") as f:
                f.write(_MAGIC)
                for q in sorted(recs):
                    t, v = recs[q]
                    f.write(_REC.pack(int(q), int(t), int(v)))
                f.flush()
                os.fsync(f.fileno())


class SegmentNemesis:
    """Fault injectors against a ``ckpt.tiered.TieredStore``'s sealed
    shard files — the cold tier's analogue of the mirror faults above.

    Fault vocabulary (each names a distinct real-world storage failure):

    - ``torn_spill``  — truncate one shard file mid-bytes, sidecar left
      stale (a crash mid-spill that `os.replace`'d anyway, or a
      filesystem that lost the tail): the CRC must reject the shard.
    - ``flip_bit``    — flip one random payload bit in one shard file
      (bit rot): the CRC must reject; the segment reconstructs from the
      surviving shards through the RS decode.
    - ``drop_shard``  — delete one shard file + sidecar outright (a
      lost object / dead disk sector).

    Keep-k rule (the storage analogue of keep-a-majority-alive): the
    nemesis never reduces a segment below k healthy shards — below
    that the data is genuinely unrecoverable and the store's documented
    behavior is an archive gap, not recovery. Fault bookkeeping is per
    segment row, so composed faults across rounds stay within budget.

    Every injection clears the store's decoded-segment cache: the next
    read must hit the disk files, or a warm cache would vacuously pass
    the recovery assertion.
    """

    KINDS = ("torn_spill", "flip_bit", "drop_shard")

    def __init__(self, store):
        self.store = store
        self._faulted: Dict[Tuple[int, int], Set[int]] = {}

    def _pick(self, rng: random.Random,
              within: Optional[Tuple[int, int]] = None,
              data_only: bool = False,
              ) -> Optional[Tuple[int, int, int]]:
        """(lo, hi, shard row) of a faultable shard, or None when no
        segment has fault budget left. ``within`` restricts candidates
        to segments overlapping that index range — the drill uses it to
        place faults squarely on a rejoining follower's catch-up path
        (a fault on a segment nothing reads proves nothing).
        ``data_only`` restricts the row choice to systematic DATA
        shards (rows 0..k-1): a parity-only fault recovers through the
        systematic stitch with no decode, so a drill asserting the RS
        reconstruct path engaged must corrupt data rows."""
        code = self.store.io.code
        segs = [
            (lo, hi) for (lo, hi) in self.store._sealed
            if len(self._faulted.get((lo, hi), ())) < code.m
            and (within is None
                 or (lo <= within[1] and hi >= within[0]))
            and (not data_only or any(
                r not in self._faulted.get((lo, hi), set())
                for r in range(code.k)
            ))
        ]
        if not segs:
            return None
        lo, hi = segs[rng.randrange(len(segs))]
        rows = [
            r for r in range(code.k if data_only else code.n)
            if r not in self._faulted.get((lo, hi), set())
        ]
        return lo, hi, rows[rng.randrange(len(rows))]

    def inject(self, rng: random.Random,
               kind: Optional[str] = None,
               within: Optional[Tuple[int, int]] = None,
               data_only: bool = False) -> Optional[str]:
        """Apply one fault (seeded choice when ``kind`` is None);
        returns a human-readable description, or None when no sealed
        segment (overlapping ``within``, if given) can absorb a fault
        under the keep-k rule."""
        got = self._pick(rng, within, data_only)
        if got is None:
            return None
        lo, hi, row = got
        kind = kind or rng.choice(self.KINDS)
        io = self.store.io
        name = io.name(lo, hi)
        p = io.shard_path(name, row)
        if kind == "torn_spill":
            with open(p, "rb") as f:
                blob = f.read()
            keep = rng.randrange(max(1, len(blob) // 2), len(blob))
            with open(p, "wb") as f:
                f.write(blob[:keep])
        elif kind == "flip_bit":
            with open(p, "rb") as f:
                blob = bytearray(f.read())
            pos = rng.randrange(len(blob))
            blob[pos] ^= 1 << rng.randrange(8)
            with open(p, "wb") as f:
                f.write(bytes(blob))
        elif kind == "drop_shard":
            for path in (p, io._crc_path(p)):
                try:
                    os.unlink(path)
                except OSError:
                    pass
        else:
            raise ValueError(f"unknown segment fault {kind!r}")
        self._faulted.setdefault((lo, hi), set()).add(row)
        # force the next read through the faulted files
        self.store._cache.clear()
        self.store._cache_order.clear()
        return f"{kind}(seg=[{lo},{hi}], shard={row})"
