"""The torture runner: workload + nemesis + history + checker, end to end.

``torture_run`` drives a single-group ``RaftEngine`` (with a recorded
``ReplicatedKV`` workload) and ``torture_run_multi`` a key-sharded
``MultiEngine``+``Router`` stack, through a seeded nemesis schedule —
process faults, message faults, and whole-process crash /
checkpoint-restore / restart cycles with storage faults against the
durability files — then quiesces, closes the client history, and hands
it to the linearizability checker. Every random choice (workload and
nemesis alike) derives from the one seed, so a failing run's report
carries a one-line repro: ``python -m raft_tpu.chaos --seed N ...``.

Crash model. The engine is one process simulating R replicas, so a
"crash" is the loss of every replica's VOLATILE state at one instant:
queues, in-flight ops, roles, timers. Durable state is what the
durability stack had on disk — the mirrored checkpoint
(``MirroredStore``) and the vote WAL — which is exactly what
``RaftEngine.restore`` rebuilds from. The runner snapshots the durable
state at the crash instant (the archive IS the simulated disk: every
committed entry was "written" when it committed), lets the nemesis
corrupt it within the keep-one-mirror-healthy rule, restores, and
carries the virtual clock forward so history timestamps stay monotone.
Writes in flight across a crash resolve as ``info`` (they may have
committed just before the crash — the checker explores both worlds);
in-flight reads resolve as ``fail`` (a read that never returned has no
effect to account for).

Client model. Each virtual client runs ONE op at a time (serial — the
§6.3 discipline) against its own rng stream: mostly writes of fresh
values (every written value is unique, which maximizes the checker's
discriminating power: a stale read names its exact culprit), reads via
the batched ReadIndex ticket path (``submit_read``/``read_confirmed``),
and occasional deletes. ``broken="dirty_reads"`` swaps the read path
for one that serves the latest SUBMITTED (possibly uncommitted) value
without leadership confirmation — the deliberately broken variant the
checker must reject, proving the harness has teeth.

Overload model (``overload=True`` / ``overload_run``; docs/OVERLOAD.md).
The closed-loop clients above are polite — they wait for outcomes — so
they can never overrun admission. The overload phases add OPEN-LOOP
traffic: Poisson arrivals at a nemesis-chosen 2-10x multiple of the
cluster's measured ingest capacity (``batch_size / heartbeat_period``
entries/s — the most a leader tick can drain), each arrival a one-shot
write from its own client id (fully concurrent, exactly the
open-loop assumption). An arrival the admission gate refuses resolves
``fail`` at once — ``Overloaded`` is raised before anything is queued,
so failed-without-effect is SOUND and the linearizability verdict must
stay ACCEPT through the storm. Admitted arrivals resolve like any
write (ok once durable, info across a crash or at give-up). The
admission bound is what keeps the harness itself bounded: outstanding
open-loop state never exceeds the configured queue depth.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import os
import random
import tempfile
import zlib
from typing import Dict, List, Optional, Tuple

from raft_tpu.admission import Overloaded
from raft_tpu.chaos.checker import (
    LINEARIZABLE,
    VIOLATION,
    CheckResult,
    check_history,
    check_read_classes,
)
from raft_tpu.chaos.history import DELETE, READ, WRITE, History, OpRecord
from raft_tpu.chaos.nemesis import MembershipView, Nemesis, NemesisAction
from raft_tpu.chaos.storage import MirroredStore, SegmentNemesis
from raft_tpu.chaos.transport import ChaosTransport
from raft_tpu.config import RaftConfig
from raft_tpu.obs import blackbox
from raft_tpu.obs.forensics import (
    ObsStack,
    resolve_bundle_dir,
    write_bundle,
)


def poisson(rng: random.Random, lam: float) -> int:
    """One Poisson(lam) draw from a seeded stream (open-loop arrival
    counts per drive slice). Knuth's product method below ~700 (exp
    underflow bound), normal approximation above."""
    if lam <= 0:
        return 0
    if lam > 700.0:
        return max(0, int(round(rng.gauss(lam, math.sqrt(lam)))))
    limit = math.exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


@dataclasses.dataclass
class TortureReport:
    seed: int
    check: CheckResult
    ops: int
    op_counts: Dict[str, int]
    crashes: int
    msg_stats: Dict[str, int]
    nemesis_log: List[str]
    repro: str
    shed_ops: int = 0          # admission-refused arrivals (fail, no effect)
    open_loop_ops: int = 0     # open-loop arrivals generated in total
    membership_ops: Dict[str, int] = dataclasses.field(default_factory=dict)
    #   reconfiguration ops the membership plane actually started
    #   (grow/shrink/remove_leader/replace) — coverage evidence for the
    #   pinned seeds
    commit_digest: str = ""
    #   CRC over the committed log (indices, terms, payload bytes) at
    #   run end — the byte-identity witness for the observability
    #   determinism pin (recorder on == recorder off).
    bundle_path: Optional[str] = None
    #   forensics repro bundle, written iff the verdict was unexpected
    #   AND a bundle destination was configured (obs.forensics).
    obs: Optional[ObsStack] = None
    #   the run's observability plane when ``observe=True`` (flight
    #   recorder ring + span table + metrics registry), for callers
    #   that inspect signals beyond the bundle.

    @property
    def verdict(self) -> str:
        return self.check.verdict

    def summary(self) -> str:
        line = (
            f"seed {self.seed}: {self.verdict} over {self.ops} ops "
            f"({self.op_counts}), {self.crashes} crash cycles, "
            f"msg {self.msg_stats}"
        )
        if self.membership_ops:
            line += f", membership {self.membership_ops}"
        if self.verdict != LINEARIZABLE:
            line += f"\n  {self.check.detail}\n  REPRO: {self.repro}"
        return line


def _default_cfg(seed: int) -> RaftConfig:
    return RaftConfig(
        n_replicas=3, entry_bytes=32, batch_size=4, log_capacity=128,
        transport="single", seed=seed,
    )


def _overload_cfg(seed: int) -> RaftConfig:
    """The torture config with admission armed: bounded queues + the
    delay controller, sized to the toy cluster (capacity 2 entries/s:
    batch 4 per 2 s tick). Depth 16 = 8 s of queue at capacity; the
    delay controller targets two ticks of sojourn judged over one
    election-timeout-scale interval."""
    return dataclasses.replace(
        _default_cfg(seed),
        admission_max_writes=16,
        admission_max_reads=64,
        admission_target_delay_s=4.0,
        admission_interval_s=20.0,
    )


def _membership_cfg(base: RaftConfig) -> RaftConfig:
    """Arm a torture config for the membership plane: two spare rows of
    headroom over the 3-voter start, so grow / replace always have a
    row to admit."""
    return dataclasses.replace(base, max_replicas=5)


def _reads_cfg(base: RaftConfig) -> RaftConfig:
    """Arm a torture config for the read scale-out plane: leader
    leases (which REQUIRE prevote — the §9.6 stickiness the lease
    safety argument rests on) under the default 2x drift bound."""
    return dataclasses.replace(base, prevote=True, read_lease=True)


#: admission-flavored refusal reasons: a span whose refusal trail hit
#: one of these closes as ``shed`` (typed load shedding), anything else
#: refused closes as plain ``failed``
_SHED_REASONS = {"depth", "delay", "fair_share", "read_depth",
                 "circuit_open"}


class _SpannedOp(OpRecord):
    """An OpRecord that closes its obs span on its own terminal event —
    every resolution path in the harness (poll, give-up, crash resolve,
    quiesce, ``History.close``) already funnels through ``ok``/``fail``/
    ``info``, so hooking here guarantees the span-completeness invariant
    (exactly one terminal span state per invoked op) by construction."""

    _span = None

    def ok(self, t, value=None):
        super().ok(t, value)
        if self._span is not None and not self._span.terminal:
            self._span.finish("ok", t)
        return self

    def fail(self, t):
        super().fail(t)
        if self._span is not None and not self._span.terminal:
            shed = bool(_SHED_REASONS & set(self._span.refusal_reasons))
            self._span.finish("shed" if shed else "failed", t)
        return self

    def info(self):
        super().info()
        if self._span is not None and not self._span.terminal:
            self._span.finish("info", None)
        return self


class _ObsHistory(History):
    """A History that opens one span per invoked op (closed by the
    record's terminal event — see _SpannedOp). The stamp/append logic
    stays in History.invoke (REC_CLS + _on_invoke hooks), so observed
    and plain runs share one timestamp discipline by construction."""

    REC_CLS = _SpannedOp

    def __init__(self, spans):
        super().__init__()
        self._spans = spans

    def _on_invoke(self, rec):
        rec._span = self._spans.begin(
            rec.op, rec.invoke_t, client=rec.client, key=rec.key
        )


class _Client:
    """One serial client: at most one op outstanding, its own rng."""

    def __init__(self, cid: int, seed: int, keys: List[bytes]):
        self.cid = cid
        self.rng = random.Random(f"client:{seed}:{cid}")
        self.keys = keys
        self.rec: Optional[OpRecord] = None
        self.ticket: Optional[int] = None   # read ticket (single-engine)
        self.seq = None                     # write seq (engine-specific)
        self.counter = 0

    def fresh_value(self) -> bytes:
        self.counter += 1
        return f"c{self.cid}v{self.counter}".encode()

    def pick(self) -> tuple:
        """(op, key, value) for the next invocation."""
        key = self.rng.choice(self.keys)
        roll = self.rng.random()
        if roll < 0.45:
            return WRITE, key, self.fresh_value()
        if roll < 0.52:
            return DELETE, key, None
        return READ, key, None


class _TortureBase:
    """Shared phase loop: invoke / drive / poll / nemesis / quiesce."""

    #: virtual seconds a client waits on one op before giving up. A
    #: write dropped across a leadership change never reads durable, and
    #: a serial client with no give-up would starve the workload for the
    #: rest of the run (seed sweeps showed 3-op histories). Giving up is
    #: recorded honestly: an abandoned write resolves ``info`` (it may
    #: STILL commit later — the unbounded interval covers that), an
    #: abandoned read ``fail`` (a read that served no value has no
    #: effect); the client then moves on.
    OP_TIMEOUT_S = 90.0

    def __init__(self, seed, phases, clients, keys, phase_s,
                 observe: bool = False, observe_device: bool = False,
                 audit: bool = False, observe_compile: bool = False):
        self.seed = seed
        self.phases = phases
        self.phase_s = phase_s
        # sentinel opt-in via env, the RAFT_TPU_FUSE_K pattern: arm the
        # compile plane without touching the harness call sites
        observe_compile = observe_compile or (
            (os.environ.get("RAFT_TPU_COMPILE_SENTINEL", "") or "0")
            != "0"
        )
        slo_objectives = None
        if audit:
            from raft_tpu.obs.slo import SLObjective

            # a generic commit objective so the SLO plane evaluates
            # burn rates during the run (alerts are passive events)
            slo_objectives = (
                SLObjective("commit_p99", "commit",
                            threshold_s=10.0, target=0.99),
            )
        self.obs: Optional[ObsStack] = (
            ObsStack.build(device=observe_device, audit=audit,
                           slo_objectives=slo_objectives,
                           compile_plane=observe_compile)
            if (observe or observe_device or audit or observe_compile)
            else None
        )
        #   observe_compile additionally attaches the XLA plane
        #   (obs.compile CompileWatch + RetraceSentinel, obs.memory
        #   census). The sentinel freezes after the warmup phase
        #   (run_phases); crash-restore cycles after that must hit the
        #   process-wide program caches or violate. Determinism-neutral
        #   like every other plane (pinned in tests/test_compile_plane).
        #   observe_device additionally attaches the device-resident
        #   plane (obs.device in-kernel rings); it implies observe.
        #   audit additionally attaches the ONLINE safety plane
        #   (obs.audit.SafetyAuditor + obs.slo.SloTracker); it also
        #   implies observe. Both are determinism-neutral: every seeded
        #   run replays byte-identically with them on or off (pinned by
        #   tests/test_obs_plane.py and tests/test_audit.py).
        #   the observability plane (flight recorder + spans + metrics;
        #   docs/OBSERVABILITY.md). Recording is determinism-neutral:
        #   every seeded run replays byte-identically with it on or off
        #   (pinned by tests/test_obs_plane.py).
        self.history = (
            _ObsHistory(self.obs.spans) if self.obs is not None
            else History()
        )
        self.keys = [f"k{i}".encode() for i in range(keys)]
        self.clients = [_Client(c, seed, self.keys) for c in range(clients)]
        self.crashes = 0
        # open-loop overload state (only driven when a runner arms it)
        self.shed_ops = 0          # admission refusals (recorded fail)
        self.ol_submitted = 0      # open-loop arrivals generated
        self._ol_rate = 0.0        # arrivals/s while a window is open
        self._ol_counter = 0
        self._ol_rng = random.Random(f"openloop:{seed}")
        self._ol_pending: List[Tuple[OpRecord, object, float]] = []
        #   (record, engine-specific seq handle, invoke time) of admitted
        #   open-loop writes awaiting durability — bounded by the
        #   admission depth bound, which is what keeps the harness's own
        #   memory bounded under any offered load

    def _ambient_span(self, rec):
        """Context manager installing ``rec``'s span as the tracker's
        ambient trace context for the duration of a client call — the
        engine's submit/submit_read hooks bind seqs and refusal reasons
        to whatever span is ambient (obs.spans)."""
        if self.obs is None or rec is None:
            return contextlib.nullcontext()
        return self._set_current(getattr(rec, "_span", None))

    @contextlib.contextmanager
    def _set_current(self, span):
        self.obs.spans.current = span
        try:
            yield
        finally:
            self.obs.spans.current = None

    def commit_digest(self) -> str:
        """CRC over the committed log at run end (engine-specific) —
        the determinism witness the observability pin compares."""
        raise NotImplementedError

    def _give_up(self, cl: _Client) -> bool:
        """Client-side op timeout (see OP_TIMEOUT_S); True if resolved."""
        rec = cl.rec
        if rec is None or self.now() - rec.invoke_t <= self.OP_TIMEOUT_S:
            return False
        if rec.op == READ:
            rec.fail(self.history.stamp(self.now()))
        else:
            rec.info()
        cl.rec, cl.ticket, cl.seq = None, None, None
        return True

    # engine adapters ----------------------------------------------------
    def now(self) -> float:
        raise NotImplementedError

    def drive(self, seconds: float) -> None:
        raise NotImplementedError

    def invoke(self, cl: _Client) -> None:
        raise NotImplementedError

    def poll(self, cl: _Client) -> None:
        raise NotImplementedError

    def apply_nemesis(self, act: NemesisAction) -> None:
        raise NotImplementedError

    def quiesce(self) -> None:
        raise NotImplementedError

    def _ol_durable(self, handle) -> bool:
        """Engine-specific durability check for an open-loop write."""
        raise NotImplementedError

    def _poll_open_loop(self) -> None:
        keep = []
        for rec, handle, t0 in self._ol_pending:
            if self._ol_durable(handle):
                rec.ok(self.history.stamp(self.now()))
            elif self.now() - t0 > self.OP_TIMEOUT_S:
                rec.info()     # may still commit: both worlds stay open
            else:
                keep.append((rec, handle, t0))
        self._ol_pending = keep

    def _resolve_open_loop_info(self) -> None:
        """Crash path: every admitted-but-unresolved open-loop write may
        or may not have committed — close as info."""
        for rec, _, _ in self._ol_pending:
            rec.info()
        self._ol_pending = []

    # the loop -----------------------------------------------------------
    def _poll_all(self) -> None:
        for cl in self.clients:
            if cl.rec is not None:
                self.poll(cl)
        if self._ol_pending:
            self._poll_open_loop()

    def _invoke_idle(self) -> None:
        for cl in self.clients:
            if cl.rec is None:
                self.invoke(cl)

    def pump_open_loop(self, dt: float) -> None:
        """Open-loop arrival hook, called once per drive slice; the
        base workload is closed-loop only (overload runners override)."""

    def pump_membership(self) -> None:
        """Membership-plane housekeeping hook, called once per drive
        slice (wipe-replace rejoin timing — see _SingleTorture)."""

    def pump_broken(self) -> None:
        """Broken-variant hook, called once per drive slice (the
        ``commit_rewind`` fault injection — see _SingleTorture)."""

    def _audit_read(self, client: int, key: bytes,
                    value, group=None) -> None:
        """Report one SERVED read to the online auditor (no-op when the
        audit plane is detached) — the serve-side half of the per-client
        monotone-read watermark."""
        obs = self.obs
        if obs is not None and obs.audit is not None:
            obs.audit.observe_read(client, key, value, self.now(),
                                   group=group)

    def membership_view(self) -> Optional[MembershipView]:
        """The nemesis's configuration snapshot; None = plane disabled
        (the default — membership kinds never enter the choice pool)."""
        return None

    def run_phases(self, nemesis: Nemesis) -> None:
        try:
            for phase_no in range(self.phases):
                self._invoke_idle()
                act = nemesis.next_action(
                    self.members(), self.alive_map(), self.partitioned,
                    self.now(), membership=self.membership_view(),
                )
                # blackbox progress mark (no-op without a journal): a
                # run killed externally mid-phase leaves WHICH phase and
                # which nemesis action it was executing in the journal
                blackbox.mark(
                    "torture_phase", phase_no=phase_no,
                    action=act.describe(),
                    t_virtual=round(self.now(), 3), ops=len(self.history),
                )
                self.apply_nemesis(act)
                # drive in slices so completions are stamped near the
                # event that produced them, not at phase granularity
                for _ in range(4):
                    self.pump_open_loop(self.phase_s / 4)
                    self.drive(self.phase_s / 4)
                    self.pump_membership()
                    self.pump_broken()
                    self._poll_all()
                    self._invoke_idle()
                if phase_no == 0:
                    self._freeze_compile_plane()
            blackbox.mark("quiesce", t_virtual=round(self.now(), 3),
                          ops=len(self.history), crashes=self.crashes)
            self.quiesce()
            self.history.close()
            obs = self.obs
            if (obs is not None and obs.memory is not None
                    and obs.memory.baseline is not None):
                # the flatness verdict must be taken NOW, while the
                # final engine generation is still alive — after the
                # run object dies the census only shows teardown
                obs.memory.final_drift = obs.memory.drift()
        finally:
            if self.obs is not None:
                self.obs.close()   # detach the process-global compile
                #                    hook; the stats stay readable

    def _freeze_compile_plane(self) -> None:
        """Warmup over (one full nemesis phase drove every program the
        run will steady-state on): pin the memory baseline and freeze
        the retrace sentinel — every later hot-path compile is a
        violation, every census drift a potential leak."""
        obs = self.obs
        if obs is None:
            return
        if obs.memory is not None:
            obs.memory.set_baseline()
        if obs.compile is not None and obs.compile.sentinel is not None:
            obs.compile.sentinel.freeze()
            blackbox.mark(
                "compile_sentinel_frozen",
                compiles=obs.compile.total_compiles,
            )


def torture_run(
    seed: int,
    phases: int = 12,
    clients: int = 3,
    keys: int = 4,
    phase_s: float = 30.0,
    cfg: Optional[RaftConfig] = None,
    workdir: Optional[str] = None,
    crash: bool = True,
    msg_faults: bool = True,
    storage_faults: bool = True,
    broken: Optional[str] = None,
    overload: bool = False,
    membership: bool = False,
    reads: bool = False,
    step_budget: int = 500_000,
    observe: bool = False,
    observe_device: bool = False,
    audit: bool = False,
    observe_compile: bool = False,
    bundle_dir: Optional[str] = None,
    blackbox_dir: Optional[str] = None,
) -> TortureReport:
    """One full single-engine torture run; see module docstring.
    ``observe_compile=True`` (or env ``RAFT_TPU_COMPILE_SENTINEL=1``)
    attaches the XLA compile-and-memory plane: every trace/compile is
    recorded per program label, the RetraceSentinel freezes after the
    warmup phase (any later hot-path compile is a typed violation), and
    the device-memory census baselines there (drift = leak candidate).
    Determinism-neutral like every other plane.
    ``audit=True`` attaches the ONLINE safety plane — the
    ``obs.audit.SafetyAuditor`` invariant checks plus the
    ``obs.slo.SloTracker`` latency/burn-rate plane (implies observe;
    determinism-neutral, pinned) — reachable afterwards as
    ``report.obs.audit`` / ``report.obs.slo``.
    ``overload=True`` arms admission (``_overload_cfg`` unless ``cfg``
    is given) and lets the nemesis open 2-10x open-loop arrival
    windows, composable with every other fault plane.
    ``membership=True`` arms the reconfiguration plane: a
    membership-headroom config (``_membership_cfg`` unless ``cfg`` is
    given) and nemesis grow/shrink/remove-the-leader/wipe-replace
    cycles, composed with every other plane — client-visible
    linearizability under reconfiguration is the property under test.
    ``observe=True`` attaches the observability plane (flight recorder,
    per-op spans, metrics registry — determinism-neutral, pinned);
    ``bundle_dir`` (or ``RAFT_TPU_BUNDLE_DIR``) arms forensics: a
    verdict other than LINEARIZABLE auto-writes a repro bundle that
    ``python -m raft_tpu.obs --explain`` reconstructs without
    re-running the seed. ``blackbox_dir`` (or ``RAFT_TPU_BLACKBOX_DIR``)
    arms the black-box progress journal (obs.blackbox): a per-process
    append-only file of phase marks — nemesis actions, crash-restore
    cycles, quiesce, the checker — that SURVIVES both engine crash
    cycles and an external kill of the harness itself."""
    base = _overload_cfg(seed) if overload else _default_cfg(seed)
    if membership and cfg is None:
        base = _membership_cfg(base)
    if reads and cfg is None:
        base = _reads_cfg(base)
    with blackbox.journal_for(f"torture_seed{seed}", blackbox_dir):
        blackbox.mark("torture_run", seed=seed, phases=phases,
                      clients=clients, keys=keys)
        run = _SingleTorture(
            seed, phases, clients, keys, phase_s,
            cfg or base, workdir, broken, membership=membership,
            reads=reads,
            observe=observe, observe_device=observe_device, audit=audit,
            observe_compile=observe_compile,
        )
        nemesis = Nemesis(
            seed, run.cfg.rows, allow_crash=crash, allow_msg=msg_faults,
            allow_storage=storage_faults, allow_overload=overload,
            allow_membership=membership,
            allow_clock=reads,
            clock_drift_bound=run.cfg.clock_drift_bound,
        )
        run.run_phases(nemesis)
        blackbox.mark("check_history", ops=len(run.history),
                      step_budget=step_budget)
        check = check_history(run.history, step_budget=step_budget)
        blackbox.mark("check_done", verdict=check.verdict)
    flags = []
    if not crash:
        flags.append("--no-crash")
    if not msg_faults:
        flags.append("--no-msg")
    if not storage_faults:
        flags.append("--no-storage")
    if broken:
        flags.append(f"--broken {broken}")
    if overload:
        flags.append("--overload")
    if membership:
        flags.append("--membership")
    if reads:
        flags.append("--read-plane")
    if audit:
        flags.append("--audit")
    if observe_compile:
        flags.append("--observe-compile")
    repro = (
        f"python -m raft_tpu.chaos --seed {seed} --phases {phases} "
        f"--clients {clients} --keys {keys} --phase-s {phase_s:g}"
        + ("".join(" " + f for f in flags))
    )
    bundle_path = _maybe_bundle(
        "torture", run, check, LINEARIZABLE, repro, nemesis.log, bundle_dir,
        extra={"crashes": run.crashes, "shed_ops": run.shed_ops,
               "open_loop_ops": run.ol_submitted,
               "membership_ops": run.membership_ops},
    )
    return TortureReport(
        seed=seed, check=check, ops=len(run.history),
        op_counts=run.history.counts(), crashes=run.crashes,
        msg_stats=run.chaos_t.stats, nemesis_log=nemesis.log, repro=repro,
        shed_ops=run.shed_ops, open_loop_ops=run.ol_submitted,
        membership_ops=run.membership_ops,
        commit_digest=run.commit_digest(), bundle_path=bundle_path,
        obs=run.obs,
    )


def _maybe_bundle(
    kind: str, run: "_TortureBase", check: CheckResult, expected: str,
    repro: str, nemesis_log: List[str], bundle_dir: Optional[str],
    extra: Optional[dict] = None, force_unexpected: bool = False,
) -> Optional[str]:
    """Forensics hook shared by every chaos entry point: when the run
    ended in anything but its expected verdict (or the runner flags the
    outcome unexpected for a non-verdict reason, e.g. a missed recovery
    window) and a bundle destination is configured, dump the repro
    bundle. Never raises into the run's own reporting path — a bundle
    that cannot be written (unwritable RAFT_TPU_BUNDLE_DIR, full disk)
    must not destroy the report it was meant to preserve."""
    bdir = resolve_bundle_dir(bundle_dir)
    if bdir is None or (check.verdict == expected and not force_unexpected):
        return None
    try:
        return write_bundle(
            bdir, kind=kind, seed=run.seed, expected=expected,
            verdict=check.verdict, detail=check.detail,
            violation_key=check.key, repro=repro, config=run.cfg,
            nemesis_log=nemesis_log, history=run.history, obs=run.obs,
            extra=extra,
        )
    except OSError as ex:
        import sys

        print(f"raft_tpu.obs: repro bundle not written to {bdir!r}: {ex}",
              file=sys.stderr)
        return None


class _SingleTorture(_TortureBase):
    def __init__(self, seed, phases, clients, keys, phase_s, cfg,
                 workdir, broken, membership: bool = False,
                 reads: bool = False,
                 observe: bool = False, observe_device: bool = False,
                 audit: bool = False, observe_compile: bool = False):
        super().__init__(seed, phases, clients, keys, phase_s,
                         observe=observe, observe_device=observe_device,
                         audit=audit, observe_compile=observe_compile)
        from raft_tpu.transport.device import SingleDeviceTransport

        self.cfg = cfg
        self.broken = broken
        self.membership = membership
        self.reads = reads or cfg.read_lease
        #   read scale-out plane: lease-class serves come from the
        #   harness's VERSIONED applied store at the index the engine
        #   returned (_value_at) — a stale leader's frozen commit view
        #   then really serves stale bytes, exactly as its local state
        #   machine would in a deployment; the shared in-process KV
        #   would otherwise mask the staleness the skew nemesis exists
        #   to produce.
        self._vidx: Dict[bytes, List[int]] = {}
        self._vval: Dict[bytes, List[Optional[bytes]]] = {}
        self._vmax = 0
        self.membership_ops: Dict[str, int] = {}
        self._wipe_rejoin: set = set()
        #   rows awaiting recovery after a wipe-replace: a wiped row must
        #   stay down until its old voter identity leaves the
        #   configuration (the engine's recover guard), then rejoins as
        #   a fresh learner
        self._tmp = None
        if workdir is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="raft_torture_")
            workdir = self._tmp.name
        self.store = MirroredStore(workdir, mirrors=2)
        self.storage_rng = random.Random(f"storage:{seed}")
        self.chaos_t = ChaosTransport(SingleDeviceTransport(cfg), seed)
        self._msg_params = None
        self.partitioned = False
        self._broken_rng = random.Random(f"broken:{seed}")
        #   the commit_rewind variant's own seeded stream (deterministic
        #   fault timing independent of the workload draws)
        self._boot_fresh()
        # dirty-read oracle for the broken variant: key -> last value
        # SUBMITTED (not committed) — exactly the cache a naive server
        # would serve reads from without waiting for consensus
        self._dirty: Dict[bytes, Optional[bytes]] = {}

    # -------------------------------------------------------------- boot
    def _boot_fresh(self) -> None:
        from raft_tpu.examples.kv import ReplicatedKV
        from raft_tpu.raft.engine import RaftEngine

        self.engine = RaftEngine(
            self.cfg, self.chaos_t, vote_log=self.store.votelog_path,
            recorder=self.obs.recorder if self.obs is not None else None,
        )
        if self.obs is not None:
            self.obs.attach(self.engine)
        if self.broken == "lease_skew" and self.engine.lease is not None:
            # the deliberately broken plane: drift bound ignored (a
            # deployment that assumed perfect clocks) — re-armed on
            # every boot so crash-restore cycles stay broken
            self.engine.lease.ignore_drift = True
        self.kv = ReplicatedKV(self.engine)
        self._register_audit_apply()
        self._register_version_feed()
        self.engine.run_until_leader()

    def _register_audit_apply(self) -> None:
        """With the online audit plane attached, feed every applied KV
        op to the auditor (value -> applied index per key — the lookup
        table the serve-side read audits consult). Registered AFTER the
        KV store so serve order matches apply order."""
        if self.obs is None or self.obs.audit is None:
            return
        from raft_tpu.examples.kv import decode_op

        auditor = self.obs.audit

        def _feed(idx: int, payload: bytes) -> None:
            op, key, value = decode_op(payload)
            if op:
                auditor.note_apply(key, idx, value)

        self.engine.register_apply(_feed)

    def _register_version_feed(self) -> None:
        """With the read plane armed, keep a per-key VERSIONED applied
        store (idx -> value lists): lease-class reads serve from it at
        the engine's returned index (_value_at). Idempotent on replay
        (committed idx -> value is stable across crash-restore), so one
        version map spans the whole run like the auditor."""
        if not self.reads:
            return
        from raft_tpu.examples.kv import decode_op

        def _feed(idx: int, payload: bytes) -> None:
            if idx <= self._vmax:
                return
            self._vmax = idx
            op, key, value = decode_op(payload)
            if op:
                self._vidx.setdefault(key, []).append(idx)
                self._vval.setdefault(key, []).append(value)

        self.engine.register_apply(_feed, replay=True)

    def _value_at(self, key: bytes, idx: int) -> Optional[bytes]:
        """The key's applied value as of log index ``idx`` — what a
        replica whose state machine stopped at ``idx`` would serve."""
        import bisect

        vi = self._vidx.get(key)
        if not vi:
            return None
        i = bisect.bisect_right(vi, idx)
        return self._vval[key][i - 1] if i else None

    def _restart(self) -> None:
        from raft_tpu.examples.kv import ReplicatedKV
        from raft_tpu.raft.engine import RaftEngine

        t0 = self.now()
        # write-before-block: the restore path replays checkpoints and
        # re-elects — if the process dies or wedges inside it, the
        # journal (which, being a per-process append-only file, SURVIVES
        # the engine's crash-restore cycle by construction) says so
        blackbox.mark("crash_restore", crashes=self.crashes,
                      t_virtual=round(t0, 3))
        path, _, _rejected = self.store.load_best()
        old_stats = self.chaos_t.stats
        self.chaos_t = ChaosTransport(
            self._fresh_base(), self.seed * 1000 + self.crashes
        )
        for k, v in old_stats.items():   # stats survive the restart
            self.chaos_t.stats[k] += v
        self.engine = RaftEngine.restore(
            self.cfg, path, self.chaos_t,
            vote_log=self.store.votelog_path,
            recorder=self.obs.recorder if self.obs is not None else None,
        )
        if self.obs is not None:
            self.obs.attach(self.engine)
            #   one recorder/span/metric plane spans crash-restore
            #   cycles: the ring keeps pre-crash events, the restored
            #   engine keeps appending
        # carry virtual time forward: a restart must not rewind the
        # history clock (heap entries armed below t0 simply fire "now")
        self.engine.clock.now = t0
        if self.broken == "lease_skew" and self.engine.lease is not None:
            self.engine.lease.ignore_drift = True
        self.kv = ReplicatedKV(self.engine, replay=True)
        self._register_audit_apply()
        self._register_version_feed()
        if self._msg_params is not None:
            self.chaos_t.set_message_faults(*self._msg_params)
        self.partitioned = False
        self.engine.run_until_leader()

    def _fresh_base(self):
        from raft_tpu.transport.device import SingleDeviceTransport

        return SingleDeviceTransport(self.cfg)

    # ----------------------------------------------------------- adapters
    def members(self) -> List[int]:
        return [r for r in range(self.cfg.rows) if self.engine.member[r]]

    def alive_map(self) -> Dict[int, bool]:
        return {r: bool(self.engine.alive[r]) for r in range(self.cfg.rows)}

    def now(self) -> float:
        return self.engine.clock.now

    def drive(self, seconds: float) -> None:
        self.engine.run_for(seconds)

    # ------------------------------------------------------ open loop
    @property
    def capacity_eps(self) -> float:
        """Measured ingest capacity (entries/s): a leader tick drains at
        most one batch, so batch_size per heartbeat_period is the most
        the cluster can commit sustained — the base the nemesis's 2-10x
        multipliers scale."""
        return self.cfg.batch_size / self.cfg.heartbeat_period

    def set_overload_rate(self, rate_mult: float) -> None:
        self._ol_rate = rate_mult * self.capacity_eps

    def pump_open_loop(self, dt: float) -> None:
        """Poisson(rate * dt) one-shot writers, each its own client id
        (fully concurrent — the open-loop model). A refusal resolves
        ``fail`` immediately: ``Overloaded`` raises before anything is
        queued, so no-effect is provable."""
        if self._ol_rate <= 0:
            return
        n = poisson(self._ol_rng, self._ol_rate * dt)
        for _ in range(n):
            self._ol_counter += 1
            self.ol_submitted += 1
            cid = 1000 + self._ol_counter
            key = self._ol_rng.choice(self.keys)
            value = f"ol{self._ol_counter}".encode()
            rec = self.history.invoke(cid, WRITE, key, value, self.now())
            try:
                with self._ambient_span(rec):
                    seq = self.kv.set(key, value, client=cid)
            except Overloaded:
                self.shed_ops += 1
                rec.fail(self.history.stamp(self.now()))
                continue
            self._ol_pending.append((rec, seq, self.now()))

    def _ol_durable(self, handle) -> bool:
        return self.engine.is_durable(handle)

    def commit_digest(self) -> str:
        # Composed from per-entry payload CRCs (idx : term : crc32 of
        # bytes) so the online auditor can reproduce the identical
        # digest from its own incremental records
        # (SafetyAuditor.commit_digest — the cross-check pinned by
        # tests/test_audit.py). Same coverage as before: the archive's
        # contiguous tail below the watermark.
        e = self.engine
        wm = int(e.commit_watermark)
        crc = zlib.crc32(f"wm:{wm}".encode())
        if wm:
            for idx in range(e.store.covered_lo(wm), wm + 1):
                ent = e.store.get(idx)
                if ent is not None:
                    crc = zlib.crc32(
                        f"{idx}:{ent[1]}:{zlib.crc32(ent[0]):08x}"
                        .encode(),
                        crc,
                    )
        return f"{crc:08x}"

    def pump_broken(self) -> None:
        """The broken-COMMIT variant (``broken="commit_rewind"``): a
        server whose storage layer silently loses acknowledged commits
        — the commit watermark rewinds by up to a batch and the rewound
        entries' durability stamps vanish, as if an fsync had lied.
        The device log is untouched, so the watermark re-advances on
        the next tick and applied state stays consistent: the OFFLINE
        checker usually cannot see this fault at all (no client-visible
        read serves the regression), which is exactly the
        falsifiability point — the ONLINE auditor's commit-monotonicity
        watermark must trip DURING the run
        (tests/test_audit.py::test_commit_rewind_trips_auditor_online)."""
        if self.broken != "commit_rewind":
            return
        e = self.engine
        if self._broken_rng.random() > 0.5 or e.commit_watermark < 4:
            return
        k = self._broken_rng.randint(1, min(self.cfg.batch_size,
                                            e.commit_watermark - 1))
        e.commit_watermark -= k
        # the "lost" acks: drop the newest k durability stamps (dict
        # order is stamp order) — the durability API now denies entries
        # it already acknowledged, the broken half the auditor flags
        for seq in list(e.commit_time)[-k:]:
            del e.commit_time[seq]

    def invoke(self, cl: _Client) -> None:
        from raft_tpu.raft.engine import LinearizableReadRefused

        op, key, value = cl.pick()
        if op == READ:
            cl.rec = self.history.invoke(cl.cid, READ, key, None, self.now())
            if self.broken == "dirty_reads":
                # deliberately broken: no leadership confirmation, no
                # apply wait — half the reads serve the latest SUBMITTED
                # (possibly uncommitted) value, half the applied state.
                # A dirty read of an in-flight write followed by an
                # applied read of the same key before it commits (or a
                # crash that loses it) is the unjustifiable pair the
                # checker must reject.
                if cl.rng.random() < 0.5 and key in self._dirty:
                    value = self._dirty[key]
                else:
                    value = self.kv.get(key)
                self._audit_read(cl.cid, key, value)
                cl.rec.ok(self.history.stamp(self.now()), value)
                cl.rec = None
                return
            try:
                with self._ambient_span(cl.rec):
                    cl.ticket = self.engine.submit_read()
                cl.rec.read_class = self.engine.read_ticket_class(
                    cl.ticket
                )
                #   the served class (lease = zero-round local serve,
                #   read_index = quorum-confirmed) rides the OpRecord so
                #   the checker can grade each class against its own
                #   model (chaos.checker.check_read_classes)
            except (LinearizableReadRefused, Overloaded):
                # refused before any effect (read-lane admission refuses
                # before minting a ticket)
                cl.rec.fail(self.history.stamp(self.now()))
                cl.rec, cl.ticket = None, None
            return
        cl.rec = self.history.invoke(cl.cid, op, key, value, self.now())
        try:
            with self._ambient_span(cl.rec):
                cl.seq = (
                    self.kv.set(key, value, client=cl.cid) if op == WRITE
                    else self.kv.delete(key, client=cl.cid)
                )
        except Overloaded:
            # shed before queueing: provably no effect
            self.shed_ops += 1
            cl.rec.fail(self.history.stamp(self.now()))
            cl.rec, cl.seq = None, None
            return
        self._dirty[key] = value if op == WRITE else None

    def poll(self, cl: _Client) -> None:
        from raft_tpu.raft.engine import LinearizableReadRefused

        if self._give_up(cl):
            return
        rec = cl.rec
        if rec.op == READ:
            if isinstance(cl.ticket, tuple):
                idx = cl.ticket[1]     # confirmed, waiting on the apply
            else:
                try:
                    idx = self.engine.read_confirmed(cl.ticket)
                except LinearizableReadRefused:
                    rec.fail(self.history.stamp(self.now()))
                    cl.rec, cl.ticket = None, None
                    return
                if idx is None:
                    return
                # confirmed; tickets are poll-once, so note the bound —
                # the value may only serve once applied state covers it
                cl.ticket = ("applied", idx)
            if self.kv.last_applied < idx:
                return
            if self.reads and getattr(rec, "read_class", None) == "lease":
                # a lease serve reads the LEADER'S OWN applied view at
                # the index its lease certified — the versioned store
                # makes a stale frozen index really serve stale bytes
                # (see __init__; this is the skew nemesis's teeth)
                value = self._value_at(rec.key, idx)
            else:
                value = self.kv.get(rec.key)
            self._audit_read(cl.cid, rec.key, value)
            rec.ok(self.history.stamp(self.now()), value)
            cl.rec, cl.ticket = None, None
            return
        if self.engine.is_durable(cl.seq):
            rec.ok(self.history.stamp(self.now()))
            cl.rec, cl.seq = None, None

    def apply_nemesis(self, act: NemesisAction) -> None:
        e = self.engine
        if act.kind == "kill":
            e.fail(act.replica)
        elif act.kind == "recover":
            e.recover(act.replica)
        elif act.kind == "slow":
            e.set_slow(act.replica, True)
        elif act.kind == "unslow":
            e.set_slow(act.replica, False)
        elif act.kind == "campaign":
            e.force_campaign(act.replica)
        elif act.kind == "partition":
            e.partition(act.groups)
            self.partitioned = True
        elif act.kind == "heal":
            e.heal_partition()
            self.partitioned = False
        elif act.kind == "plan":
            e.schedule_faults(act.plan)
        elif act.kind == "msg_on":
            self._msg_params = (act.drop, act.dup, act.delay)
            self.chaos_t.set_message_faults(*self._msg_params)
        elif act.kind == "msg_off":
            self._msg_params = None
            self.chaos_t.clear_message_faults()
        elif act.kind == "crash_restart":
            self._crash_restart(act.storage)
        elif act.kind == "overload_on":
            self.set_overload_rate(act.rate_mult)
        elif act.kind == "overload_off":
            self._ol_rate = 0.0
        elif act.kind == "skew_on":
            e.set_lease_rate(act.replica, act.rate)
        elif act.kind == "skew_off":
            e.set_lease_rate(act.replica, 1.0)
        elif act.kind == "mem_grow":
            self._mem_op("grow", lambda: e.add_server(act.replica))
        elif act.kind == "mem_shrink":
            self._mem_op("shrink", lambda: e.remove_server(act.replica))
        elif act.kind == "mem_remove_leader":
            lead = e.leader_id
            if lead is not None and e.member[lead]:
                self._mem_op(
                    "remove_leader", lambda: e.remove_server(lead)
                )
        elif act.kind == "mem_replace":
            self._mem_replace(act.replica, act.spare)

    # -------------------------------------------------- membership plane
    def membership_view(self) -> Optional[MembershipView]:
        if not self.membership:
            return None
        e = self.engine
        rows = range(self.cfg.rows)
        return MembershipView(
            voters=[r for r in rows if e.member[r]],
            learners=[r for r in rows if e.learner[r]],
            spares=[
                r for r in rows if not e.member[r] and not e.learner[r]
            ],
            leader=e.leader_id,
            in_flight=(
                e._pending_config is not None
                or bool(e._staged_config)
                or any(q in e._config_seqs for q, _ in e._queue)
            ),
        )

    def _mem_op(self, name: str, fn) -> bool:
        """Run one reconfiguration op; an engine refusal (leadership
        gap, change already in flight, admission shedding under an
        overload window) is a logged no-op — the nemesis gates on a
        snapshot that may have gone stale by apply time."""
        try:
            fn()
        except (RuntimeError, ValueError, Overloaded):
            return False
        self.membership_ops[name] = self.membership_ops.get(name, 0) + 1
        return True

    def _mem_replace(self, victim: int, spare: int) -> None:
        """The wipe-replace cycle: crash the victim if needed, start the
        replace ladder (removal now, learner re-admission + promotion
        staged behind it), and only once the ladder is ACCEPTED destroy
        the victim's durable state in full (device row + checkpoint
        mirrors + vote WAL). Ordering matters: replace() can be refused
        (leadership gap, admission shedding under a composed overload
        window), and wiping first would strand a wiped, still-configured
        voter that nothing may ever restart. A refusal therefore leaves
        an ordinary crashed — recoverable — member behind. The rejoining
        row stays down until its old identity durably leaves the
        configuration (``pump_membership`` recovers it)."""
        e = self.engine
        if not e.member[victim]:
            return
        if e.alive[victim]:
            e.fail(victim)
        if self._mem_op("replace", lambda: e.replace(victim, spare)):
            e.wipe(victim)
            self.store.wipe_node(victim)
            self._wipe_rejoin.add(spare)
            if spare != victim:
                self._wipe_rejoin.add(victim)
                #   the removed row itself restarts as an unconfigured
                #   spare once its removal commits — future grows may
                #   re-admit it

    def pump_membership(self) -> None:
        if not self._wipe_rejoin:
            return
        e = self.engine
        for v in list(self._wipe_rejoin):
            if e.alive[v]:
                self._wipe_rejoin.discard(v)
            elif not e.member[v]:
                # the old voter identity has left the configuration:
                # the row may now restart (fresh learner rejoin)
                e.recover(v)
                if e.alive[v]:
                    self._wipe_rejoin.discard(v)

    def _crash_restart(self, storage: str) -> None:
        # resolve in-flight ops against the dying engine: writes may
        # have committed unobserved (info — both worlds stay open);
        # reads never returned (fail — no effect to account for)
        for cl in self.clients:
            if cl.rec is None:
                continue
            if cl.rec.op == READ:
                cl.rec.fail(self.history.stamp(self.now()))
            else:
                cl.rec.info()
            cl.rec, cl.ticket, cl.seq = None, None, None
        self._resolve_open_loop_info()
        self.store.save(self.engine)
        if storage == "tear_votelog":
            self.store.tear_votelog(self.storage_rng)
        elif storage == "flip_bit":
            self.store.flip_bit(
                self.storage_rng.randrange(self.store.mirrors),
                self.storage_rng,
            )
        elif storage == "rollback":
            self.store.rollback(
                self.storage_rng.randrange(self.store.mirrors)
            )
        self.crashes += 1
        self._restart()

    def quiesce(self) -> None:
        """Heal every fault plane, then resolve all outstanding ops."""
        e = self.engine
        self._ol_rate = 0.0        # overload window ends with the run
        self._msg_params = None
        self.chaos_t.clear_message_faults()
        e.heal_partition()
        self.partitioned = False
        self.pump_membership()   # wiped rows that may legally restart, do
        for r in range(self.cfg.rows):
            if (e.member[r] or e.learner[r]) and not e.alive[r]:
                # recover() quietly refuses wiped still-configured voters
                # (their replace ladder may not have committed); the
                # quorum-liveness gating guarantees a live voter
                # majority without them, so the probe below still lands
                e.recover(r)
            e.set_slow(r, False)
        probe = None
        for _ in range(200):
            try:
                probe = e.submit(bytes(self.cfg.entry_bytes))
                break
            except Overloaded:
                # the gate is still draining the overload backlog; give
                # it ticks — arrivals have stopped, so depth and delay
                # both fall monotonically from here
                e.run_for(2 * self.cfg.heartbeat_period)
                self._poll_all()
        assert probe is not None, "admission never re-opened at quiesce"
        e.run_until_committed(probe, limit=3000.0)
        for _ in range(40):
            self._poll_all()
            if all(cl.rec is None for cl in self.clients):
                break
            e.run_for(4 * self.cfg.heartbeat_period)
        # anything still unresolved closes as info/fail via History.close
        for cl in self.clients:
            if cl.rec is not None and cl.rec.op == READ:
                cl.rec.fail(self.history.stamp(self.now()))
                cl.rec, cl.ticket = None, None


def torture_run_multi(
    seed: int,
    n_groups: int = 4,
    phases: int = 10,
    clients: int = 3,
    keys: int = 6,
    phase_s: float = 30.0,
    cfg: Optional[RaftConfig] = None,
    overload: bool = False,
    step_budget: int = 500_000,
    observe: bool = False,
    observe_device: bool = False,
    audit: bool = False,
    observe_compile: bool = False,
    bundle_dir: Optional[str] = None,
    blackbox_dir: Optional[str] = None,
) -> TortureReport:
    """Multi-Raft torture: the sharded Router/ShardedKV client surface
    under per-group process faults. No crash cycles or message faults —
    ``MultiEngine`` has no checkpoint/restore or pluggable transport yet
    (its module docstring scopes both); per-key histories across groups
    are the point: the Router must keep every key's subhistory
    linearizable while sibling groups fail independently.
    ``overload=True`` arms the per-group queue bounds and lets the
    nemesis open open-loop arrival windows routed through a no-retry
    Router (shed = ``fail``, same soundness argument as the single
    engine)."""
    with blackbox.journal_for(f"torture_multi_seed{seed}", blackbox_dir):
        blackbox.mark("torture_run_multi", seed=seed, n_groups=n_groups,
                      phases=phases)
        run = _MultiTorture(
            seed, phases, clients, keys, phase_s, cfg, n_groups,
            overload=overload, observe=observe,
            observe_device=observe_device, audit=audit,
            observe_compile=observe_compile,
        )
        nemesis = Nemesis(
            seed, run.cfg.n_replicas, allow_crash=False, allow_msg=False,
            allow_storage=False, allow_overload=overload,
        )
        run.run_phases(nemesis)
        blackbox.mark("check_history", ops=len(run.history))
        check = check_history(run.history, step_budget=step_budget)
        blackbox.mark("check_done", verdict=check.verdict)
    repro = (
        f"python -m raft_tpu.chaos --seed {seed} --multi "
        f"--groups {n_groups} --phases {phases} --clients {clients} "
        f"--keys {keys} --phase-s {phase_s:g}"
        + (" --overload" if overload else "")
    )
    bundle_path = _maybe_bundle(
        "torture_multi", run, check, LINEARIZABLE, repro, nemesis.log,
        bundle_dir,
        extra={"n_groups": n_groups, "shed_ops": run.shed_ops,
               "open_loop_ops": run.ol_submitted},
    )
    return TortureReport(
        seed=seed, check=check, ops=len(run.history),
        op_counts=run.history.counts(), crashes=0,
        msg_stats={}, nemesis_log=nemesis.log, repro=repro,
        shed_ops=run.shed_ops, open_loop_ops=run.ol_submitted,
        commit_digest=run.commit_digest(), bundle_path=bundle_path,
        obs=run.obs,
    )


class _MultiTorture(_TortureBase):
    def __init__(self, seed, phases, clients, keys, phase_s, cfg, n_groups,
                 overload: bool = False, observe: bool = False,
                 observe_device: bool = False, audit: bool = False,
                 observe_compile: bool = False):
        super().__init__(seed, phases, clients, keys, phase_s,
                         observe=observe, observe_device=observe_device,
                         audit=audit, observe_compile=observe_compile)
        from raft_tpu.examples.kv_sharded import ShardedKV
        from raft_tpu.multi.engine import MultiEngine
        from raft_tpu.multi.router import Router

        self.cfg = cfg or RaftConfig(
            n_replicas=3, entry_bytes=32, batch_size=4, log_capacity=128,
            transport="single", seed=seed,
            admission_max_writes=(16 if overload else None),
        )
        obs = self.obs
        self.engine = MultiEngine(
            self.cfg, n_groups,
            recorder=obs.recorder if obs is not None else None,
        )
        if obs is not None:
            self.engine.metrics = obs.registry
            if obs.audit is not None:
                self.engine.auditor = obs.audit
                self.engine.slo = obs.slo
            if obs.device is not None:
                self.engine.attach_device_obs(obs.device)
            if obs.memory is not None:
                # the multi path wires the stack by hand (no
                # ObsStack.attach); the memory census still needs its
                # roots or every engine buffer reads as a leak
                obs.memory.watch_engine(self.engine, name="multi")
        self.engine.seed_leaders()
        spans = obs.spans if obs is not None else None
        self.router = Router(self.engine, spans=spans)
        self._ol_router = Router(self.engine, max_retries=0, spans=spans)
        #   open-loop arrivals do not retry: a refused one-shot writer
        #   is SHED (fail, no effect) — retrying it would re-close the
        #   loop the overload model exists to open
        self.kv = ShardedKV(self.engine, self.router)
        if obs is not None and obs.audit is not None:
            from raft_tpu.examples.kv import decode_op

            auditor = obs.audit

            def _make_feed(g: int):
                def _feed(idx: int, payload: bytes) -> None:
                    op, key, value = decode_op(payload)
                    if op:
                        auditor.note_apply(key, idx, value, group=g)
                return _feed

            for g in range(self.engine.G):
                self.engine.register_apply(g, _make_feed(g))
        self.partitioned = False
        self._part_group: Optional[int] = None
        self.nem_rng = random.Random(f"multi-nemesis:{seed}")

    def members(self) -> List[int]:
        return list(range(self.cfg.n_replicas))

    def alive_map(self) -> Dict[int, bool]:
        # a replica counts as dead for the kill gate if ANY group lost
        # it (faults below are applied per-group or globally)
        return {
            r: bool(self.engine.alive[:, r].all())
            for r in range(self.cfg.n_replicas)
        }

    def now(self) -> float:
        return self.engine.clock.now

    def drive(self, seconds: float) -> None:
        self.engine.run_for(seconds)

    # ------------------------------------------------------ open loop
    @property
    def capacity_eps(self) -> float:
        """Aggregate measured ingest capacity over the groups the key
        set actually routes to (each group drains at most one batch per
        tick)."""
        covered = len({self.router.group_of(k) for k in self.keys})
        return covered * self.cfg.batch_size / self.cfg.heartbeat_period

    def set_overload_rate(self, rate_mult: float) -> None:
        self._ol_rate = rate_mult * self.capacity_eps

    def pump_open_loop(self, dt: float) -> None:
        from raft_tpu.examples.kv import _SET, encode_op
        from raft_tpu.multi.engine import NotLeader

        if self._ol_rate <= 0:
            return
        n = poisson(self._ol_rng, self._ol_rate * dt)
        for _ in range(n):
            self._ol_counter += 1
            self.ol_submitted += 1
            cid = 1000 + self._ol_counter
            key = self._ol_rng.choice(self.keys)
            value = f"ol{self._ol_counter}".encode()
            rec = self.history.invoke(cid, WRITE, key, value, self.now())
            try:
                with self._ambient_span(rec):
                    handle = self._ol_router.submit(
                        key,
                        encode_op(self.cfg.entry_bytes, _SET, key, value),
                    )
            except Overloaded:
                self.shed_ops += 1
                rec.fail(self.history.stamp(self.now()))
                continue
            except NotLeader:
                # leadership gap, not admission — still provably no
                # effect (refused before queueing), still a clean fail
                rec.fail(self.history.stamp(self.now()))
                continue
            self._ol_pending.append((rec, handle, self.now()))

    def _ol_durable(self, handle) -> bool:
        g, seq = handle
        return self.engine.is_durable(g, seq)

    def commit_digest(self) -> str:
        return multi_commit_digest(self.engine)

    def invoke(self, cl: _Client) -> None:
        from raft_tpu.multi.engine import NotLeader

        op, key, value = cl.pick()
        cl.rec = self.history.invoke(cl.cid, op, key, value, self.now())
        try:
            if op == READ:
                with self._ambient_span(cl.rec):
                    g, idx = self.router.read_index(key)
                if self.kv.last_applied[g] < idx:
                    self.drive(2 * self.cfg.heartbeat_period)
                if self.kv.last_applied[g] < idx:
                    cl.rec.fail(self.history.stamp(self.now()))   # apply lag: no value served
                else:
                    value = self.kv.get(key)
                    self._audit_read(cl.cid, key, value, group=g)
                    cl.rec.ok(self.history.stamp(self.now()), value)
                cl.rec = None
                return
            with self._ambient_span(cl.rec):
                cl.seq = (
                    self.kv.set(key, value) if op == WRITE
                    else self.kv.delete(key)
                )
        except (NotLeader, Overloaded) as ex:
            # nothing was queued (submit_to_leader refuses before
            # queueing; read_index confirms nothing; admission and the
            # router's breaker refuse before any effect): provably no
            # effect
            if isinstance(ex, Overloaded):
                self.shed_ops += 1
            cl.rec.fail(self.history.stamp(self.now()))
            cl.rec, cl.seq = None, None

    def poll(self, cl: _Client) -> None:
        if cl.rec is None or cl.rec.op == READ:
            return
        if self._give_up(cl):
            return
        g, seq = cl.seq
        if self.engine.is_durable(g, seq):
            cl.rec.ok(self.history.stamp(self.now()))
            cl.rec, cl.seq = None, None

    def apply_nemesis(self, act: NemesisAction) -> None:
        e = self.engine
        rng = self.nem_rng
        g = rng.randrange(e.G)
        if act.kind == "kill":
            e.fail(g, act.replica)
        elif act.kind == "recover":
            for gg in range(e.G):
                if not e.alive[gg, act.replica]:
                    e.recover(gg, act.replica)
        elif act.kind == "slow":
            e.set_slow(g, act.replica, True)
        elif act.kind == "unslow":
            for gg in range(e.G):
                e.set_slow(gg, act.replica, False)
        elif act.kind == "campaign":
            e.force_campaign(g, act.replica)
        elif act.kind == "partition":
            self._part_group = g
            e.partition(g, act.groups)
            self.partitioned = True
        elif act.kind == "heal":
            if self._part_group is not None:
                e.heal_partition(self._part_group)
            self._part_group = None
            self.partitioned = False
        elif act.kind == "plan":
            # scope the classic fragment to one group (the multi-Raft
            # FaultEvent.group field)
            from raft_tpu.faults.plan import FaultPlan

            e.schedule_faults(FaultPlan([
                dataclasses.replace(ev, group=g) for ev in act.plan.events
            ]))
        elif act.kind == "overload_on":
            self.set_overload_rate(act.rate_mult)
        elif act.kind == "overload_off":
            self._ol_rate = 0.0

    def quiesce(self) -> None:
        e = self.engine
        self._ol_rate = 0.0
        for g in range(e.G):
            e.heal_partition(g)
            for r in range(self.cfg.n_replicas):
                if not e.alive[g, r]:
                    e.recover(g, r)
                e.set_slow(g, r, False)
        self.partitioned = False
        for g in range(e.G):
            e.run_until_leader(g, limit=3000.0)
        for _ in range(40):
            self._poll_all()
            if all(cl.rec is None for cl in self.clients):
                break
            e.run_for(4 * self.cfg.heartbeat_period)


# ---------------------------------------------------- overload recovery
@dataclasses.dataclass
class OverloadReport:
    """One seeded overload-and-recover scenario (``overload_run``):
    baseline -> open-loop storm at ``rate_mult`` x capacity -> arrivals
    subside -> recovery. The anti-metastability property is
    ``recovery_ok``: goodput back to >= ``recover_frac`` of the
    pre-overload baseline, with the delay controller quiet, within
    ``recovery_window_s`` virtual seconds of the storm ending — plus
    the safety half: the host queue never exceeded its bound and the
    client history (shed ops recorded as no-effect failures) checked
    linearizable."""

    seed: int
    rate_mult: float
    capacity_eps: float
    baseline_goodput: float          # committed entries/s, pre-storm
    overload_goodput: float          # committed entries/s, during
    recovery_goodput: float          # rolling goodput at recovery detect
    shed: Dict[str, int]             # gate refusals by reason
    admitted: Dict[str, int]
    open_loop_ops: int
    depth_bound: int
    depth_high_water: int            # gate-observed arrival-time max
    queue_depth_max: int             # directly sampled queue depth max
    queue_delay_p99_overload_s: float
    queue_delay_p99_recovery_s: float
    recovered_in_s: Optional[float]  # None = never within the window
    recovery_window_s: float
    recovery_ok: bool
    check: CheckResult
    ops: int
    op_counts: Dict[str, int]
    repro: str
    bundle_path: Optional[str] = None   # forensics (obs.forensics)
    obs: Optional[ObsStack] = None

    @property
    def verdict(self) -> str:
        return self.check.verdict

    def summary(self) -> str:
        rec = ("never" if self.recovered_in_s is None
               else f"{self.recovered_in_s:.0f}s")
        return (
            f"seed {self.seed} x{self.rate_mult:g}: {self.verdict}, "
            f"goodput {self.baseline_goodput:.2f}->"
            f"{self.overload_goodput:.2f}->{self.recovery_goodput:.2f} e/s, "
            f"shed {sum(self.shed.values())}/{self.open_loop_ops}, "
            f"depth max {self.queue_depth_max}/{self.depth_bound}, "
            f"recovered in {rec} (window {self.recovery_window_s:g}s)"
        )


def overload_run(
    seed: int, *args, blackbox_dir: Optional[str] = None, **kwargs,
) -> OverloadReport:
    """Journaled front door for :func:`_overload_run_impl` — the impl's
    signature and defaults are the single source of truth (everything
    but ``blackbox_dir`` forwards verbatim; see its docstring for the
    scenario). ``blackbox_dir`` / ``RAFT_TPU_BLACKBOX_DIR`` arms the
    progress journal like the other chaos entry points."""
    with blackbox.journal_for(f"overload_seed{seed}", blackbox_dir):
        blackbox.mark("overload_run", seed=seed)
        return _overload_run_impl(seed, *args, **kwargs)


def _overload_run_impl(
    seed: int,
    rate_mult: float = 5.0,
    baseline_s: float = 120.0,
    overload_s: float = 180.0,
    recovery_window_s: float = 300.0,
    recover_frac: float = 0.9,
    cfg: Optional[RaftConfig] = None,
    step_budget: int = 500_000,
    observe: bool = False,
    bundle_dir: Optional[str] = None,
) -> OverloadReport:
    """The deterministic overload scenario behind the acceptance
    criterion (no composed process faults — ``torture_run(overload=
    True)`` composes; this run isolates the admission story so the
    recovery assertion is crisp):

    1. *Baseline*: closed-loop clients plus a polite open-loop trickle
       at half capacity; measure goodput (committed entries/s).
    2. *Storm*: open-loop Poisson arrivals at ``rate_mult`` x measured
       capacity for ``overload_s``. The queue must never exceed its
       bound; excess arrivals shed as typed no-effect refusals.
    3. *Recovery*: arrivals drop back to the trickle. Goodput must
       return to >= ``recover_frac`` of baseline — with the delay
       controller out of its shedding state — within
       ``recovery_window_s`` (the documented recovery window,
       docs/OVERLOAD.md). A system with queues allowed to grow
       unboundedly fails exactly this: it keeps paying the backlog long
       after the storm (the metastable signature).

    The client history (closed-loop ops + every open-loop arrival, shed
    ones as ``fail``) goes through the linearizability checker like any
    torture run.
    """
    run = _SingleTorture(
        seed, 0, 2, 3, 30.0,
        cfg or _overload_cfg(seed), None, None, observe=observe,
    )
    e = run.engine
    gate = e.admission
    assert gate is not None, "overload_run needs admission configured"
    base_rate = 0.5 * run.capacity_eps
    slice_s = 2 * run.cfg.heartbeat_period
    depth_max = 0

    def window(seconds: float, rate: float) -> None:
        nonlocal depth_max
        run._ol_rate = rate
        t_end = run.now() + seconds
        while run.now() < t_end:
            run._invoke_idle()
            run.pump_open_loop(slice_s)
            depth_max = max(depth_max, len(e._queue))
            run.drive(slice_s)
            depth_max = max(depth_max, len(e._queue))
            run._poll_all()

    def commits_in(t0: float, t1: float) -> int:
        return sum(1 for t in e.commit_time.values() if t0 < t <= t1)

    # 1. baseline ------------------------------------------------------
    def delay_mark() -> int:
        # CUMULATIVE sample index: stable across the gate's buffer trim
        # (delay_samples drops its older half past MAX_DELAY_SAMPLES)
        return gate.delay_dropped + len(gate.delay_samples)

    t0 = run.now()
    window(baseline_s, base_rate)
    t1 = run.now()
    baseline_goodput = commits_in(t0, t1) / (t1 - t0)
    delay_mark_base = delay_mark()

    # 2. storm ---------------------------------------------------------
    window(overload_s, rate_mult * run.capacity_eps)
    t2 = run.now()
    overload_goodput = commits_in(t1, t2) / (t2 - t1)
    delay_mark_storm = delay_mark()

    # 3. recovery ------------------------------------------------------
    run._ol_rate = base_rate
    roll_s = min(60.0, recovery_window_s / 2)
    recovered_in = None
    recovery_goodput = 0.0
    while run.now() < t2 + recovery_window_s:
        window(slice_s, base_rate)
        now = run.now()
        rolling = commits_in(now - roll_s, now) / roll_s
        head_delay = 0.0
        if e._queue:
            head_delay = now - e.submit_time.get(e._queue[0][0], now)
        if (now - t2 >= roll_s
                and rolling >= recover_frac * baseline_goodput
                and not gate.shedding
                and head_delay < gate.target_delay_s):
            recovered_in = now - t2
            recovery_goodput = rolling
            break
    if recovered_in is None:
        now = run.now()
        recovery_goodput = commits_in(now - roll_s, now) / roll_s
    def delay_p99(lo: int, hi: int) -> float:
        # cumulative marks -> current buffer offsets; samples trimmed
        # away mid-phase just shrink the slice (the retained half is
        # the recent one, which is the regime the percentile reports)
        lo = max(0, lo - gate.delay_dropped)
        hi = max(0, hi - gate.delay_dropped)
        window = gate.delay_samples[lo:hi]
        if not window:
            return float("nan")
        import numpy as np

        return float(np.percentile(window, 99))

    q_p99_storm = delay_p99(delay_mark_base, delay_mark_storm)
    q_p99_rec = delay_p99(delay_mark_storm, delay_mark())

    run._ol_rate = 0.0
    run.quiesce()
    run.history.close()
    check = check_history(run.history, step_budget=step_budget)
    report = gate.report(queue_depth=len(e._queue))
    repro = (f"python -m raft_tpu.chaos --seed {seed} "
             f"--overload-recovery {rate_mult:g}")
    bundle_path = _maybe_bundle(
        "overload", run, check, LINEARIZABLE, repro, [], bundle_dir,
        extra={"rate_mult": rate_mult, "recovered_in_s": recovered_in,
               "recovery_window_s": recovery_window_s,
               "shed": report.shed},
        force_unexpected=recovered_in is None,
        #   a missed recovery window is an unexpected outcome even when
        #   the history itself checks LINEARIZABLE — bundle it too
    )
    return OverloadReport(
        seed=seed, rate_mult=rate_mult, capacity_eps=run.capacity_eps,
        baseline_goodput=baseline_goodput,
        overload_goodput=overload_goodput,
        recovery_goodput=recovery_goodput,
        shed=report.shed, admitted=report.admitted,
        open_loop_ops=run.ol_submitted,
        depth_bound=gate.max_writes,
        depth_high_water=report.depth_high_water,
        queue_depth_max=depth_max,
        queue_delay_p99_overload_s=q_p99_storm,
        queue_delay_p99_recovery_s=q_p99_rec,
        recovered_in_s=recovered_in,
        recovery_window_s=recovery_window_s,
        recovery_ok=recovered_in is not None,
        check=check, ops=len(run.history),
        op_counts=run.history.counts(),
        repro=repro, bundle_path=bundle_path, obs=run.obs,
    )


# ------------------------------------------------- reconfiguration drill
@dataclasses.dataclass
class ReconfigReport:
    """One seeded deterministic reconfiguration drill (``reconfig_run``):
    grow twice through the learner phase, shrink, remove the leader,
    then wipe-replace a voter — with closed-loop client traffic flowing
    throughout. Two properties are asserted on top of the history
    verdict:

    - **availability**: after every configuration commit, a fresh write
      commits within ``availability_window_s`` VIRTUAL seconds (the
      documented resume window, docs/MEMBERSHIP.md) — ``events`` carries
      each op's measured resume time and ``availability_ok`` the
      conjunction;
    - **learner catch-up**: ``promote_s`` (fresh join) and
      ``replace_promote_s`` (rejoin-from-nothing after total durable
      loss) measure attach -> voter on the virtual clock.
    """

    seed: int
    check: CheckResult
    ops: int
    op_counts: Dict[str, int]
    events: List[dict]              # {op, t, resume_s, ok}
    promote_s: Optional[float]
    replace_promote_s: Optional[float]
    availability_window_s: float
    availability_ok: bool
    repro: str
    bundle_path: Optional[str] = None   # forensics (obs.forensics)
    obs: Optional[ObsStack] = None

    @property
    def verdict(self) -> str:
        return self.check.verdict

    def summary(self) -> str:
        evs = ", ".join(
            f"{ev['op']}:{ev['resume_s']:.0f}s" if ev["ok"]
            else f"{ev['op']}:STALLED" for ev in self.events
        )
        line = (
            f"seed {self.seed}: {self.verdict} over {self.ops} ops, "
            f"resume [{evs}] (window {self.availability_window_s:g}s), "
            f"promote {self.promote_s:.0f}s, "
            f"wipe-replace promote {self.replace_promote_s:.0f}s"
            if self.promote_s is not None
            and self.replace_promote_s is not None
            else f"seed {self.seed}: {self.verdict}, drill incomplete"
        )
        if self.verdict != LINEARIZABLE or not self.availability_ok:
            line += f"\n  REPRO: {self.repro}"
        return line


def reconfig_run(
    seed: int, *args, blackbox_dir: Optional[str] = None, **kwargs,
) -> ReconfigReport:
    """Journaled front door for :func:`_reconfig_run_impl` — the impl's
    signature and defaults are the single source of truth (everything
    but ``blackbox_dir`` forwards verbatim; see its docstring for the
    drill). ``blackbox_dir`` / ``RAFT_TPU_BLACKBOX_DIR`` arms the
    progress journal like the other chaos entry points."""
    with blackbox.journal_for(f"reconfig_seed{seed}", blackbox_dir):
        blackbox.mark("reconfig_run", seed=seed)
        return _reconfig_run_impl(seed, *args, **kwargs)


def _reconfig_run_impl(
    seed: int,
    availability_window_s: float = 120.0,
    catchup_limit_s: float = 900.0,
    cfg: Optional[RaftConfig] = None,
    step_budget: int = 500_000,
    observe: bool = False,
    bundle_dir: Optional[str] = None,
) -> ReconfigReport:
    """The deterministic reconfiguration scenario behind the acceptance
    criteria (no random nemesis — ``torture_run(membership=True)``
    composes; this run isolates the membership story so the
    availability assertion is crisp):

    1. *Grow 3 -> 4 -> 5*, learner-first: each ``add_server`` attaches a
       non-voting learner, heals it, auto-promotes at the lag bound.
    2. *Shrink 5 -> 4*: remove a non-leader voter.
    3. *Remove the leader*: the removed leader keeps serving until the
       entry commits, steps down, and the survivors elect (§4.2.2).
    4. *Wipe-replace*: crash a voter, destroy its durable state
       entirely (device row + mirrors + vote WAL), and ``replace`` it —
       removal, learner re-admission of the wiped row under a fresh
       identity, snapshot-install catch-up, promotion.

    After every configuration commit a probe write must commit within
    ``availability_window_s`` virtual seconds: reconfiguration is
    supposed to be something the cluster serves traffic THROUGH, not
    around.
    """
    run = _SingleTorture(
        seed, 0, 2, 3, 30.0,
        cfg or _membership_cfg(_default_cfg(seed)), None, None,
        membership=True, observe=observe,
    )
    e = run.engine
    slice_s = 2 * run.cfg.heartbeat_period
    events: List[dict] = []

    def drive(seconds: float) -> None:
        t_end = run.now() + seconds
        while run.now() < t_end:
            run._invoke_idle()
            run.drive(slice_s)
            run.pump_membership()
            run._poll_all()

    def probe_resume(op: str) -> None:
        """A config entry just committed: commit progress must resume
        inside the window."""
        t0 = run.now()
        seq = e.submit(bytes(run.cfg.entry_bytes))
        end = t0 + availability_window_s
        while not e.is_durable(seq) and run.now() < end and e._q:
            e.step_event()
        ok = e.is_durable(seq)
        events.append({
            "op": op, "t": t0,
            "resume_s": (run.now() - t0) if ok else None, "ok": ok,
        })

    def until_voter(r: int) -> Optional[float]:
        """Drive with traffic until row ``r`` is a voter; returns the
        virtual seconds it took, None on timeout."""
        t0 = run.now()
        end = t0 + catchup_limit_s
        while not e.member[r] and run.now() < end:
            drive(slice_s)
        return (run.now() - t0) if e.member[r] else None

    drive(30.0)                                      # baseline traffic

    # 1. grow 3 -> 4 -> 5 through the learner phase
    t0 = run.now()
    e.add_server(3)
    promote_s = until_voter(3)
    if promote_s is not None:
        promote_s = run.now() - t0
    probe_resume("grow")
    e.add_server(4)
    until_voter(4)
    probe_resume("grow")

    # 2. shrink 5 -> 4 (an election gap can straddle any probe window —
    # re-elect before each leader-required op instead of dying on
    # leader_id=None with an unrelated traceback)
    e.run_until_leader(limit=catchup_limit_s)
    victim = next(
        r for r in range(run.cfg.rows)
        if e.member[r] and r != e.leader_id
    )
    s_rm = e.remove_server(victim)
    e.run_until_committed(s_rm, limit=catchup_limit_s)
    probe_resume("shrink")

    # 3. remove the leader
    e.run_until_leader(limit=catchup_limit_s)
    lead = e.leader_id
    e.remove_server(lead)
    end = run.now() + catchup_limit_s
    while e.member[lead] and run.now() < end:
        drive(slice_s)
    e.run_until_leader(limit=catchup_limit_s)
    probe_resume("remove_leader")

    # 4. wipe-replace a voter (rejoin-from-nothing as a learner)
    e.run_until_leader(limit=catchup_limit_s)
    victim = next(
        r for r in range(run.cfg.rows)
        if e.member[r] and r != e.leader_id
    )
    e.fail(victim)
    e.wipe(victim)
    run.store.wipe_node(victim)
    t0 = run.now()
    e.replace(victim, victim)
    run._wipe_rejoin.add(victim)
    end = run.now() + catchup_limit_s
    while e.member[victim] and run.now() < end:
        drive(slice_s)        # the removal half of the ladder commits
    replace_promote_s = (
        until_voter(victim) if not e.member[victim] else None
    )
    if replace_promote_s is not None:
        replace_promote_s = run.now() - t0
    probe_resume("wipe_replace")

    run.quiesce()
    run.history.close()
    check = check_history(run.history, step_budget=step_budget)
    availability_ok = bool(events) and all(ev["ok"] for ev in events)
    repro = f"python -m raft_tpu.chaos --reconfig --seed {seed}"
    bundle_path = _maybe_bundle(
        "reconfig", run, check, LINEARIZABLE, repro, [], bundle_dir,
        extra={"events": events, "promote_s": promote_s,
               "replace_promote_s": replace_promote_s},
        force_unexpected=not availability_ok,
    )
    return ReconfigReport(
        seed=seed, check=check, ops=len(run.history),
        op_counts=run.history.counts(), events=events,
        promote_s=promote_s, replace_promote_s=replace_promote_s,
        availability_window_s=availability_window_s,
        availability_ok=availability_ok,
        repro=repro, bundle_path=bundle_path, obs=run.obs,
    )


# --------------------------------------------- segment-nemesis drill
@dataclasses.dataclass
class SegmentReport:
    """Result of :func:`segment_storage_run` — the tiered-store
    acceptance drill: sealed segments are corrupted (torn spill, bit
    flip, dropped shard — within the keep-k rule) while a ring-lapped
    follower's only rejoin material lives in them. The claim under
    test: recovery rides the RS reconstruct path (``reconstructs`` > 0,
    never a silent garbage load), the chunked stream completes the
    rejoin, and the client history stays LINEARIZABLE throughout."""

    seed: int
    check: CheckResult
    ops: int
    op_counts: Dict[str, int]
    faults: List[str]            # injected segment faults, as applied
    tier: Dict[str, int]         # final TieredStore stats
    chunks_shipped: int          # incremental-install chunks to rejoin
    rejoined: bool               # the lapped follower caught back up
    repro: str
    bundle_path: Optional[str] = None
    obs: Optional[ObsStack] = None

    @property
    def verdict(self) -> str:
        return self.check.verdict

    @property
    def recovered_via_rs(self) -> bool:
        return self.tier.get("segment_reconstructs", 0) > 0 \
            and self.tier.get("segments_lost", 0) == 0

    def summary(self) -> str:
        line = (
            f"seed {self.seed}: {self.verdict} over {self.ops} ops, "
            f"faults [{', '.join(self.faults)}], "
            f"{self.tier.get('segment_reconstructs', 0)} RS "
            f"reconstructs, {self.chunks_shipped} chunks, "
            f"rejoined={self.rejoined}"
        )
        if self.verdict != LINEARIZABLE or not self.rejoined:
            line += f"\n  REPRO: {self.repro}"
        return line


def segment_storage_run(
    seed: int, *args, blackbox_dir: Optional[str] = None, **kwargs,
) -> SegmentReport:
    """Journaled front door for :func:`_segment_storage_run_impl`
    (see its docstring for the drill script)."""
    with blackbox.journal_for(f"segments_seed{seed}", blackbox_dir):
        blackbox.mark("segment_storage_run", seed=seed)
        return _segment_storage_run_impl(seed, *args, **kwargs)


def _segment_storage_run_impl(
    seed: int,
    catchup_limit_s: float = 600.0,
    step_budget: int = 500_000,
    observe: bool = False,
    bundle_dir: Optional[str] = None,
) -> SegmentReport:
    """The sealed-segment storage nemesis, scripted (no random schedule
    — the fault set is the point, like ``reconfig_run``):

    1. Client traffic builds KV state; a follower dies.
    2. Filler commits lap the ring AND spill past the (deliberately
       small, ``log_capacity // 2``) hot tail, so part of the dead
       follower's future catch-up range exists ONLY as sealed RS-coded
       segments on disk.
    3. The nemesis corrupts sealed shards — one torn spill, one bit
       flip, one dropped shard, seeded placement under the keep-k rule.
    4. The follower recovers: its rejoin streams chunks whose bytes
       must come back through CRC rejection + RS reconstruct (a store
       that loaded a corrupted shard would install garbage the KV
       differential and the checker would catch).
    5. Quiesce, close the history, check linearizability; client
       traffic keeps flowing through every phase.
    """
    cfg = dataclasses.replace(
        _default_cfg(seed),
        tiered_log_dir=tempfile.mkdtemp(prefix="raft_segdrill_"),
        tiered_hot_entries=_default_cfg(seed).log_capacity // 2,
        segment_entries=_default_cfg(seed).log_capacity // 4,
    )
    run = _SingleTorture(
        seed, 0, 2, 3, 30.0, cfg, None, None, observe=observe,
    )
    e = run.engine
    store = e.store
    slice_s = 2 * run.cfg.heartbeat_period
    faults: List[str] = []

    def drive(seconds: float) -> None:
        t_end = run.now() + seconds
        while run.now() < t_end:
            run._invoke_idle()
            run.drive(slice_s)
            run._poll_all()

    drive(30.0)                                     # baseline KV traffic
    victim = next(
        r for r in range(cfg.n_replicas) if r != e.leader_id
    )
    e.fail(victim)
    blackbox.mark("segment_victim_down", victim=victim)
    # lap the ring and spill sealed segments into the catch-up range:
    # zero payloads decode as KV no-ops, so the checker's world is
    # untouched while the log (and the cold tier) grows
    filler = bytes(cfg.entry_bytes)
    target = 3 * cfg.log_capacity
    while e.commit_watermark < target:
        for _ in range(2 * cfg.batch_size):
            e.submit(filler)
        drive(2 * slice_s)
    assert store.stats["segments_sealed"] > 0, \
        "drill misconfigured: nothing sealed"
    nem = SegmentNemesis(store)
    srng = random.Random(f"segments:{seed}")
    # The rejoin stream installs from the ring-fitting tail base
    # (wm - capacity + 1) and hands off to the ring-served repair
    # window at the horizon — so the segment reads happen on the FIRST
    # chunks. Put the corruption exactly there (the hot tail is
    # deliberately smaller than the ring, so that base is sealed); one
    # more fault lands anywhere for kind coverage — the crash-restore
    # leg below sweeps the whole checkpoint span through the store
    # regardless.
    path_lo = e.commit_watermark - cfg.log_capacity + 1
    path = (path_lo, path_lo + 2 * cfg.batch_size)
    # data_only on the on-path faults: a parity-shard fault recovers
    # through the systematic stitch (no decode), and this drill's pass
    # condition is precisely that the RS decode engaged
    for kind, rng_range in (("flip_bit", path), ("drop_shard", path),
                            ("torn_spill", None)):
        desc = nem.inject(
            srng, kind, within=rng_range,
            data_only=rng_range is not None,
        )
        if desc is not None:
            faults.append(desc)
            blackbox.mark("segment_fault", fault=desc)
    wm_down = e.commit_watermark
    chunks0 = e._shipper.chunks_total
    e.recover(victim)
    end = run.now() + catchup_limit_s
    while run.now() < end:
        drive(slice_s)
        if int(e._fetch(e.state.match_index)[victim]) >= wm_down:
            break
    rejoined = int(e._fetch(e.state.match_index)[victim]) >= wm_down
    chunks = e._shipper.chunks_total - chunks0
    # Crash-restore leg: checkpoint assembly reads the WHOLE checkpoint
    # span (2x ring capacity) through the store — most of it sealed
    # here — so every faulted segment on disk must come back through
    # CRC rejection + RS reconstruct or the restored cluster would
    # restart from garbage (the post-restore reads and the checker
    # would catch it).
    run._crash_restart("none")
    e = run.engine
    drive(30.0)
    tier = dict(store.stats)
    run.quiesce()
    run.history.close()
    check = check_history(run.history, step_budget=step_budget)
    repro = f"python -m raft_tpu.chaos --segments --seed {seed}"
    bundle_path = _maybe_bundle(
        "segments", run, check, LINEARIZABLE, repro, faults, bundle_dir,
        extra={"faults": faults, "tier": tier, "rejoined": rejoined},
        force_unexpected=not rejoined,
    )
    return SegmentReport(
        seed=seed, check=check, ops=len(run.history),
        op_counts=run.history.counts(), faults=faults, tier=tier,
        chunks_shipped=chunks, rejoined=rejoined, repro=repro,
        bundle_path=bundle_path, obs=run.obs,
    )


# --------------------------------------------- group-migration drill
@dataclasses.dataclass
class MigrationReport:
    """Result of :func:`migration_run` — the group-shard acceptance
    drill: Rebalancer-driven group moves between mesh shards while the
    sharded-KV client workload runs, with a per-move commit-progress
    probe. ``verdict`` must stay LINEARIZABLE and every move's probe
    must land inside ``resume_window_s`` virtual seconds."""

    seed: int
    check: CheckResult
    ops: int
    op_counts: Dict[str, int]
    moves: List[dict]
    resume_window_s: float
    progress_ok: bool
    n_shards: int
    repro: str
    commit_digest: str = ""
    bundle_path: Optional[str] = None
    obs: Optional[ObsStack] = None

    @property
    def verdict(self) -> str:
        return self.check.verdict

    def summary(self) -> str:
        return (
            f"seed={self.seed} verdict={self.verdict} "
            f"moves={len(self.moves)} shards={self.n_shards} "
            f"progress_ok={self.progress_ok} ops={self.ops}"
        )


def migration_run(
    seed: int,
    n_groups: int = 8,
    n_moves: int = 3,
    resume_window_s: float = 120.0,
    clients: int = 3,
    keys: int = 8,
    cfg: Optional[RaftConfig] = None,
    step_budget: int = 500_000,
    observe: bool = False,
    bundle_dir: Optional[str] = None,
    blackbox_dir: Optional[str] = None,
) -> MigrationReport:
    """Migration-under-load: the deterministic drill behind the
    group-shard acceptance criteria (the randomized composition rides
    ``torture_run_multi`` under ``RAFT_TPU_GSHARD=1`` — this run
    isolates the placement story so the progress assertion is crisp).

    A sharded ``MultiEngine`` (``transport="mesh_groups"``; needs a
    multi-device backend — the 8-virtual-device CPU mesh in CI) serves
    the ShardedKV torture workload while ``n_moves`` group migrations
    fire mid-traffic: each move is planned by the StatusBoard-fed
    :class:`raft_tpu.multi.rebalancer.Rebalancer` when the load spread
    warrants one, else forced round-robin (the drill must exercise the
    move even when the synthetic load happens to be balanced). After
    every move, a probe write on the MOVED group must commit within
    ``resume_window_s`` virtual seconds, and the whole per-key history
    must check LINEARIZABLE."""
    with blackbox.journal_for(f"migration_seed{seed}", blackbox_dir):
        blackbox.mark("migration_run", seed=seed, n_groups=n_groups,
                      moves=n_moves)
        base = cfg or RaftConfig(
            n_replicas=3, entry_bytes=32, batch_size=4, log_capacity=128,
            transport="mesh_groups", seed=seed,
        )
        run = _MultiTorture(
            seed, 0, clients, keys, 30.0, base, n_groups,
            observe=observe,
        )
        e = run.engine
        if e.n_shards < 2:
            raise RuntimeError(
                "migration_run needs a sharded layout (>= 2 devices "
                f"for the gshard axis; engine degraded to "
                f"{e.transport_mode!r})"
            )
        from raft_tpu.multi.rebalancer import Rebalancer

        reb = Rebalancer(e)
        slice_s = 2 * run.cfg.heartbeat_period
        moves: List[dict] = []

        def drive(seconds: float) -> None:
            t_end = run.now() + seconds
            while run.now() < t_end:
                run._invoke_idle()
                run.drive(slice_s)
                run._poll_all()

        drive(30.0)                               # baseline traffic
        for i in range(n_moves):
            plan = reb.plan(max_moves=1)
            if plan:
                mv = e.migrate_group(plan[0]["group"], plan[0]["dst"])
                planned = True
            else:
                # balanced load: force the busiest group one shard over
                g = max(range(e.G),
                        key=lambda gg: (len(e._queue[gg]), -gg))
                mv = e.migrate_group(g, (e.shard_of(g) + 1) % e.n_shards)
                planned = False
            assert mv is not None
            blackbox.mark("migrate", group=mv["group"], src=mv["src"],
                          dst=mv["dst"])
            # progress probe ON THE MOVED GROUP: commit must resume
            # inside the window, with the client workload still running
            t0 = run.now()
            probe = e.submit(mv["group"], bytes(run.cfg.entry_bytes))
            end = t0 + resume_window_s
            while not e.is_durable(mv["group"], probe) and \
                    run.now() < end and e._q:
                e.step_event()
            mv.update({
                "planned": planned,
                "resume_s": (run.now() - t0)
                if e.is_durable(mv["group"], probe) else None,
                "ok": e.is_durable(mv["group"], probe),
            })
            moves.append(mv)
            drive(slice_s)                        # traffic between moves

        run.quiesce()
        run.history.close()
        blackbox.mark("check_history", ops=len(run.history))
        check = check_history(run.history, step_budget=step_budget)
        blackbox.mark("check_done", verdict=check.verdict)
    progress_ok = bool(moves) and all(m["ok"] for m in moves)
    repro = (
        f"python -m raft_tpu.chaos --migration --seed {seed} "
        f"--groups {n_groups}"
    )
    bundle_path = _maybe_bundle(
        "migration", run, check, LINEARIZABLE, repro, [], bundle_dir,
        extra={"moves": moves, "n_shards": e.n_shards},
        force_unexpected=not progress_ok,
    )
    return MigrationReport(
        seed=seed, check=check, ops=len(run.history),
        op_counts=run.history.counts(), moves=moves,
        resume_window_s=resume_window_s, progress_ok=progress_ok,
        n_shards=e.n_shards, repro=repro,
        commit_digest=run.commit_digest(), bundle_path=bundle_path,
        obs=run.obs,
    )


# ------------------------------------------------- read scale-out drill
@dataclasses.dataclass
class ReadsReport:
    """Result of :func:`reads_run` — the read scale-out acceptance
    drill (docs/READS.md): lease churn + leader kill + clock-skew
    nemesis composed, with PER-READ-CLASS verdicts
    (``chaos.checker.check_read_classes``) instead of one blanket
    linearizability grade. The deterministic stale-probe phase is the
    falsifiability core: a partitioned, clock-skewed old leader is
    probed after a rival committed — the correct plane must REFUSE
    (``refused_stale``), the ``broken="lease_skew"`` variant serves
    the stale bytes and must be CAUGHT (lease-class VIOLATION offline,
    ``read_monotone`` online)."""

    seed: int
    per_class: Dict[str, CheckResult]
    ops: int
    op_counts: Dict[str, int]
    lease_serves: int
    read_index_serves: int
    session_serves: int
    refused_stale: int
    stale_served: int           # broken-plane stale serves observed
    leader_kills: int
    skew_log: List[str]
    audit_violations: Optional[int]
    repro: str
    broken: Optional[str] = None
    bundle_path: Optional[str] = None
    obs: Optional[ObsStack] = None

    @property
    def verdict(self) -> str:
        """Worst per-class verdict (every class must hold its own
        contract for the drill to pass)."""
        verdicts = [c.verdict for c in self.per_class.values()]
        if VIOLATION in verdicts:
            return VIOLATION
        if any(v != LINEARIZABLE for v in verdicts):
            return "UNDETERMINED"
        return LINEARIZABLE

    @property
    def caught(self) -> bool:
        """Broken-variant success: the stale serve happened AND at
        least one detector (offline per-class checker, online
        auditor) flagged it."""
        offline = self.per_class.get("lease") is not None and \
            self.per_class["lease"].verdict == VIOLATION
        online = bool(self.audit_violations)
        return self.stale_served > 0 and (offline or online)

    def summary(self) -> str:
        cls = {c: r.verdict for c, r in self.per_class.items()}
        return (
            f"seed={self.seed} classes={cls} lease={self.lease_serves} "
            f"read_index={self.read_index_serves} "
            f"session={self.session_serves} "
            f"refused_stale={self.refused_stale} "
            f"stale_served={self.stale_served} ops={self.ops}"
        )


def reads_run(
    seed: int,
    broken: Optional[str] = None,
    clients: int = 3,
    keys: int = 4,
    step_budget: int = 500_000,
    observe: bool = True,
    bundle_dir: Optional[str] = None,
    blackbox_dir: Optional[str] = None,
) -> ReadsReport:
    """The deterministic read scale-out drill (``--reads``): leader
    leases under write traffic, clock-skew churn across the configured
    drift band, a leader kill with lease resumption, session reads on
    commit-index tokens, and the scripted STALE-PROBE scenario —
    partition the (slow-clocked) leader away, let the majority elect
    and commit past it, then probe the old leader's lease read. The
    correct plane provably refuses (its lease expired before the rival
    could exist); ``broken="lease_skew"`` (drift bound ignored) still
    holds the lease on its slow clock, serves the frozen — now stale —
    state, and must be caught by the per-class checker and the online
    auditor. Success therefore means the OPPOSITE thing per variant,
    exactly like ``--broken dirty_reads``."""
    if broken not in (None, "lease_skew"):
        raise ValueError(f"unknown reads_run broken variant {broken!r}")
    cfg = _reads_cfg(_default_cfg(seed))
    with blackbox.journal_for(f"reads_seed{seed}", blackbox_dir):
        blackbox.mark("reads_run", seed=seed, broken=broken or "")
        run = _SingleTorture(
            seed, 0, clients, keys, 30.0, cfg, None, broken,
            reads=True, observe=observe, audit=True,
        )
        e = run.engine
        drift = cfg.clock_drift_bound
        skew_log: List[str] = []
        refused_stale = 0
        stale_served = 0
        leader_kills = 0
        session_cid = 900
        session_floor = [0]

        def session_read(key: bytes) -> None:
            """One session-consistent read: serve from applied state
            gated on the client's commit-index token, no leader
            contact (the single-engine twin of Router.read_session)."""
            rec = run.history.invoke(
                session_cid, READ, key, None, run.now()
            )
            rec.read_class = "session"
            rec.ryw_floor = session_floor[0]
            idx = int(e.applied_index)
            if idx < session_floor[0]:
                # the apply stream lags the token (ReadLagging's
                # single-engine analogue): typed refusal, no effect
                rec.fail(run.history.stamp(run.now()))
                return
            value = run._value_at(key, idx)
            rec.serve_index = idx
            session_floor[0] = max(session_floor[0], idx)
            run._audit_read(session_cid, key, value)
            rec.ok(run.history.stamp(run.now()), value)
            e._note_read_served("session", 0.0)

        def drive(seconds: float) -> None:
            t_end = run.now() + seconds
            i = 0
            while run.now() < t_end:
                run._invoke_idle()
                run.drive(2 * cfg.heartbeat_period)
                run._poll_all()
                session_read(run.keys[i % len(run.keys)])
                i += 1

        # ---- phase 1: leases under traffic --------------------------
        drive(60.0)
        blackbox.mark("reads_warmup",
                      classes=dict(e.read_class_counts))
        # ---- phase 2: skew churn across the drift band --------------
        for rate in (1.0 / drift, drift, 1.0):
            lead = e.leader_id
            if lead is not None:
                e.set_lease_rate(lead, rate)
                skew_log.append(f"t={run.now():.1f} "
                                f"skew(Server{lead}, {rate:.3f})")
            drive(30.0)
        # ---- phase 3: leader kill; lease must resume ----------------
        lead = (e.leader_id if e.leader_id is not None
                else e.run_until_leader())
        e.fail(lead)
        leader_kills += 1
        skew_log.append(f"t={run.now():.1f} kill(Server{lead})")
        e.run_until_leader()
        e.recover(lead)
        drive(45.0)
        # ---- phase 4: the stale probe (falsifiability core) ---------
        lead = (e.leader_id if e.leader_id is not None
                else e.run_until_leader())
        slow_rate = 1.0 / drift        # slowest clock INSIDE the band
        e.set_lease_rate(lead, slow_rate)
        skew_log.append(f"t={run.now():.1f} "
                        f"skew(Server{lead}, {slow_rate:.3f})")
        probe_key = run.keys[0]
        w_old = b"stale-old"
        rec = run.history.invoke(901, WRITE, probe_key, w_old, run.now())
        s1 = run.kv.set(probe_key, w_old)
        e.run_until_committed(s1)
        rec.ok(run.history.stamp(run.now()))
        others = [p for p in range(cfg.rows)
                  if e.member[p] and p != lead]
        e.partition([[lead], others])
        run.partitioned = True
        blackbox.mark("stale_probe_partition", leader=lead,
                      t_virtual=round(run.now(), 3))
        # §9.6 stickiness must elapse before any rival can be elected —
        # which is exactly why a correct lease (duration f0/drift on a
        # clock no slower than 1/drift) has expired by then
        e.run_for(cfg.follower_timeout[0] + 0.5)
        for cand in others:
            e.force_campaign(cand)
            if e.leader_id == cand:
                break
        assert e.leader_id in others, \
            "stale-probe majority election did not land"
        w_new = b"stale-new"
        rec = run.history.invoke(901, WRITE, probe_key, w_new, run.now())
        s2 = run.kv.set(probe_key, w_new)
        e.run_until_committed(s2, limit=120.0)
        rec.ok(run.history.stamp(run.now()))
        # the probe CLIENT first observes the new value through the new
        # leader (arming the auditor's monotone watermark), then probes
        # the old one
        probe_cid = 902
        rec = run.history.invoke(probe_cid, READ, probe_key, None,
                                 run.now())
        tk = e.submit_read()
        rec.read_class = e.read_ticket_class(tk)
        idx = None
        for _ in range(200):
            idx = e.read_confirmed(tk)
            if idx is not None:
                break
            e.step_event()
        assert idx is not None and run.kv.last_applied >= idx
        fresh = run.kv.get(probe_key)
        run._audit_read(probe_cid, probe_key, fresh)
        rec.ok(run.history.stamp(run.now()), fresh)
        # ---- the probe itself ---------------------------------------
        from raft_tpu.raft.engine import LinearizableReadRefused

        rec = run.history.invoke(probe_cid, READ, probe_key, None,
                                 run.now())
        try:
            tk = e.submit_read(r=lead)
        except LinearizableReadRefused:
            # the CORRECT plane lands here: its lease expired before
            # the rival could be elected, and the classic fallback's
            # quorum check refuses from the minority side
            refused_stale += 1
            rec.fail(run.history.stamp(run.now()))
        else:
            cls = e.read_ticket_class(tk)
            rec.read_class = cls
            pidx = e.read_confirmed(tk)
            assert pidx is not None, \
                "old-leader ticket neither served nor refused"
            value = run._value_at(probe_key, pidx)
            if cls == "lease" and value != fresh:
                stale_served += 1
                skew_log.append(
                    f"t={run.now():.1f} STALE lease serve at idx "
                    f"{pidx}: {value!r} (fresh {fresh!r})"
                )
            run._audit_read(probe_cid, probe_key, value)
            rec.ok(run.history.stamp(run.now()), value)
        blackbox.mark("stale_probe_done", refused=refused_stale,
                      served_stale=stale_served)
        e.heal_partition()
        run.partitioned = False
        e.set_lease_rate(lead, 1.0)    # un-skew the probed row
        drive(30.0)
        run.quiesce()
        run.history.close()
        blackbox.mark("check_history", ops=len(run.history))
        per_class = check_read_classes(
            run.history, step_budget=step_budget
        )
        blackbox.mark("check_done", verdicts={
            c: r.verdict for c, r in per_class.items()
        })
    repro = (
        f"python -m raft_tpu.chaos --reads --seed {seed}"
        + (f" --broken {broken}" if broken else "")
    )
    worst = next(
        (r for r in per_class.values() if r.verdict != LINEARIZABLE),
        CheckResult(LINEARIZABLE, 0),
    )
    expected = VIOLATION if broken else LINEARIZABLE
    bundle_path = _maybe_bundle(
        "reads", run, worst, expected, repro, skew_log, bundle_dir,
        extra={"refused_stale": refused_stale,
               "stale_served": stale_served,
               "classes": dict(e.read_class_counts)},
    )
    aud = (run.obs.audit.total_violations
           if run.obs is not None and run.obs.audit is not None
           else None)
    counts = e.read_class_counts
    return ReadsReport(
        seed=seed, per_class=per_class, ops=len(run.history),
        op_counts=run.history.counts(),
        lease_serves=counts.get("lease", 0),
        read_index_serves=counts.get("read_index", 0),
        session_serves=counts.get("session", 0),
        refused_stale=refused_stale, stale_served=stale_served,
        leader_kills=leader_kills, skew_log=skew_log,
        audit_violations=aud, repro=repro, broken=broken,
        bundle_path=bundle_path, obs=run.obs,
    )


def multi_commit_digest(engine) -> str:
    """CRC over every group's committed archive tail + watermark — the
    MultiEngine commit fingerprint (the single definition the multi
    open-loop runner and the wire drill both report, so their
    byte-identity pins compare the same quantity)."""
    crc = 0
    for g in range(engine.G):
        wm = int(engine.commit_watermark[g])
        crc = zlib.crc32(f"g{g}:wm:{wm}".encode(), crc)
        arch = engine._archive[g]
        for idx in sorted(i for i in arch if i <= wm):
            crc = zlib.crc32(
                arch[idx], zlib.crc32(f"{idx}".encode(), crc)
            )
    return f"{crc:08x}"


# ------------------------------------------------------- the wire drill
@dataclasses.dataclass
class WireReport:
    """Result of :func:`wire_run` — torture traffic driven through a
    REAL loopback TCP server (``raft_tpu.net``) instead of in-process
    calls, with the leader-kill and overload nemeses composed. Ops are
    recorded in the same ``History`` the in-process runners use and
    graded per read class (``check_read_classes``), so the wire tier
    earns the same verdict currency as everything else: LINEARIZABLE
    or it does not ship."""

    seed: int
    per_class: Dict[str, "CheckResult"]
    ops: int
    op_counts: Dict[str, int]
    wire_refusals: Dict[str, int]
    shed_writes: int             # open-loop arrivals typed-refused
    not_leader_frames: int       # NOT_LEADER wire frames observed
    leader_kills: int
    net: dict                    # final server ``net`` stats section
    read_classes: Dict[str, int]
    repro: str
    commit_digest: str = ""      # multi_commit_digest at quiesce
    traced: bool = False         # the wire trace plane was armed
    client_spans: int = 0        # client-side span count (traced runs)
    server_spans: int = 0        # server-side wire-op span count
    pump: Optional[dict] = None  # PumpProfiler.stats() (traced runs)
    bundle_path: Optional[str] = None
    #   one bundle carrying BOTH span tables (spans + client_spans)
    #   when a bundle_dir was configured — the joined --explain input

    @property
    def verdict(self) -> str:
        verdicts = [c.verdict for c in self.per_class.values()]
        if VIOLATION in verdicts:
            return VIOLATION
        if any(v != LINEARIZABLE for v in verdicts):
            return "UNDETERMINED"
        return LINEARIZABLE

    def summary(self) -> str:
        cls = {c: r.verdict for c, r in self.per_class.items()}
        return (
            f"seed={self.seed} classes={cls} ops={self.ops} "
            f"shed={self.shed_writes} "
            f"not_leader={self.not_leader_frames} "
            f"kills={self.leader_kills} "
            f"conns={self.net.get('connections')} "
            f"bytes_in={self.net.get('bytes_in')}"
        )


def wire_run(
    seed: int,
    clients: int = 4,
    keys: int = 4,
    ops_per_phase: int = 10,
    groups: int = 2,
    step_budget: int = 500_000,
    blackbox_dir: Optional[str] = None,
    trace: bool = True,
    bundle_dir: Optional[str] = None,
) -> WireReport:
    """The deterministic wire-plane drill (``--wire``): a sharded
    Router stack served over a REAL loopback asyncio TCP server, with
    torture traffic arriving as wire frames. Three phases, nemeses
    composed:

    1. steady traffic — ``clients`` wire clients (own connections, own
       session tokens) running mixed writes / linearizable reads /
       session reads;
    2. LEADER KILL on the hottest group mid-traffic — clients ride
       ``NOT_LEADER`` wire refusals + backoff through the election,
       the row recovers after;
    3. OVERLOAD — an open-loop burst of one-shot writers (retry-free
       connections) past the admission depth bound: the gate's typed
       refusals surface as ``REFUSED`` wire frames, recorded ``fail``
       (provably no effect — the wire preserves the contract the
       checker leans on).

    Every client op is recorded in the shared ``History`` on the
    engine's virtual clock (the asyncio loop and the engine share one
    thread, so host execution order is real-time order — the same
    soundness argument the in-process runners make) and graded with
    ``check_read_classes``; the drill passes only if every class holds
    its contract, a shed happened, and NOT_LEADER frames were ridden
    through. No real-clock sleeps beyond the client's millisecond-scale
    jittered backoff — the run is event-driven end to end.

    ``trace=True`` (the default — the drill RUNS traced, ISSUE 15)
    arms the full wire trace plane: one client-side span per op
    (attempts/backoffs/redials), server-side wire spans adopting the
    propagated context, the pump-phase profiler, and the net metrics
    registry — all strictly additive (the determinism pin compares
    trace on vs off on a serial deterministic scenario; the drill's
    own asyncio/TCP interleaving is outside the seeded-replay domain,
    which is exactly why its verdict currency is the history checker,
    not replay identity). With a ``bundle_dir`` (argument or
    ``RAFT_TPU_BUNDLE_DIR``), a traced run writes one repro bundle
    carrying BOTH span tables — the ``--explain`` joined-forensics
    input — regardless of verdict (the artifact is the point of the
    traced drill, not a failure symptom)."""
    import asyncio

    from raft_tpu.examples.kv_sharded import ShardedKV
    from raft_tpu.multi.engine import MultiEngine
    from raft_tpu.multi.router import Router
    from raft_tpu.net import (
        IngestServer,
        RouterBackend,
        WireClient,
        WireDisconnected,
        WireRefused,
    )
    from raft_tpu.net.client import WireError

    cfg = dataclasses.replace(
        _default_cfg(seed),
        admission_max_writes=8,
        admission_max_reads=64,
    )
    eng = MultiEngine(cfg, groups)
    router = Router(eng, drive=False)
    skv = ShardedKV(eng, router)
    eng.seed_leaders()
    history = History()
    key_pool = [f"wk{i}".encode() for i in range(keys)]
    rng = random.Random(f"wire:{seed}")
    leader_kills = 0
    shed_writes = 0

    # -- the wire trace plane (strictly additive; trace=False is the
    # -- byte-compatible PR-14 drill) ---------------------------------
    client_spans = server_spans = pump = registry = None
    if trace:
        from raft_tpu.obs.hostprof import PumpProfiler
        from raft_tpu.obs.registry import MetricsRegistry
        from raft_tpu.obs.spans import SpanTracker

        client_spans = SpanTracker()
        server_spans = SpanTracker()
        registry = MetricsRegistry()
        pump = PumpProfiler(registry=registry)
        # the engines' own causal hooks chain onto the server wire
        # spans (ambient binding across the pump's dispatch)
        eng.spans = server_spans

    def _clock() -> float:
        # both sides' spans stamp the SAME virtual clock (one thread),
        # so the joined timeline is one consistent time axis
        return eng.clock.now

    def _g(key: bytes) -> int:
        return router.group_of(key)

    async def client_ops(wc: WireClient, cid: int, n: int) -> None:
        """One serial client: the §6.3 discipline over the wire."""
        crng = random.Random(f"wire:{seed}:{cid}")
        for i in range(n):
            key = key_pool[crng.randrange(len(key_pool))]
            p = crng.random()
            if p < 0.6:
                value = f"c{cid}v{i}-{crng.randrange(1 << 20)}".encode()
                rec = history.invoke(cid, WRITE, key, value,
                                     eng.clock.now)
                try:
                    await wc.submit(key, value)
                except WireRefused:
                    # typed refusal: provably nothing queued — FAIL is
                    # sound (the gate/NotLeader contract over the wire)
                    rec.fail(history.stamp(eng.clock.now))
                except (WireDisconnected, WireError, ConnectionError):
                    rec.info()      # outcome unknown: may still commit
                else:
                    rec.ok(history.stamp(eng.clock.now))
            else:
                cls = "session" if p > 0.85 else "linearizable"
                rec = history.invoke(cid, READ, key, None,
                                     eng.clock.now)
                if cls == "session":
                    rec.ryw_floor = wc.session.floor.get(_g(key), 0)
                try:
                    out = await wc.read(key, cls=cls)
                except (WireRefused, WireError, WireDisconnected,
                        ConnectionError):
                    # an unserved read has no effect, whatever killed it
                    rec.fail(history.stamp(eng.clock.now))
                else:
                    rec.read_class = out.cls
                    rec.serve_index = out.index
                    rec.ok(history.stamp(eng.clock.now), out.value)

    async def flood(port: int, n: int) -> int:
        """Open-loop one-shot writers: no retries, unique client ids —
        the overload nemesis at the wire."""
        wc = await WireClient(
            "127.0.0.1", port, pool=1, retries=0,
            rng=random.Random(f"wire-flood:{seed}"),
            spans=client_spans, clock=_clock, trace_node=1001,
        ).connect()
        shed = 0
        async def one(j: int) -> None:
            nonlocal shed
            key = key_pool[j % len(key_pool)]
            value = f"flood{j}-{rng.randrange(1 << 20)}".encode()
            rec = history.invoke(1000 + j, WRITE, key, value,
                                 eng.clock.now)
            try:
                await wc.submit(key, value)
            except WireRefused:
                shed += 1
                rec.fail(history.stamp(eng.clock.now))
            except (WireDisconnected, WireError, ConnectionError):
                rec.info()
            else:
                rec.ok(history.stamp(eng.clock.now))
        await asyncio.gather(*[one(j) for j in range(n)])
        await wc.close()
        return shed

    async def main() -> dict:
        nonlocal leader_kills, shed_writes
        server = IngestServer(
            RouterBackend(router, skv),
            drive_quantum_s=2 * cfg.heartbeat_period,
            spans=server_spans, registry=registry, pump=pump,
        )
        port = await server.start()
        blackbox.mark("wire_serving", port=port)
        wcs = [
            await WireClient(
                "127.0.0.1", port, pool=1, retries=48,
                rng=random.Random(f"wire:{seed}:conn{cid}"),
                spans=client_spans, clock=_clock, trace_node=cid + 1,
            ).connect()
            for cid in range(clients)
        ]
        # ---- phase 1: steady wire traffic ---------------------------
        await asyncio.gather(*[
            client_ops(wc, cid, ops_per_phase)
            for cid, wc in enumerate(wcs)
        ])
        blackbox.mark("wire_steady_done", ops=len(history))
        # ---- phase 2: leader kill mid-traffic -----------------------
        hot = _g(key_pool[0])
        lead = eng.leader_id[hot]
        if lead is None:
            lead = eng.run_until_leader(hot)
        eng.fail(hot, lead)
        leader_kills += 1
        blackbox.mark("wire_leader_kill", group=hot, row=lead)
        await asyncio.gather(*[
            client_ops(wc, cid, ops_per_phase)
            for cid, wc in enumerate(wcs)
        ])
        eng.recover(hot, lead)
        blackbox.mark("wire_kill_ridden", ops=len(history))
        # ---- phase 3: open-loop overload burst ----------------------
        shed_writes = await flood(port, 3 * cfg.admission_max_writes)
        await asyncio.gather(*[
            client_ops(wc, cid, ops_per_phase)
            for cid, wc in enumerate(wcs)
        ])
        # ---- quiesce ------------------------------------------------
        for wc in wcs:
            await wc.close()
        stats = server.stats()
        nl = sum(wc.stats["not_leader"] for wc in wcs)
        await server.stop()
        return {"net": stats, "not_leader": nl}

    with blackbox.journal_for(f"wire_seed{seed}", blackbox_dir):
        blackbox.mark("wire_run", seed=seed)
        out = asyncio.run(main())
        history.close()
        blackbox.mark("check_history", ops=len(history))
        per_class = check_read_classes(history, step_budget=step_budget)
        blackbox.mark("check_done", verdicts={
            c: r.verdict for c, r in per_class.items()
        })
    counts: Dict[str, int] = {}
    for rec in history.ops:
        c = getattr(rec, "read_class", None)
        if c:
            counts[c] = counts.get(c, 0) + 1
    rep = WireReport(
        seed=seed,
        per_class=per_class,
        ops=len(history),
        op_counts=history.counts(),
        wire_refusals=dict(out["net"].get("refusals", {})),
        shed_writes=shed_writes,
        not_leader_frames=out["not_leader"],
        leader_kills=leader_kills,
        net=out["net"],
        read_classes=counts,
        repro=f"python -m raft_tpu.chaos --wire --seed {seed}"
              + ("" if trace else " (untraced)"),
        commit_digest=multi_commit_digest(eng),
        traced=trace,
        client_spans=len(client_spans) if client_spans else 0,
        server_spans=(
            sum(1 for sp in server_spans.spans
                if sp.op.startswith("wire_"))
            if server_spans else 0
        ),
        pump=out["net"].get("pump"),
    )
    dest = resolve_bundle_dir(bundle_dir)
    if trace and dest is not None:
        # the traced drill's artifact: BOTH span tables in one bundle
        # (plus the op history and faults-free context) — what the
        # joined --explain consumes; written on every verdict because
        # the cross-process trace IS the deliverable here
        try:
            rep.bundle_path = write_bundle(
                dest,
                kind="wire",
                seed=seed,
                expected=LINEARIZABLE,
                verdict=rep.verdict,
                repro=rep.repro,
                config=cfg,
                history=history,
                spans=server_spans,
                client_spans=client_spans,
                extra={"side": "server+client", "net": rep.net,
                       "commit_digest": rep.commit_digest},
            )
        except OSError as ex:       # an unwritable dir must not eat
            import sys              # the report it was meant to save

            print(f"wire bundle not written: {ex}", file=sys.stderr)
    return rep


# ------------------------------------------------- transaction drill
@dataclasses.dataclass
class TxnReport:
    """Result of :func:`txn_run` — the cross-group transaction
    acceptance drill (docs/TXN.md): a transactional transfer workload
    (conserved account sum) over a sharded ``MultiEngine`` with
    single-key traffic alongside on a DISJOINT keyspace, under a
    composed nemesis — leader kill, partition, one ``migrate_group``
    mid-transaction — plus an abandoned-coordinator TTL case and a
    deliberately racing pair. ``check`` is the serializability witness
    verification (``chaos.checker.check_serializable``); ``singles``
    grades the single-key history with the linearizability checker.

    ``broken="txn_partial_commit"`` (coordinator commits after a
    failed prewrite) and ``broken="txn_dirty_read"`` (reads serve
    staged intents) must be CAUGHT: verdict VIOLATION, or the
    conserved-sum invariant broken."""

    seed: int
    check: CheckResult
    singles: CheckResult
    txns: int
    committed: int
    aborted: int
    unresolved: int
    conflicts: int
    single_ops: int
    conserved_ok: bool
    expected_total: int
    observed_total: int
    moves: List[dict]
    nemeses: List[str]
    broken: Optional[str]
    repro: str
    commit_digest: str = ""
    bundle_path: Optional[str] = None
    read_certs: Dict[str, int] = dataclasses.field(default_factory=dict)

    @property
    def verdict(self) -> str:
        return self.check.verdict

    @property
    def caught(self) -> bool:
        """For ``broken=`` variants: did the harness call it wrong?
        Either the witness verification finds a VIOLATION or the
        application-level conserved-sum invariant broke (the blind
        dirty-read path shows up there when the poisoned basis is not
        in the witness)."""
        return self.check.verdict == VIOLATION or not self.conserved_ok

    def summary(self) -> str:
        return (
            f"seed={self.seed} verdict={self.verdict} "
            f"txns={self.txns} committed={self.committed} "
            f"aborted={self.aborted} conflicts={self.conflicts} "
            f"conserved={self.conserved_ok} "
            f"singles={self.singles.verdict} moves={len(self.moves)}"
            + (f" broken={self.broken} caught={self.caught}"
               if self.broken else "")
        )


def txn_run(
    seed: int,
    n_groups: int = 4,
    accounts: int = 6,
    cfg: Optional[RaftConfig] = None,
    broken: Optional[str] = None,
    step_budget: int = 500_000,
    bundle_dir: Optional[str] = None,
    blackbox_dir: Optional[str] = None,
    extra_nemeses: bool = False,
    lease_reads: bool = False,
) -> TxnReport:
    """The deterministic transaction drill (``--txn``). Scripted
    phases, every choice seeded:

    1. seed transaction (every account <- 100) + validated transfers;
    2. abandoned coordinator: a transaction's handle is dropped after
       prewrite — its replicated locks sit until the TTL expires, and
       the next writer's status-check kicks the resolver (DECIDE-abort,
       first-decision-wins); a BLIND transfer (no wire expects; its
       read basis recorded in the witness) then lands on the freed
       keys — the ``txn_dirty_read`` store poisons that basis with the
       aborted transaction's staged intents, which the witness replay
       must reject;
    3. racing pair: two transfers sharing an account begun
       back-to-back, so BOTH prewrite and log order picks the lock
       winner — the loser must abort (``txn_partial_commit`` commits
       it anyway: a cross-group atomicity violation the replay / end
       state comparison must catch);
    4. leader kill mid-transaction (recovered), partition of a
       participant group mid-transaction (healed), and ONE
       ``migrate_group`` of a participant group mid-transaction —
       the coordinator rides typed refusals through all three;
    5. quiesce: unresolved records settle from the replicated decision
       map, every account is read back (``final_state``), the
       conserved-sum invariant is checked, and the witness + the
       single-key history are graded.

    Single-key traffic runs throughout on a DISJOINT keyspace
    (``k*`` vs ``a*``): lock-oblivious plain writes landing inside a
    lock window would genuinely break strict serializability, which is
    a documented property of the mixed deployment (docs/TXN.md), not a
    bug this drill should trip over.

    ``extra_nemeses=True`` composes the round-16 remainder nemeses
    into the same run (phase 4b): ``mem_replace`` (MultiEngine runs
    fixed membership, so the replace window is the honest
    approximation — a participant follower fails for a window and a
    "replacement" rejoins on the same row via catch-up), a
    ``wire_slow`` induced-slow-follower window (the wire fault the
    mesh transport can express: traffic received, nothing appended),
    and an open-loop ``overload`` burst through the admission gate on
    the single-key plane — each landing mid-transaction or against
    live lock traffic, named in ``nemeses``.

    ``lease_reads=True`` arms the read-plane lease path
    (``cfg.read_lease`` + prevote) and routes every transfer's basis
    read through :meth:`TxnCoordinator.validated_read`: the expects a
    transaction validates under its locks anchor to a leader-certified
    read index — zero quorum rounds when the participant leader holds
    a valid lease — instead of the bare applied map. ``read_certs``
    on the report counts the certification classes ridden."""
    from raft_tpu.chaos.checker import (
        SERIALIZABLE,
        TxnRecord,
        check_serializable,
    )
    from raft_tpu.chaos.history import FAIL, INFO, OK
    from raft_tpu.multi.engine import MultiEngine, NotLeader, ReadLagging
    from raft_tpu.multi.router import Router
    from raft_tpu.txn import TxnCoordinator, TxnItem, TxnShardedKV
    from raft_tpu.txn import ops as _T

    with blackbox.journal_for(f"txn_seed{seed}", blackbox_dir):
        blackbox.mark("txn_run", seed=seed, n_groups=n_groups,
                      broken=broken or "")
        base = cfg or RaftConfig(
            n_replicas=3, entry_bytes=32, batch_size=4,
            log_capacity=256, transport="mesh_groups", seed=seed,
            # the lease path rests on §9.6 leader stickiness — read_lease
            # refuses to arm without prevote (config.py validation)
            prevote=lease_reads, read_lease=lease_reads,
            # the overload window needs a gate that can actually shed:
            # a write-depth bound far above steady drill traffic but
            # inside the burst's open-loop spill
            admission_max_writes=(12 if extra_nemeses else None),
        )
        eng = MultiEngine(base, n_groups)
        if eng.n_shards < 2:
            raise RuntimeError(
                "txn_run needs a sharded layout (>= 2 devices for the "
                f"gshard axis; engine degraded to {eng.transport_mode!r})"
            )
        router = Router(eng, drive=False)
        skv = TxnShardedKV(
            eng, router,
            broken=(broken if broken == "txn_dirty_read" else None),
        )
        eng.seed_leaders()
        hb = base.heartbeat_period
        coord = TxnCoordinator(
            skv, decision_group=0, ttl_s=40.0 * hb,
            broken=(broken if broken == "txn_partial_commit" else None),
            lease_reads=lease_reads,
        )
        rng = random.Random(f"txn-drill:{seed}")
        acct = [b"a%d" % i for i in range(accounts)]
        skeys = [b"k%d" % i for i in range(6)]
        history = History()
        records: List[TxnRecord] = []
        inflight: List[tuple] = []
        moves: List[dict] = []
        nemeses: List[str] = []
        conflicts = 0
        single_count = [0]
        _single_pending: List[tuple] = []

        def now() -> float:
            return eng.clock.now

        def poll_inflight() -> None:
            nonlocal inflight
            keep = []
            for rec, h in inflight:
                if coord.poll(h, now()):
                    _finish(rec, h)
                else:
                    keep.append((rec, h))
            inflight = keep
            done = [p for p in _single_pending
                    if eng.is_durable(*p[1])]
            for rec, handle in done:
                rec.ok(history.stamp(now()))
                _single_pending.remove((rec, handle))

        def _finish(rec: TxnRecord, h) -> None:
            rec.complete_t = history.stamp(now())
            if h.status == "committed":
                d = skv.decision(h.txn_id)
                rec.status, rec.pos = OK, (d[2] if d else None)
            else:
                rec.status = FAIL

        def drive(seconds: float) -> None:
            t_end = now() + seconds
            while now() < t_end:
                eng.run_for(2 * hb)
                coord.poll_all(now())
                poll_inflight()

        def single_op() -> None:
            """One plain op on the disjoint keyspace, recorded in the
            single-key history (mixed traffic: the txn plane must not
            break the non-transactional path)."""
            key = rng.choice(skeys)
            single_count[0] += 1
            if rng.random() < 0.3:
                rec = history.invoke(7000 + single_count[0], READ, key,
                                     None, now())
                try:
                    g, idx = router.read_index(key)
                except Exception:
                    rec.fail(history.stamp(now()))
                    return
                if skv.last_applied[g] < idx:
                    drive(2 * hb)
                if skv.last_applied[g] < idx:
                    rec.fail(history.stamp(now()))
                else:
                    rec.ok(history.stamp(now()), skv.get(key))
                return
            value = b"s%d" % single_count[0]
            rec = history.invoke(7000 + single_count[0], WRITE, key,
                                 value, now())
            try:
                handle = skv.set(key, value)
            except (NotLeader, Overloaded):
                rec.fail(history.stamp(now()))
                return
            _single_pending.append((rec, handle))

        def begin_txn(writes, expects, wire_expects=True,
                      witness_expects=None, limit_s=600.0):
            """Open one transaction under the drill's retry loop.
            ``expects`` go to the coordinator (validated under locks)
            only when ``wire_expects``; the WITNESS records
            ``witness_expects`` (default: the validated set) — a blind
            transaction's observed read basis still obligates the
            serial order even though the server never certified it."""
            nonlocal conflicts
            rec = TxnRecord(
                txn_id=0, writes=dict(writes),
                expects=dict(witness_expects if witness_expects
                             is not None else expects),
                status=INFO, pos=None,
                invoke_t=history.stamp(now()),
            )
            items = []
            for k, v in writes.items():
                it = TxnItem(k, value=v, delete=v is None)
                if wire_expects and k in expects:
                    it.has_expect, it.expect = True, expects[k]
                items.append(it)
            deadline = now() + limit_s
            while True:
                try:
                    h = coord.begin(items)
                    break
                except _T.LockConflict as ex:
                    conflicts += 1
                    drive(max(ex.retry_after_s, 2 * hb))
                except (NotLeader, Overloaded):
                    drive(4 * hb)
                if now() > deadline:
                    rec.status = FAIL
                    records.append(rec)
                    return rec, None
            rec.txn_id = h.txn_id
            records.append(rec)
            inflight.append((rec, h))
            return rec, h

        def settle(*handles, limit_s=600.0) -> None:
            deadline = now() + limit_s
            while any(not h.done for h in handles if h is not None):
                if now() > deadline:
                    break
                drive(4 * hb)

        def bal(key: bytes) -> Optional[bytes]:
            """A transfer's basis read. With ``lease_reads`` armed it
            goes through the coordinator's validated path (certified
            read index, zero rounds under a valid lease), riding out
            elections and apply lag like any router read; otherwise
            the plain applied read the drill always used."""
            for _ in range(80):
                try:
                    return coord.validated_read(key)
                except (NotLeader, ReadLagging):
                    drive(4 * hb)
            return skv.get(key)

        def transfer(src: bytes, dst: bytes, mid=None):
            """One validated transfer src -> dst: read both balances,
            expect them under the locks, write the moved amounts.
            ``mid`` (if given) fires between prewrite and settle — how
            the drill lands a nemesis INSIDE a transaction window."""
            amt = rng.randint(1, 9)
            bs, bd = bal(src), bal(dst)
            writes = {
                src: str(int(bs or b"0") - amt).encode(),
                dst: str(int(bd or b"0") + amt).encode(),
            }
            rec, h = begin_txn(writes, {src: bs, dst: bd})
            if h is not None and mid is not None:
                mid(h)
            if h is not None:
                settle(h)
            single_op()
            return rec, h

        # ---- phase 1: seed + baseline --------------------------------
        blackbox.mark("txn_phase", name="seed")
        _, h0 = begin_txn({a: b"100" for a in acct}, {})
        settle(h0)
        if h0 is None or h0.status != "committed":
            raise RuntimeError("txn_run could not seed the accounts")
        for _ in range(3):
            i = rng.randrange(3, accounts)
            j = rng.randrange(3, accounts)
            while j == i:
                j = rng.randrange(3, accounts)
            transfer(acct[i], acct[j])

        # ---- phase 2: abandoned coordinator + TTL + blind basis ------
        blackbox.mark("txn_phase", name="abandon")
        ab_amt = rng.randint(1, 9)
        ab_rec, ab_h = begin_txn(
            {acct[0]: str(100 - ab_amt).encode(),
             acct[1]: str(100 + ab_amt).encode()},
            {acct[0]: bal(acct[0]), acct[1]: bal(acct[1])},
        )
        if ab_h is not None:
            # the coordinator dies here: drop the handle unpolled — its
            # locks must resolve via TTL + status-check, not our help
            inflight.remove((ab_rec, ab_h))
        drive(6 * hb)                     # prewrites apply, locks live
        for _ in range(2):                # traffic AWAY from a0..a2
            i = rng.randrange(3, accounts)
            j = rng.randrange(3, accounts)
            while j == i:
                j = rng.randrange(3, accounts)
            transfer(acct[i], acct[j])
        drive(45.0 * hb)                  # past the lock TTL
        # blind transfer a0 -> a2: basis read NOW (an expired foreign
        # lock still sits on a0 — the dirty-read store serves its
        # staged, never-committed intent), written WITHOUT server-side
        # expects, basis recorded in the witness
        b0, b2 = bal(acct[0]), bal(acct[2])
        blind_amt = rng.randint(1, 9)
        _, bh = begin_txn(
            {acct[0]: str(int(b0 or b"0") - blind_amt).encode(),
             acct[2]: str(int(b2 or b"0") + blind_amt).encode()},
            {}, wire_expects=False,
            witness_expects={acct[0]: b0, acct[2]: b2},
        )
        settle(bh)

        # ---- phase 3: racing pair ------------------------------------
        blackbox.mark("txn_phase", name="race")
        r_amt = rng.randint(1, 9)
        ba3, ba4, ba5 = bal(acct[3]), bal(acct[4]), bal(acct[5])
        _, rh1 = begin_txn(
            {acct[3]: str(int(ba3 or b"0") - r_amt).encode(),
             acct[4]: str(int(ba4 or b"0") + r_amt).encode()},
            {acct[3]: ba3, acct[4]: ba4},
        )
        # begun back-to-back: rh1's locks are not APPLIED yet, so the
        # conflict check passes and BOTH prewrite — log order picks
        # the a4 lock winner, the loser must abort (lock_lost)
        _, rh2 = begin_txn(
            {acct[4]: str(int(ba4 or b"0") - r_amt).encode(),
             acct[5]: str(int(ba5 or b"0") + r_amt).encode()},
            {acct[4]: ba4, acct[5]: ba5},
        )
        settle(rh1, rh2)

        # ---- phase 4: nemeses mid-transaction ------------------------
        blackbox.mark("txn_phase", name="nemesis")
        killed: List[tuple] = []
        parted: List[int] = []

        def kill_mid(h) -> None:
            g = h.groups[0]
            r = eng.leader_id[g]
            if r is None:
                r = 0
            r = int(r)
            eng.fail(g, r)
            killed.append((g, r))
            nemeses.append(f"kill g{g} r{r}")
            blackbox.mark("txn_nemesis", kind="kill", group=g, replica=r)

        def part_mid(h) -> None:
            g = h.groups[-1]
            r = eng.leader_id[g]
            if r is None:
                r = 0
            r = int(r)
            rest = [x for x in range(base.n_replicas) if x != r]
            eng.partition(g, [[r], rest])
            parted.append(g)
            nemeses.append(f"partition g{g} leader {r} alone")
            blackbox.mark("txn_nemesis", kind="partition", group=g)

        def move_mid(h) -> None:
            g = h.groups[0]
            mv = eng.migrate_group(g, (eng.shard_of(g) + 1)
                                   % eng.n_shards)
            if mv is not None:
                moves.append(mv)
                nemeses.append(f"migrate g{g} -> shard {mv['dst']}")
            blackbox.mark("txn_nemesis", kind="migrate", group=g,
                          ok=mv is not None)

        i, j = rng.randrange(accounts), rng.randrange(accounts)
        while j == i:
            j = rng.randrange(accounts)
        transfer(acct[i], acct[j], mid=kill_mid)
        for g, r in killed:
            eng.recover(g, r)
        transfer(acct[j], acct[i])

        i, j = rng.randrange(accounts), rng.randrange(accounts)
        while j == i:
            j = rng.randrange(accounts)
        transfer(acct[i], acct[j], mid=part_mid)
        for g in parted:
            eng.heal_partition(g)
        transfer(acct[j], acct[i])

        i, j = rng.randrange(accounts), rng.randrange(accounts)
        while j == i:
            j = rng.randrange(accounts)
        transfer(acct[i], acct[j], mid=move_mid)
        for _ in range(2):
            i = rng.randrange(accounts)
            j = rng.randrange(accounts)
            while j == i:
                j = rng.randrange(accounts)
            transfer(acct[i], acct[j])

        # ---- phase 4b: round-16 remainder nemeses (opt-in) -----------
        if extra_nemeses:
            blackbox.mark("txn_phase", name="nemesis_extra")

            def _pick_pair():
                a = rng.randrange(accounts)
                b = rng.randrange(accounts)
                while b == a:
                    b = rng.randrange(accounts)
                return a, b

            # mem_replace: MultiEngine runs FIXED membership, so the
            # replace window is approximated the only honest way the
            # layer allows — a participant FOLLOWER fails mid-txn (the
            # removed voter) and the "replacement" rejoins on the same
            # row via log catch-up. Quorum survives (2/3 up), so the
            # transaction must ride it out, not abort.
            replaced: List[tuple] = []

            def replace_mid(h) -> None:
                g = h.groups[0]
                lead = eng.leader_id[g]
                r = next(
                    (x for x in range(base.n_replicas)
                     if x != lead and eng.alive[g, x]),
                    None,
                )
                if r is None:
                    return
                eng.fail(g, r)
                replaced.append((g, r))
                nemeses.append(
                    f"mem_replace g{g} r{r} (fixed-membership window)"
                )
                blackbox.mark("txn_nemesis", kind="mem_replace",
                              group=g, replica=r)

            i, j = _pick_pair()
            transfer(acct[i], acct[j], mid=replace_mid)
            drive(6 * hb)
            for g, r in replaced:
                eng.recover(g, r)
            transfer(acct[j], acct[i])

            # wire fault: the induced-slow follower — the wire-level
            # fault the mesh transport expresses (traffic received,
            # nothing appended, matchIndex goes stale) — for a window
            # spanning a transaction's prewrite/validate.
            slowed: List[tuple] = []

            def wire_mid(h) -> None:
                g = h.groups[-1]
                lead = eng.leader_id[g]
                r = next(
                    (x for x in range(base.n_replicas) if x != lead),
                    0,
                )
                eng.set_slow(g, r, True)
                slowed.append((g, r))
                nemeses.append(f"wire_slow g{g} r{r}")
                blackbox.mark("txn_nemesis", kind="wire_slow",
                              group=g, replica=r)

            i, j = _pick_pair()
            transfer(acct[i], acct[j], mid=wire_mid)
            drive(8 * hb)
            for g, r in slowed:
                eng.set_slow(g, r, False)
            transfer(acct[j], acct[i])

            # overload window: an open-loop burst on the single-key
            # plane — submits queue faster than the drill drives, the
            # admission gate refuses the spill (typed), lock traffic
            # keeps flowing underneath.
            burst, refused = 64, 0
            for _ in range(burst):
                key = rng.choice(skeys)
                single_count[0] += 1
                value = b"o%d" % single_count[0]
                rec = history.invoke(7000 + single_count[0], WRITE,
                                     key, value, now())
                try:
                    handle = skv.set(key, value)
                except (NotLeader, Overloaded, _T.LockConflict):
                    refused += 1
                    rec.fail(history.stamp(now()))
                else:
                    _single_pending.append((rec, handle))
            nemeses.append(f"overload burst {burst} "
                           f"({refused} refused)")
            blackbox.mark("txn_nemesis", kind="overload",
                          submitted=burst, refused=refused)
            drive(12 * hb)
            i, j = _pick_pair()
            transfer(acct[i], acct[j])

        # ---- phase 5: quiesce + grade --------------------------------
        blackbox.mark("txn_phase", name="quiesce")
        for g in range(eng.G):
            eng.heal_partition(g)
            for r in range(base.n_replicas):
                if not eng.alive[g, r]:
                    eng.recover(g, r)
        for g in range(eng.G):
            eng.run_until_leader(g, limit=3000.0)
        deadline = now() + 600.0
        while (inflight or coord._resolves) and now() < deadline:
            drive(4 * hb)
        drive(8 * hb)
        # unresolved records settle from the REPLICATED decision map —
        # the same authority a restarted coordinator replays
        for rec in records:
            if rec.status == INFO:
                d = skv.decision(rec.txn_id)
                if d is not None:
                    rec.status = OK if d[0] else FAIL
                    rec.pos = d[2] if d[0] else None
        history.close()
        final_state = {a: skv.get(a) for a in acct
                       if skv.get(a) is not None}
        observed = sum(int(v) for v in final_state.values())
        expected_total = 100 * accounts
        conserved_ok = observed == expected_total
        blackbox.mark("txn_check", txns=len(records),
                      observed=observed, expected=expected_total)
        check = check_serializable(records, final_state=final_state,
                                   initial={})
        singles = check_history(history, step_budget=step_budget)
        blackbox.mark("txn_done", verdict=check.verdict,
                      singles=singles.verdict)

    committed = sum(1 for r in records if r.status == OK)
    aborted = sum(1 for r in records if r.status == FAIL)
    unresolved = sum(1 for r in records if r.status == INFO)
    repro = (
        f"python -m raft_tpu.chaos --txn --seed {seed}"
        + (f" --broken {broken}" if broken else "")
        + (" --txn-extra" if extra_nemeses else "")
        + (" --txn-lease-reads" if lease_reads else "")
    )
    shim = type("_Shim", (), {
        "seed": seed, "cfg": base, "history": history, "obs": None,
    })()
    expected = SERIALIZABLE if broken is None else VIOLATION
    bundle_path = _maybe_bundle(
        "txn", shim, check, expected, repro, nemeses, bundle_dir,
        extra={"moves": moves, "conserved_ok": conserved_ok,
               "observed_total": observed,
               "coordinator": coord.status_snapshot()},
        force_unexpected=(broken is None and not conserved_ok),
    )
    return TxnReport(
        seed=seed, check=check, singles=singles, txns=len(records),
        committed=committed, aborted=aborted, unresolved=unresolved,
        conflicts=conflicts, single_ops=single_count[0],
        conserved_ok=conserved_ok, expected_total=expected_total,
        observed_total=observed, moves=moves, nemeses=nemeses,
        broken=broken, repro=repro,
        commit_digest=multi_commit_digest(eng),
        bundle_path=bundle_path,
        read_certs=dict(coord.read_certs) if lease_reads else {},
    )


# ------------------------------------------------- the cluster drill
@dataclasses.dataclass
class ClusterReport:
    """Result of :func:`cluster_run` — the multi-process acceptance
    drill (docs/CLUSTER.md): N REAL OS processes, each one replica
    (``cluster.child``) on its own port, tortured with the faults the
    in-process harness could only simulate — ``kill -9`` (the RAM tail
    is GONE), SIGSTOP/SIGCONT, userspace partition, an open-loop write
    burst, and restart-with-handoff on the same dirs. Every client op
    is recorded in one ``History`` stamped by the DRIVER's monotonic
    clock (one process, one clock — the real-time-order soundness
    argument), so the per-class verdicts are the same currency every
    other tier earns: LINEARIZABLE or it does not ship.

    The restart evidence is the tentpole claim: the resurrected child
    must ADOPT its prior generation's sealed segments by manifest
    (``segments_adopted >= 1`` with ``segments_resealed == 0`` — the
    durable work is never redone) and catch the cluster's commit via
    the resumable snapshot stream (``snap_chunks_in >= 1``, resumed
    from its sealed high-water mark)."""

    seed: int
    per_class: Dict[str, "CheckResult"]
    ops: int
    op_counts: Dict[str, int]
    read_classes: Dict[str, int]
    nodes: int
    kills: int
    restarts: int
    partitions: int
    pauses: int
    flood_ops: int
    generation: int          # restarted node's post-restart generation
    segments_adopted: int    # sealed segments adopted via manifest
    segments_resealed: int   # MUST stay 0: durable work never redone
    snap_chunks_in: int      # resumable-stream chunks the rejoin rode
    rejoined: bool           # restarted commit caught the cluster's
    incarnations: int        # child_start marks in the victim journal
    failovers: int           # client dead-dial failovers ridden
    statuses: Dict[int, Optional[dict]]
    base_dir: str            # where the forensics artifacts live
    repro: str

    @property
    def verdict(self) -> str:
        verdicts = [c.verdict for c in self.per_class.values()]
        if VIOLATION in verdicts:
            return VIOLATION
        if any(v != LINEARIZABLE for v in verdicts):
            return "UNDETERMINED"
        return LINEARIZABLE

    @property
    def handoff_ok(self) -> bool:
        """The durable-restart contract, in one bool."""
        return (self.generation >= 2 and self.segments_adopted >= 1
                and self.segments_resealed == 0 and self.rejoined)

    def summary(self) -> str:
        cls = {c: r.verdict for c, r in self.per_class.items()}
        return (
            f"seed={self.seed} classes={cls} ops={self.ops} "
            f"procs={self.nodes} kills={self.kills} "
            f"restarts={self.restarts} partitions={self.partitions} "
            f"pauses={self.pauses} gen={self.generation} "
            f"adopted={self.segments_adopted} "
            f"resealed={self.segments_resealed} "
            f"snap_in={self.snap_chunks_in} rejoined={self.rejoined} "
            f"failovers={self.failovers}"
        )


def cluster_run(
    seed: int,
    nodes: int = 3,
    clients: int = 3,
    keys: int = 4,
    ops_per_phase: int = 10,
    preload: int = 96,
    step_budget: int = 500_000,
    base_dir: Optional[str] = None,
    blackbox_dir: Optional[str] = None,
) -> ClusterReport:
    """The multi-process cluster drill (``--cluster``): spawn ``nodes``
    real replica processes under a :class:`ClusterSupervisor`, drive
    recorded client traffic through the wire tier, and compose the
    process nemeses in sequence:

    1. PRELOAD — enough committed writes that the hot tier spills and
       seals segments (the durable handoff needs something to hand off);
    2. steady traffic;
    3. PARTITION a follower off (userspace deny-lists), keep writing on
       the majority side, then ``kill -9`` the isolated follower — the
       composed fault: a process that was partitioned AND died;
    4. OVERLOAD — an open-loop burst of one-shot writers while the
       victim is down (also widens the log gap past ``snap_threshold``,
       so the rejoin MUST ride the resumable snapshot stream);
    5. RESTART the victim on the same dirs + port: it adopts the prior
       generation's sealed segments by manifest and streams the tail;
       the drill polls its self-published status until its commit
       catches the survivors' (the rejoin witness);
    6. SIGSTOP a follower through live traffic, SIGCONT it (the
       paused-not-dead partial failure), then a final read round.

    Ops record ``ok``/``fail``/``info`` under the wire client's typed
    exceptions (a mid-flight disconnect is ``info`` — the op may have
    committed; a typed refusal is ``fail`` — provably no effect) and
    the history is graded per read class. Raises
    :class:`raft_tpu.cluster.ClusterBroken` (fast-fail) when the
    environment cannot spawn children at all — callers translate that
    to a skip, not minutes of timeout burn."""
    import asyncio
    import time as _time

    from raft_tpu.cluster import ClusterBroken, ClusterSupervisor
    from raft_tpu.net import WireClient, WireDisconnected, WireRefused
    from raft_tpu.net.client import WireError

    base = base_dir or tempfile.mkdtemp(prefix=f"cluster-seed{seed}-")
    bdir = blackbox_dir or os.path.join(base, "blackbox")
    sup = ClusterSupervisor(
        nodes, base,
        heartbeat_s=0.05, election_timeout_s=0.4,
        snap_threshold=24, segment_entries=16, hot_entries=32,
        env={"RAFT_TPU_BLACKBOX_DIR": bdir},
    )
    history = History()
    key_pool = [f"ck{i}".encode() for i in range(keys)]
    now = _time.monotonic
    counters = [0] * (clients + 1)
    kills = restarts = partitions = pauses = 0
    flood_ops = 0
    evidence: Dict[int, Optional[dict]] = {}
    rejoined = False
    victim = -1
    failovers = 0

    _WRITE_AMBIGUOUS = (WireDisconnected, WireError, ConnectionError,
                        OSError)
    _READ_DEAD = (WireRefused, WireError, WireDisconnected,
                  ConnectionError, OSError)

    async def write_one(wc, cid: int, key: bytes, value: bytes) -> None:
        rec = history.invoke(cid, WRITE, key, value, now())
        try:
            await wc.submit(key, value)
        except WireRefused:
            rec.fail(history.stamp(now()))   # typed: provably no effect
        except _WRITE_AMBIGUOUS:
            rec.info()                        # outcome unknown
        else:
            rec.ok(history.stamp(now()))

    async def client_ops(wc, cid: int, n: int, rng) -> None:
        """One serial client: the §6.3 discipline over real processes."""
        for _ in range(n):
            key = key_pool[rng.randrange(len(key_pool))]
            p = rng.random()
            if p < 0.55:
                counters[cid] += 1
                await write_one(wc, cid, key,
                                f"c{cid}v{counters[cid]}".encode())
            else:
                cls = "session" if p > 0.85 else "linearizable"
                rec = history.invoke(cid, READ, key, None, now())
                if cls == "session":
                    rec.ryw_floor = wc.session.floor.get(0, 0)
                try:
                    out = await wc.read(key, cls=cls)
                except _READ_DEAD:
                    # an unserved read has no effect, whatever killed it
                    rec.fail(history.stamp(now()))
                else:
                    rec.read_class = out.cls
                    rec.serve_index = out.index
                    rec.ok(history.stamp(now()), out.value)

    async def preload_writes(wc, cid: int, n: int) -> None:
        for _ in range(n):
            counters[cid] += 1
            i = counters[cid]
            await write_one(wc, cid, key_pool[i % len(key_pool)],
                            f"c{cid}v{i}".encode())

    async def flood(n: int) -> int:
        """Open-loop one-shot writers against whichever node answers:
        no retries, unique client ids — the overload nemesis at the
        process tier (and the gap-widener for the snap rejoin)."""
        lead = sup.leader()
        host, _, port = sup.addr(lead if lead is not None
                                 else 0).rpartition(":")
        wc = await WireClient(
            host or "127.0.0.1", int(port), pool=1, retries=1,
            rng=random.Random(f"cluster-flood:{seed}"),
            addr_map=sup.addr_map(),
        ).connect()
        async def one(j: int) -> None:
            key = key_pool[j % len(key_pool)]
            await write_one(wc, 1000 + j, key, f"flood{j}".encode())
        await asyncio.gather(*[one(j) for j in range(n)])
        await wc.close()
        return n

    def _commit_of(i: int) -> int:
        st = sup.status(i)
        return int(st["commit"]) if st else 0

    async def main() -> None:
        nonlocal kills, restarts, partitions, pauses, flood_ops
        nonlocal evidence, rejoined, victim, failovers
        wcs = []
        for cid in range(1, clients + 1):
            host, _, port = sup.addr((cid - 1) % nodes).rpartition(":")
            wcs.append(await WireClient(
                host or "127.0.0.1", int(port), pool=1, retries=40,
                max_backoff_s=0.25,
                rng=random.Random(f"cluster:{seed}:conn{cid}"),
                addr_map=sup.addr_map(),
            ).connect())
        rngs = [random.Random(f"cluster:{seed}:{cid}")
                for cid in range(1, clients + 1)]

        # ---- phase 0: preload — seal segments to hand off later -----
        per = max(1, preload // clients)
        blackbox.mark("cluster_preload", writes=per * clients)
        await asyncio.gather(*[
            preload_writes(wc, cid + 1, per)
            for cid, wc in enumerate(wcs)
        ])
        # ---- phase 1: steady traffic --------------------------------
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        blackbox.mark("cluster_steady_done", ops=len(history))
        # ---- phase 2: partition a follower, then kill -9 it ---------
        lead = sup.leader()
        victim = next(i for i in range(nodes)
                      if i != (lead if lead is not None else 0))
        majority = [i for i in range(nodes) if i != victim]
        sup.partition([majority, [victim]])
        partitions += 1
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        sup.kill9(victim)
        kills += 1
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        sup.heal()
        # ---- phase 3: open-loop burst while the victim is down ------
        flood_ops = await flood(32)
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        # ---- phase 4: restart-with-handoff --------------------------
        target = max(_commit_of(i) for i in majority)
        sup.restart(victim)
        restarts += 1
        deadline = now() + 15.0
        while now() < deadline:
            st = sup.status(victim)
            if (st and st.get("generation", 1) >= 2
                    and int(st.get("commit", 0)) >= target):
                rejoined = True
                break
            await asyncio.sleep(0.1)
        blackbox.mark("cluster_rejoin", node=victim, rejoined=rejoined,
                      target=target)
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        # ---- phase 5: SIGSTOP a follower through live traffic -------
        lead = sup.leader()
        candidates = [i for i in range(nodes)
                      if i != (lead if lead is not None else 0)
                      and sup.alive(i)]
        # prefer a follower that is NOT the freshly restarted victim:
        # pausing mid-catch-up is a different drill than paused-not-dead
        paused = next((i for i in candidates if i != victim),
                      candidates[0])

        async def pause_cycle() -> None:
            sup.pause(paused)
            await asyncio.sleep(0.8)
            sup.resume(paused)

        pauses += 1
        await asyncio.gather(pause_cycle(), *[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        # ---- quiesce + evidence -------------------------------------
        for wc in wcs:
            failovers += wc.stats.get("failovers", 0)
            await wc.close()
        await asyncio.sleep(0.7)   # one status-publish period
        evidence = {i: sup.status(i) for i in range(nodes)}

    with blackbox.journal_for(f"cluster_seed{seed}", bdir):
        blackbox.mark("cluster_run", seed=seed, nodes=nodes)
        try:
            sup.start_all()
            asyncio.run(main())
        finally:
            sup.stop_all()
        history.close()
        blackbox.mark("check_history", ops=len(history))
        per_class = check_read_classes(history, step_budget=step_budget)
        blackbox.mark("check_done", verdicts={
            c: r.verdict for c, r in per_class.items()
        })

    vstat = evidence.get(victim) or {}
    tier = vstat.get("tier", {})
    incarnations = 0
    try:
        marks = blackbox.read_journal(os.path.join(
            bdir, f"journal_cluster-n{victim}.jsonl"))
        incarnations = sum(1 for m in marks
                           if m.get("phase") == "child_start")
    except Exception:
        pass
    counts: Dict[str, int] = {}
    for rec in history.ops:
        c = getattr(rec, "read_class", None)
        if c:
            counts[c] = counts.get(c, 0) + 1
    return ClusterReport(
        seed=seed,
        per_class=per_class,
        ops=len(history),
        op_counts=history.counts(),
        read_classes=counts,
        nodes=nodes,
        kills=kills,
        restarts=restarts,
        partitions=partitions,
        pauses=pauses,
        flood_ops=flood_ops,
        generation=int(vstat.get("generation", 0)),
        segments_adopted=int(tier.get("segments_adopted", 0)),
        segments_resealed=int(tier.get("segments_resealed", -1)),
        snap_chunks_in=int(vstat.get("snap_chunks_in", 0)),
        rejoined=rejoined,
        incarnations=incarnations,
        failovers=failovers,
        statuses=evidence,
        base_dir=base,
        repro=f"python -m raft_tpu.chaos --cluster --seed {seed}",
    )


# ---------------------------------------- the cluster storage drill
@dataclasses.dataclass
class ClusterStorageReport:
    """Result of :func:`cluster_storage_run` — the lying-disk nemesis
    over the multi-process cluster tier (docs/CLUSTER.md storage-fault
    model): every durable write the replicas make goes through the
    ``FaultyIO`` VFS seam, and the drill composes seed-driven torn
    writes, fsync stalls, a disk-full window, post-kill media rot
    (mid-file WAL bit flip, torn manifest, flipped sealed shard), and
    an fsync-EIO fail-stop with the process faults the cluster drill
    already owns (partition, ``kill -9``, restart-with-handoff).

    The healthy run must come back LINEARIZABLE per read class WITH
    the recovery receipts: the victim truncated its WAL at the first
    bad CRC (never skipped past it), rode the ``manifest.json.prev``
    fallback, reconstructed the flipped shard through the RS decode,
    the leader shed the full-disk window as typed refusals, and the
    EIO'd node FAIL-STOPPED — death certificate published, exit 97,
    and ZERO fsync calls after the EIO (the fsyncgate contract).

    The broken variants are the teeth check: ``fsync_lies`` (acks ride
    fsyncs that never persisted — a cluster-wide kill -9 must surface
    the lost acked writes as a checker VIOLATION) and
    ``wal_skip_corrupt`` (replay skips a corrupt record, silently
    shifting every later index past Raft's (index, term) checks — the
    commit-digest plane must catch the divergence). A broken run
    SUCCEEDS only when ``caught``."""

    seed: int
    broken: Optional[str]
    per_class: Dict[str, "CheckResult"]
    ops: int
    op_counts: Dict[str, int]
    nodes: int
    kills: int
    restarts: int
    partitions: int
    generation: int           # torn victim's post-restart generation
    segments_adopted: int
    segments_resealed: int    # MUST stay 0 even off manifest.json.prev
    rejoined: bool
    wal_truncated: int        # records dropped at the first bad CRC
    manifest_fallbacks: int   # recovery rode manifest.json.prev
    segment_reconstructs: int  # flipped shard repaired via RS decode
    disk_full_sheds: int      # typed refusals during the full window
    stalls: int               # fsync-stall windows the victim absorbed
    eio_cert: Optional[dict]  # the fail-stopped node's death.json
    eio_exit: Optional[int]   # its exit code (97 = fail-stop contract)
    fsync_after_eio: int      # MUST stay 0: no fsync retry after EIO
    digest_ok: bool           # commit digests agree at shared ckpts
    digest_detail: str
    caught: Optional[bool]    # broken runs: the harness saw the lie
    caught_by: str
    statuses: Dict[int, Optional[dict]]
    base_dir: str
    repro: str

    @property
    def verdict(self) -> str:
        verdicts = [c.verdict for c in self.per_class.values()]
        if VIOLATION in verdicts:
            return VIOLATION
        if any(v != LINEARIZABLE for v in verdicts):
            return "UNDETERMINED"
        return LINEARIZABLE

    @property
    def handoff_ok(self) -> bool:
        return (self.generation >= 2 and self.segments_adopted >= 1
                and self.segments_resealed == 0 and self.rejoined)

    @property
    def fail_stop_ok(self) -> bool:
        """The fsyncgate contract in one bool: the EIO'd node died
        distinctly (exit 97), published its own death certificate, and
        never called fsync again after the error."""
        return (self.eio_cert is not None and self.eio_exit == 97
                and self.fsync_after_eio == 0)

    @property
    def storage_ok(self) -> bool:
        """Every recovery receipt the healthy run must produce."""
        return (self.wal_truncated >= 1 and self.manifest_fallbacks >= 1
                and self.segment_reconstructs >= 1
                and self.disk_full_sheds >= 1 and self.stalls >= 1
                and self.fail_stop_ok and self.digest_ok)

    def summary(self) -> str:
        cls = {c: r.verdict for c, r in self.per_class.items()}
        core = (
            f"seed={self.seed} classes={cls} ops={self.ops} "
            f"gen={self.generation} adopted={self.segments_adopted} "
            f"resealed={self.segments_resealed} rejoined={self.rejoined} "
            f"wal_trunc={self.wal_truncated} "
            f"manifest_fb={self.manifest_fallbacks} "
            f"reconstructs={self.segment_reconstructs} "
            f"full_sheds={self.disk_full_sheds} stalls={self.stalls} "
            f"fail_stop={self.fail_stop_ok} digest_ok={self.digest_ok}"
        )
        if self.broken:
            return (f"{core} broken={self.broken} caught={self.caught} "
                    f"by={self.caught_by}")
        return core


def _digest_agreement(
    statuses: Dict[int, Optional[dict]],
) -> Tuple[bool, str]:
    """Compare commit-digest checkpoints across nodes: every shared
    checkpoint index must carry the same digest (replicas that applied
    the same prefix MUST agree byte-for-byte). Returns (ok, detail);
    zero overlap is ok=True with a detail saying so."""
    ckpts: Dict[int, Dict[int, int]] = {}
    for i, st in statuses.items():
        if st:
            ckpts[i] = {int(idx): int(d)
                        for idx, d in st.get("digest_ckpts", [])}
    overlap = 0
    for i in ckpts:
        for j in ckpts:
            if j <= i:
                continue
            for idx in ckpts[i].keys() & ckpts[j].keys():
                overlap += 1
                if ckpts[i][idx] != ckpts[j][idx]:
                    return False, (
                        f"digest DIVERGED at idx {idx}: node {i} "
                        f"{ckpts[i][idx]:#x} != node {j} "
                        f"{ckpts[j][idx]:#x}")
    if overlap == 0:
        return True, "no shared checkpoint index"
    return True, f"{overlap} shared checkpoints agree"


def cluster_storage_run(
    seed: int,
    nodes: int = 3,
    clients: int = 3,
    keys: int = 4,
    ops_per_phase: int = 10,
    preload: int = 96,
    step_budget: int = 500_000,
    base_dir: Optional[str] = None,
    blackbox_dir: Optional[str] = None,
    broken: Optional[str] = None,
) -> ClusterStorageReport:
    """The storage-fault nemesis drill (``--cluster-storage``): the
    multi-process cluster under a lying disk. Healthy composition:

    1. PRELOAD — seal segments on every node (the faults need durable
       state to chew on); all nodes boot with the ``FaultyIO`` seam
       armed benign (``disk.json`` present, no faults yet);
    2. arm TORN writes + fsync STALLS on one follower, keep traffic
       flowing (acked writes still ride real fsyncs — torn prefixes
       only ever leak UN-fsynced bytes, the crash-model guarantee);
    3. PARTITION that follower, write through the majority, then
       ``kill -9`` it — the RAM tail and the un-fsynced torn tail die;
    4. rot the corpse: flip a mid-file WAL bit, tear the WAL tail
       mid-record, truncate ``manifest.json`` half-written, flip one
       payload bit in a sealed data shard (CRC sidecar left stale);
    5. a wall-clock DISK-FULL window on the leader under traffic —
       submits shed as typed refusals (provably no effect), never
       corruption;
    6. RESTART the victim on the rotten dirs: recovery must truncate
       the WAL at the first bad CRC, fall back to
       ``manifest.json.prev``, reconstruct the flipped shard through
       the RS decode, and rejoin without resealing adopted work;
    7. arm fsync-EIO on the OTHER follower mid-run: its next WAL fsync
       fail-stops the process (death certificate, exit 97, no fsync
       retry — fsyncgate), then restart it clean;
    8. final traffic + quiesce; per-class check + cross-node commit-
       digest comparison.

    ``broken="fsync_lies"`` / ``broken="wal_skip_corrupt"`` run the
    deliberately broken storage layers instead; see the report class.
    Raises :class:`raft_tpu.cluster.ClusterBroken` when the
    environment cannot spawn children at all."""
    import asyncio
    import time as _time

    from raft_tpu.cluster import ClusterBroken, ClusterSupervisor
    from raft_tpu.cluster.storage import (
        flip_file_bit, flip_sealed_shard, read_disk_stats,
        tear_file_tail, torn_truncate, write_plan,
    )
    from raft_tpu.net import WireClient, WireDisconnected, WireRefused
    from raft_tpu.net.client import WireError

    assert broken in (None, "fsync_lies", "wal_skip_corrupt"), broken
    base = base_dir or tempfile.mkdtemp(
        prefix=f"cluster-storage-seed{seed}-")
    bdir = blackbox_dir or os.path.join(base, "blackbox")
    rng = random.Random(f"cluster-storage:{seed}")
    env = {"RAFT_TPU_BLACKBOX_DIR": bdir}
    if broken == "wal_skip_corrupt":
        env["RAFT_TPU_WAL_SKIP_CORRUPT"] = "1"
    # broken variants keep every write in the WAL + RAM (no sealing):
    # fsync_lies must be able to LOSE the acked writes wholesale, and
    # wal_skip_corrupt needs the whole log replayed from the WAL
    hot = 32 if broken is None else 128
    snap = 24 if broken is None else 10_000
    sup = ClusterSupervisor(
        nodes, base,
        heartbeat_s=0.05, election_timeout_s=0.4,
        snap_threshold=snap, segment_entries=16, hot_entries=hot,
        # recovering under injection is EXPECTED to include rough
        # starts; the death-certificate exemption plus extra headroom
        # keeps the crash-loop verdict for genuinely broken envs
        fast_fail=6,
        env=env,
    )
    if broken != "wal_skip_corrupt":
        # arm the VFS seam on every node from first boot (benign until
        # a phase rewrites the plan; fsync_lies starts lying at once)
        plan = {"seed": seed}
        if broken == "fsync_lies":
            plan["fsync_lies"] = True
        for i in range(nodes):
            write_plan(sup.node_dir(i), plan)

    history = History()
    key_pool = [f"sk{i}".encode() for i in range(keys)]
    now = _time.monotonic
    counters = [0] * (clients + 1)
    kills = restarts = partitions = 0
    evidence: Dict[int, Optional[dict]] = {}
    rejoined = False
    victim = eio_node = full_node = -1
    eio_cert: Optional[dict] = None
    eio_exit: Optional[int] = None
    fsync_after_eio = -1
    stalls = 0
    caught: Optional[bool] = None
    caught_by = ""
    digest_ok, digest_detail = True, ""

    _WRITE_AMBIGUOUS = (WireDisconnected, WireError, ConnectionError,
                        OSError)
    _READ_DEAD = (WireRefused, WireError, WireDisconnected,
                  ConnectionError, OSError)

    async def write_one(wc, cid: int, key: bytes, value: bytes) -> None:
        rec = history.invoke(cid, WRITE, key, value, now())
        try:
            await wc.submit(key, value)
        except WireRefused:
            rec.fail(history.stamp(now()))   # typed: provably no effect
        except _WRITE_AMBIGUOUS:
            rec.info()                        # outcome unknown
        else:
            rec.ok(history.stamp(now()))

    async def client_ops(wc, cid: int, n: int, crng) -> None:
        for _ in range(n):
            key = key_pool[crng.randrange(len(key_pool))]
            p = crng.random()
            if p < 0.55:
                counters[cid] += 1
                await write_one(wc, cid, key,
                                f"c{cid}v{counters[cid]}".encode())
            else:
                cls = "session" if p > 0.85 else "linearizable"
                rec = history.invoke(cid, READ, key, None, now())
                if cls == "session":
                    rec.ryw_floor = wc.session.floor.get(0, 0)
                try:
                    out = await wc.read(key, cls=cls)
                except _READ_DEAD:
                    rec.fail(history.stamp(now()))
                else:
                    rec.read_class = out.cls
                    rec.serve_index = out.index
                    rec.ok(history.stamp(now()), out.value)

    async def preload_writes(wc, cid: int, n: int) -> None:
        for _ in range(n):
            counters[cid] += 1
            i = counters[cid]
            await write_one(wc, cid, key_pool[i % len(key_pool)],
                            f"c{cid}v{i}".encode())

    async def read_round(wc, cid: int) -> None:
        for key in key_pool:
            rec = history.invoke(cid, READ, key, None, now())
            try:
                out = await wc.read(key, cls="linearizable")
            except _READ_DEAD:
                rec.fail(history.stamp(now()))
            else:
                rec.read_class = out.cls
                rec.serve_index = out.index
                rec.ok(history.stamp(now()), out.value)

    def _commit_of(i: int) -> int:
        st = sup.status(i)
        return int(st["commit"]) if st else 0

    async def _connect(cid: int):
        host, _, port = sup.addr((cid - 1) % nodes).rpartition(":")
        return await WireClient(
            host or "127.0.0.1", int(port), pool=1, retries=40,
            max_backoff_s=0.25,
            rng=random.Random(f"cluster-storage:{seed}:conn{cid}"),
            addr_map=sup.addr_map(),
        ).connect()

    def _corrupt_dead_victim() -> None:
        """Phase 4: media rot on the killed victim's durable files —
        the recovery paths, not steady state, are on trial."""
        ndir = sup.node_dir(victim)
        wal = os.path.join(ndir, "wal.bin")
        pos = flip_file_bit(wal, rng)                 # mid-file rot
        torn = tear_file_tail(wal, 37)                # mid-record tear
        manifest = os.path.join(ndir, "segments", "manifest.json")
        m_torn = torn_truncate(manifest)              # half-written
        shard = flip_sealed_shard(
            os.path.join(ndir, "segments"), rng)      # stale CRC
        blackbox.mark("storage_rot", node=victim, wal_flip_at=pos,
                      wal_torn_to=torn, manifest_torn=m_torn,
                      shard=shard)

    async def main_healthy() -> None:
        nonlocal kills, restarts, partitions, evidence, rejoined
        nonlocal victim, eio_node, full_node, eio_cert, eio_exit
        nonlocal fsync_after_eio, stalls
        wcs = [await _connect(cid) for cid in range(1, clients + 1)]
        rngs = [random.Random(f"cluster-storage:{seed}:{cid}")
                for cid in range(1, clients + 1)]

        # ---- phase 1: preload — seal segments on every node ---------
        per = max(1, preload // clients)
        blackbox.mark("storage_preload", writes=per * clients)
        await asyncio.gather(*[
            preload_writes(wc, cid + 1, per)
            for cid, wc in enumerate(wcs)
        ])
        # ---- phase 2: torn writes + fsync stalls on a follower ------
        lead = sup.leader()
        lead = lead if lead is not None else 0
        followers = [i for i in range(nodes) if i != lead]
        victim, eio_node = followers[0], followers[-1]
        write_plan(sup.node_dir(victim), {
            "seed": seed, "torn": True,
            "stall_every": 3, "stall_s": 0.05,
        })
        blackbox.mark("storage_arm_torn", node=victim)
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        # ---- phase 3: partition the torn victim, then kill -9 -------
        sup.partition([[i for i in range(nodes) if i != victim],
                       [victim]])
        partitions += 1
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        sup.kill9(victim)
        kills += 1
        sup.heal()
        stats = read_disk_stats(sup.node_dir(victim))
        stalls = int(stats.get("stalls", 0))
        # ---- phase 4: media rot on the corpse -----------------------
        _corrupt_dead_victim()
        # ---- phase 5: disk-full window on the leader ----------------
        full_node = sup.leader()
        full_node = full_node if full_node is not None else lead
        write_plan(sup.node_dir(full_node), {
            "seed": seed, "full_until_ts": _time.time() + 0.8,
        })
        blackbox.mark("storage_arm_full", node=full_node)
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        # window expires by wall clock; restore the benign plan and
        # let the shed submits' retries drain before the next phase
        write_plan(sup.node_dir(full_node), {"seed": seed})
        await asyncio.sleep(0.3)
        # ---- phase 6: restart the victim on the rotten dirs ---------
        write_plan(sup.node_dir(victim), {"seed": seed})  # faults off
        target = max(_commit_of(i) for i in range(nodes) if i != victim)
        sup.restart(victim)
        restarts += 1
        deadline = now() + 15.0
        while now() < deadline:
            st = sup.status(victim)
            if (st and st.get("generation", 1) >= 2
                    and int(st.get("commit", 0)) >= target):
                rejoined = True
                break
            await asyncio.sleep(0.1)
        blackbox.mark("storage_rejoin", node=victim, rejoined=rejoined,
                      target=target)
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        # ---- phase 7: fsync EIO on the other follower → fail-stop ---
        write_plan(sup.node_dir(eio_node), {"seed": seed,
                                            "eio_arm": True})
        blackbox.mark("storage_arm_eio", node=eio_node)

        async def _await_fail_stop() -> None:
            nonlocal eio_exit
            end = now() + 10.0
            while now() < end:
                if not sup.alive(eio_node):
                    p = sup.procs.get(eio_node)
                    eio_exit = p.poll() if p is not None else None
                    return
                await asyncio.sleep(0.1)

        await asyncio.gather(_await_fail_stop(), *[
            client_ops(wc, cid + 1, ops_per_phase, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        # the certificate and the no-retry proof, BEFORE the respawn
        # unlinks death.json
        eio_cert = sup.death_certificate(eio_node)
        fsync_after_eio = int(read_disk_stats(
            sup.node_dir(eio_node)).get("fsync_after_eio", -1))
        blackbox.mark("storage_fail_stop", node=eio_node,
                      exit=eio_exit, cert=bool(eio_cert))
        write_plan(sup.node_dir(eio_node), {"seed": seed})  # disk fixed
        sup.restart(eio_node)
        restarts += 1
        # ---- phase 8: final traffic + read round + quiesce ----------
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        await read_round(wcs[0], 1)
        for wc in wcs:
            await wc.close()
        await asyncio.sleep(0.7)   # one status-publish period
        evidence = {i: sup.status(i) for i in range(nodes)}

    async def main_fsync_lies() -> None:
        """Every disk lies about fsync; a cluster-wide kill -9 drops
        every acked-but-never-persisted write. The checker must see
        the loss (reads of acked keys come back empty)."""
        nonlocal kills, restarts, evidence, victim
        wcs = [await _connect(cid) for cid in range(1, clients + 1)]
        # enough acked writes to touch every key, few enough that
        # nothing seals (segment writes are real; the WAL is the lie)
        await asyncio.gather(*[
            preload_writes(wc, cid + 1, 8)
            for cid, wc in enumerate(wcs)
        ])
        for wc in wcs:
            await wc.close()
        victim = 0
        for i in range(nodes):
            sup.kill9(i)
            kills += 1
        blackbox.mark("storage_lies_killall", nodes=nodes)
        for i in range(nodes):
            sup.restart(i, wait_ready=False)
            restarts += 1
        for i in range(nodes):
            sup.wait_ready(i)
        # the read round that surfaces the loss
        wc = await _connect(1)
        deadline = now() + 10.0
        while now() < deadline and sup.leader() is None:
            await asyncio.sleep(0.1)
        await read_round(wc, 1)
        await wc.close()
        await asyncio.sleep(0.7)
        evidence = {i: sup.status(i) for i in range(nodes)}

    async def main_wal_skip() -> None:
        """Replay skips a corrupt WAL record (env-armed): every later
        record shifts down one index, invisible to Raft's (index,
        term) checks. The commit-digest plane must diverge."""
        nonlocal kills, restarts, evidence, victim, rejoined
        nonlocal caught, caught_by, digest_ok, digest_detail
        wcs = [await _connect(cid) for cid in range(1, clients + 1)]
        await asyncio.gather(*[
            preload_writes(wc, cid + 1, max(1, 40 // clients))
            for cid, wc in enumerate(wcs)
        ])
        lead = sup.leader()
        lead = lead if lead is not None else 0
        victim = next(i for i in range(nodes) if i != lead)
        sup.kill9(victim)
        kills += 1
        # flip one payload bit mid-WAL: the skip-not-truncate replay
        # swallows the record and shifts the suffix
        wal = os.path.join(sup.node_dir(victim), "wal.bin")
        step = 17 + 64           # _WAL_REC header + record payload
        nrec = os.path.getsize(wal) // step
        bad = max(1, int(nrec * 0.55))
        off = bad * step + 17 + 5   # inside record bad+1's payload
        with open(wal, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0x01]))
        blackbox.mark("storage_wal_flip", node=victim, record=bad + 1,
                      offset=off, records=nrec)
        sup.restart(victim)
        restarts += 1
        # a little fresh traffic so the leader's appends walk the
        # victim's shifted log forward past a checkpoint index
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase,
                       random.Random(f"cluster-storage:{seed}:{cid}"))
            for cid, wc in enumerate(wcs)
        ])
        target = max(_commit_of(i) for i in range(nodes) if i != victim)
        deadline = now() + 15.0
        while now() < deadline:
            st = sup.status(victim)
            if st and int(st.get("commit", 0)) >= target:
                rejoined = True
            evidence = {i: sup.status(i) for i in range(nodes)}
            digest_ok, digest_detail = _digest_agreement(evidence)
            if not digest_ok:
                break
            await asyncio.sleep(0.2)
        for wc in wcs:
            await wc.close()
        await asyncio.sleep(0.7)
        evidence = {i: sup.status(i) for i in range(nodes)}
        ok2, det2 = _digest_agreement(evidence)
        if not ok2:
            digest_ok, digest_detail = ok2, det2
        skipped = int((evidence.get(victim) or {})
                      .get("wal_skipped_corrupt", 0))
        caught = (not digest_ok) and skipped >= 1
        caught_by = "digest" if caught else ""
        blackbox.mark("storage_skip_verdict", caught=caught,
                      skipped=skipped, detail=digest_detail)

    mains = {None: main_healthy, "fsync_lies": main_fsync_lies,
             "wal_skip_corrupt": main_wal_skip}
    with blackbox.journal_for(f"cluster_storage_seed{seed}", bdir):
        blackbox.mark("cluster_storage_run", seed=seed, nodes=nodes,
                      broken=broken)
        try:
            sup.start_all()
            asyncio.run(mains[broken]())
        finally:
            sup.stop_all()
        history.close()
        blackbox.mark("check_history", ops=len(history))
        per_class = check_read_classes(history, step_budget=step_budget)
        blackbox.mark("check_done", verdicts={
            c: r.verdict for c, r in per_class.items()
        })

    if broken is None:
        digest_ok, digest_detail = _digest_agreement(evidence)
    elif broken == "fsync_lies":
        verdicts = [c.verdict for c in per_class.values()]
        caught = VIOLATION in verdicts
        caught_by = "checker" if caught else ""
        digest_detail = "n/a (fsync_lies)"

    vstat = evidence.get(victim) or {}
    tier = vstat.get("tier", {})
    flag = {"fsync_lies": " --broken fsync_lies",
            "wal_skip_corrupt": " --broken wal_skip_corrupt"}
    return ClusterStorageReport(
        seed=seed,
        broken=broken,
        per_class=per_class,
        ops=len(history),
        op_counts=history.counts(),
        nodes=nodes,
        kills=kills,
        restarts=restarts,
        partitions=partitions,
        generation=int(vstat.get("generation", 0)),
        segments_adopted=int(tier.get("segments_adopted", 0)),
        segments_resealed=int(tier.get("segments_resealed", -1)),
        rejoined=rejoined,
        wal_truncated=int(vstat.get("wal_truncated_records", 0)),
        manifest_fallbacks=int(tier.get("manifest_fallbacks", 0)),
        segment_reconstructs=int(tier.get("segment_reconstructs", 0)),
        disk_full_sheds=int(
            (evidence.get(full_node) or {}).get("disk_full_shed", 0)),
        stalls=stalls,
        eio_cert=eio_cert,
        eio_exit=eio_exit,
        fsync_after_eio=fsync_after_eio,
        digest_ok=digest_ok,
        digest_detail=digest_detail,
        caught=caught,
        caught_by=caught_by,
        statuses=evidence,
        base_dir=base,
        repro=(f"python -m raft_tpu.chaos --cluster-storage "
               f"--seed {seed}{flag.get(broken, '')}"),
    )


# ---------------------------------------- the cluster network drill
@dataclasses.dataclass
class ClusterNetReport:
    """Result of :func:`cluster_net_run` — the lying-NETWORK nemesis
    over the multi-process cluster tier (docs/CLUSTER.md network-fault
    model): every peer byte rides the ``cluster/netfault.py`` seam,
    and the drill composes seed-driven latency + jitter, a bandwidth
    trickle, mid-frame connection tears, duplicate + reordered +
    cross-redial-replayed delivery, and post-header bit corruption
    with an ASYMMETRIC partition of the leader (its sends deliver, its
    replies vanish — the send-only-leader wedge) and the process
    faults the cluster tier already owns (``kill -9``,
    restart-with-handoff).

    The healthy run must come back LINEARIZABLE per read class WITH
    the wire receipts: corruption was INJECTED and every corrupted
    frame was DROPPED at the CRC check (never decoded into the log —
    commit digests still agree), connections were torn and redialed,
    duplicated/reordered replies were counted as zero lease evidence
    (``stale_round_ignored``), and the asymmetrically-partitioned
    leader DEMOTED itself (CheckQuorum) so a new leader rose within
    the liveness window.

    The broken variants are the teeth check: ``peer_no_crc`` (CRC
    negotiation disabled — injected corruption is accepted and the
    commit-digest plane must diverge) and ``lease_stale_round``
    (append replies credit lease evidence at ARRIVAL time regardless
    of round — delayed in-flight replies stretch a deposed leader's
    lease past the next election and the per-class checker must flag
    the stale read). A broken run SUCCEEDS only when ``caught``."""

    seed: int
    broken: Optional[str]
    per_class: Dict[str, "CheckResult"]
    ops: int
    op_counts: Dict[str, int]
    nodes: int
    kills: int
    restarts: int
    partitions: int
    frames_delayed: int       # releases scheduled late (latency/bw)
    frames_dup: int
    frames_reordered: int
    frames_replayed: int      # cross-redial-incarnation duplicates
    conns_torn: int           # mid-frame cut + FIN
    corrupt_injected: int     # bit flips the nemesis put on the wire
    corrupt_dropped: int      # frames the CRC check refused to decode
    stale_round_ignored: int  # dup/reordered replies credited ZERO
    demotions: int            # CheckQuorum step-downs (asym leader)
    reelected: bool
    reelect_s: float          # asym partition -> new leader wall time
    dialer_drops: int         # bounded-buffer frame drops
    redials: int
    generation: int           # kill -9 victim's post-restart generation
    segments_adopted: int
    rejoined: bool
    digest_ok: bool
    digest_detail: str
    caught: Optional[bool]    # broken runs: the harness saw the lie
    caught_by: str
    statuses: Dict[int, Optional[dict]]
    base_dir: str
    repro: str

    @property
    def verdict(self) -> str:
        verdicts = [c.verdict for c in self.per_class.values()]
        if VIOLATION in verdicts:
            return VIOLATION
        if any(v != LINEARIZABLE for v in verdicts):
            return "UNDETERMINED"
        return LINEARIZABLE

    @property
    def handoff_ok(self) -> bool:
        return (self.generation >= 2 and self.segments_adopted >= 1
                and self.rejoined)

    @property
    def net_ok(self) -> bool:
        """Every wire receipt the healthy run must produce."""
        return (self.frames_delayed >= 1 and self.frames_dup >= 1
                and self.conns_torn >= 1 and self.redials >= 1
                and self.corrupt_injected >= 1
                and self.corrupt_dropped >= 1
                and self.stale_round_ignored >= 1
                and self.demotions >= 1 and self.reelected
                and self.digest_ok)

    def summary(self) -> str:
        cls = {c: r.verdict for c, r in self.per_class.items()}
        core = (
            f"seed={self.seed} classes={cls} ops={self.ops} "
            f"delayed={self.frames_delayed} dup={self.frames_dup} "
            f"reordered={self.frames_reordered} "
            f"replayed={self.frames_replayed} torn={self.conns_torn} "
            f"corrupt={self.corrupt_injected}/{self.corrupt_dropped} "
            f"stale_ignored={self.stale_round_ignored} "
            f"demotions={self.demotions} reelected={self.reelected} "
            f"reelect_s={self.reelect_s:.2f} redials={self.redials} "
            f"drops={self.dialer_drops} gen={self.generation} "
            f"rejoined={self.rejoined} digest_ok={self.digest_ok}"
        )
        if self.broken:
            return (f"{core} broken={self.broken} caught={self.caught} "
                    f"by={self.caught_by}")
        return core


def cluster_net_run(
    seed: int,
    nodes: int = 3,
    clients: int = 3,
    keys: int = 4,
    ops_per_phase: int = 12,
    preload: int = 96,
    step_budget: int = 500_000,
    base_dir: Optional[str] = None,
    blackbox_dir: Optional[str] = None,
    broken: Optional[str] = None,
) -> ClusterNetReport:
    """The network-fault nemesis drill (``--cluster-net``): the
    multi-process cluster under a lying network. Healthy composition:

    1. PRELOAD on a clean wire — the ``net.json`` seam is armed benign
       on every node from first boot, and the per-peer ``CAP_CRC``
       latches establish while frames are intact;
    2. arm the full wire chaos on every node: latency + jitter,
       bandwidth trickle, torn frames (mid-frame cut + FIN), duplicate
       and reordered delivery, cross-redial replay, and post-header
       bit corruption — every corrupted frame must be DROPPED at the
       CRC check (counted, never decoded), every tear redialed;
    3. ASYMMETRIC partition of the leader: its appends deliver (so
       vote stickiness suppresses elections — the wedge) but every
       reply to it vanishes; CheckQuorum must demote it within an
       election timeout and a new leader must rise — the liveness
       gate;
    4. ``kill -9`` the ex-leader under live wire faults, write through
       the survivors, restart it — the catch-up stream resumes across
       torn connections from the last acked cursor;
    5. final traffic, lift the faults, quiesce; per-class check +
       cross-node commit-digest comparison + the wire receipts.

    ``broken="peer_no_crc"`` / ``broken="lease_stale_round"`` run the
    deliberately broken planes instead; see the report class. Raises
    :class:`raft_tpu.cluster.ClusterBroken` when the environment
    cannot spawn children at all."""
    import asyncio
    import time as _time

    from raft_tpu.cluster import ClusterBroken, ClusterSupervisor
    from raft_tpu.cluster.netfault import (
        merge_net_plan, read_net_stats, write_net_plan,
    )
    from raft_tpu.net import WireClient, WireDisconnected, WireRefused
    from raft_tpu.net.client import WireError

    assert broken in (None, "peer_no_crc", "lease_stale_round"), broken
    base = base_dir or tempfile.mkdtemp(
        prefix=f"cluster-net-seed{seed}-")
    bdir = blackbox_dir or os.path.join(base, "blackbox")
    env = {"RAFT_TPU_BLACKBOX_DIR": bdir}
    if broken == "peer_no_crc":
        env["RAFT_TPU_PEER_NO_CRC"] = "1"
    elif broken == "lease_stale_round":
        env["RAFT_TPU_LEASE_STALE_ROUND"] = "1"
    sup = ClusterSupervisor(
        nodes, base,
        heartbeat_s=0.05,
        # the stale-round variant ramps a reply delay under the sound
        # CheckQuorum threshold (= the election timeout); the wider
        # timeout gives the ramp honest headroom without changing what
        # is on trial (the lease clock, not the election)
        election_timeout_s=(0.6 if broken == "lease_stale_round"
                            else 0.4),
        snap_threshold=24, segment_entries=16, hot_entries=32,
        fast_fail=6,
        env=env,
    )
    for i in range(nodes):
        # the seam must exist from first boot (the child arms NetFaults
        # only when net.json is present); benign until a phase merges
        # fault keys in
        write_net_plan(sup.node_dir(i), {"seed": seed})

    history = History()
    key_pool = [f"nk{i}".encode() for i in range(keys)]
    now = _time.monotonic
    counters = [0] * (clients + 3)
    kills = restarts = partitions = 0
    evidence: Dict[int, Optional[dict]] = {}
    wire_totals: Dict[str, int] = {}
    corrupt_dropped_dead = 0     # killed incarnations' counted drops
    stale_ignored_dead = 0
    dialer_dead = {"drops": 0, "redials": 0}
    rejoined = False
    reelected = False
    reelect_s = -1.0
    demotions = 0
    victim = -1
    caught: Optional[bool] = None
    caught_by = ""
    digest_ok, digest_detail = True, ""

    #: the full healthy-run wire chaos (frame units are per-node
    #: GLOBAL every-N clocks, so cadence survives redials)
    chaos = {
        "delay_ms": 2, "jitter_ms": 3, "bw_bytes_s": 262144,
        "dup_every": 5, "reorder_every": 9, "reorder_hold_ms": 30,
        "corrupt_every": 4, "torn_every": 45, "replay_redial": True,
    }

    _WRITE_AMBIGUOUS = (WireDisconnected, WireError, ConnectionError,
                        OSError)
    _READ_DEAD = (WireRefused, WireError, WireDisconnected,
                  ConnectionError, OSError)

    def _harvest(i: int) -> None:
        """Fold one node's published wire counters into the totals —
        called before a kill (the next incarnation restarts at zero)
        and once per node at the end."""
        for k, v in read_net_stats(sup.node_dir(i)).items():
            wire_totals[k] = wire_totals.get(k, 0) + int(v)

    async def write_one(wc, cid: int, key: bytes, value: bytes) -> None:
        rec = history.invoke(cid, WRITE, key, value, now())
        try:
            await wc.submit(key, value)
        except WireRefused:
            rec.fail(history.stamp(now()))   # typed: provably no effect
        except _WRITE_AMBIGUOUS:
            rec.info()                        # outcome unknown
        else:
            rec.ok(history.stamp(now()))

    async def client_ops(wc, cid: int, n: int, crng) -> None:
        for _ in range(n):
            key = key_pool[crng.randrange(len(key_pool))]
            p = crng.random()
            if p < 0.55:
                counters[cid] += 1
                await write_one(wc, cid, key,
                                f"c{cid}v{counters[cid]}".encode())
            else:
                cls = "session" if p > 0.85 else "linearizable"
                rec = history.invoke(cid, READ, key, None, now())
                if cls == "session":
                    rec.ryw_floor = wc.session.floor.get(0, 0)
                try:
                    out = await wc.read(key, cls=cls)
                except _READ_DEAD:
                    rec.fail(history.stamp(now()))
                else:
                    rec.read_class = out.cls
                    rec.serve_index = out.index
                    rec.ok(history.stamp(now()), out.value)

    async def preload_writes(wc, cid: int, n: int) -> None:
        for _ in range(n):
            counters[cid] += 1
            i = counters[cid]
            await write_one(wc, cid, key_pool[i % len(key_pool)],
                            f"c{cid}v{i}".encode())

    async def read_round(wc, cid: int) -> None:
        for key in key_pool:
            rec = history.invoke(cid, READ, key, None, now())
            try:
                out = await wc.read(key, cls="linearizable")
            except _READ_DEAD:
                rec.fail(history.stamp(now()))
            else:
                rec.read_class = out.cls
                rec.serve_index = out.index
                rec.ok(history.stamp(now()), out.value)

    def _commit_of(i: int) -> int:
        st = sup.status(i)
        return int(st["commit"]) if st else 0

    async def _connect(cid: int, pin: Optional[int] = None,
                       retries: int = 40):
        at = pin if pin is not None else (cid - 1) % nodes
        host, _, port = sup.addr(at).rpartition(":")
        return await WireClient(
            host or "127.0.0.1", int(port), pool=1, retries=retries,
            max_backoff_s=0.25,
            rng=random.Random(f"cluster-net:{seed}:conn{cid}"),
            addr_map=sup.addr_map() if pin is None else None,
        ).connect()

    async def main_healthy() -> None:
        nonlocal kills, restarts, partitions, evidence, rejoined
        nonlocal victim, demotions, reelected, reelect_s
        nonlocal corrupt_dropped_dead, stale_ignored_dead
        wcs = [await _connect(cid) for cid in range(1, clients + 1)]
        rngs = [random.Random(f"cluster-net:{seed}:{cid}")
                for cid in range(1, clients + 1)]

        # ---- phase 1: preload on a clean wire (CRC latches set) -----
        per = max(1, preload // clients)
        blackbox.mark("net_preload", writes=per * clients)
        await asyncio.gather(*[
            preload_writes(wc, cid + 1, per)
            for cid, wc in enumerate(wcs)
        ])
        # ---- phase 2: full wire chaos on every node -----------------
        sup.net_fault(dict(chaos, seed=seed))
        blackbox.mark("net_arm_chaos", plan=chaos)
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        # ---- phase 3: asymmetric partition of the leader ------------
        lead = sup.leader()
        lead = lead if lead is not None else 0
        sup.partition_asym(lead)
        partitions += 1
        t0 = now()
        deadline = t0 + 12.0
        while now() < deadline:
            st = sup.status(lead)
            if st and int(st.get("leader_demotions", 0)) >= 1:
                demotions = int(st["leader_demotions"])
                break
            await asyncio.sleep(0.05)
        while now() < deadline:
            for j in range(nodes):
                st = sup.status(j)
                if (j != lead and st and st.get("role") == "leader"
                        and sup.alive(j)):
                    reelected = True
                    reelect_s = now() - t0
                    break
            if reelected:
                break
            await asyncio.sleep(0.05)
        blackbox.mark("net_asym_verdict", lead=lead,
                      demotions=demotions, reelected=reelected,
                      reelect_s=round(max(reelect_s, 0.0), 3))
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        sup.heal()
        # ---- phase 4: kill -9 the ex-leader under live faults -------
        victim = lead
        st = sup.status(victim) or {}
        corrupt_dropped_dead += int(st.get("peer_frames_corrupt", 0))
        stale_ignored_dead += int(st.get("stale_round_ignored", 0))
        for k in ("drops", "redials"):
            dialer_dead[k] += int((st.get("dialer") or {}).get(k, 0))
        _harvest(victim)
        sup.kill9(victim)
        kills += 1
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        target = max(_commit_of(i) for i in range(nodes) if i != victim)
        sup.restart(victim)
        restarts += 1
        deadline = now() + 20.0
        while now() < deadline:
            st = sup.status(victim)
            if (st and st.get("generation", 1) >= 2
                    and int(st.get("commit", 0)) >= target):
                rejoined = True
                break
            await asyncio.sleep(0.1)
        blackbox.mark("net_rejoin", node=victim, rejoined=rejoined,
                      target=target)
        # ---- phase 5: final traffic, lift faults, quiesce -----------
        await asyncio.gather(*[
            client_ops(wc, cid + 1, ops_per_phase // 2, rngs[cid])
            for cid, wc in enumerate(wcs)
        ])
        sup.net_fault({k: None for k in chaos})
        await read_round(wcs[0], 1)
        for wc in wcs:
            await wc.close()
        await asyncio.sleep(0.7)   # one status-publish period
        evidence = {i: sup.status(i) for i in range(nodes)}
        for i in range(nodes):
            _harvest(i)

    async def main_peer_no_crc() -> None:
        """CRC negotiation disabled on every node: injected corruption
        decodes as a legal frame, the follower applies the flipped
        record, and the commit-digest plane must diverge."""
        nonlocal evidence, digest_ok, digest_detail, caught, caught_by
        wcs = [await _connect(cid) for cid in range(1, clients + 1)]
        await asyncio.gather(*[
            preload_writes(wc, cid + 1, max(1, 40 // clients))
            for cid, wc in enumerate(wcs)
        ])
        sup.net_fault({"seed": seed, "corrupt_every": 3})
        blackbox.mark("net_arm_corrupt", crc=False)
        deadline = now() + 25.0
        while now() < deadline:
            await asyncio.gather(*[
                client_ops(wc, cid + 1, 4,
                           random.Random(f"cluster-net:{seed}:{cid}"))
                for cid, wc in enumerate(wcs)
            ])
            evidence = {i: sup.status(i) for i in range(nodes)}
            digest_ok, digest_detail = _digest_agreement(evidence)
            if not digest_ok:
                break
            await asyncio.sleep(0.2)
        for wc in wcs:
            await wc.close()
        await asyncio.sleep(0.7)
        evidence = {i: sup.status(i) for i in range(nodes)}
        ok2, det2 = _digest_agreement(evidence)
        if not ok2:
            digest_ok, digest_detail = ok2, det2
        for i in range(nodes):
            _harvest(i)
        injected = int(wire_totals.get("frames_corrupt_injected", 0))
        caught = (not digest_ok) and injected >= 1
        caught_by = "digest" if caught else ""
        blackbox.mark("net_no_crc_verdict", caught=caught,
                      injected=injected, detail=digest_detail)

    async def main_lease_stale_round() -> None:
        """Append replies credit lease evidence at ARRIVAL time (env-
        armed). A reply-delay ramp on the followers fills the wire
        with in-flight acks, then a ONE-SIDED partition (the followers
        stop talking to — and hearing — the old leader, but its own
        side stays open): the delayed acks keep arriving and keep
        refreshing the broken lease while the majority elects a new
        leader and commits a fresh write. The old leader serves the
        overwritten value as a lease read — the per-class checker must
        flag it."""
        nonlocal evidence, caught, caught_by, partitions
        wcs = [await _connect(cid) for cid in range(1, clients + 1)]
        await asyncio.gather(*[
            preload_writes(wc, cid + 1, max(1, 24 // clients))
            for cid, wc in enumerate(wcs)
        ])
        lead = sup.leader()
        lead = lead if lead is not None else 0
        followers = [i for i in range(nodes) if i != lead]
        # the ramp: each step widens the reply delay by LESS than the
        # CheckQuorum threshold, so the arrival gap at each step never
        # demotes the leader (the broken clock keeps ack ages near
        # zero in steady state — masking CheckQuorum is the bug's own
        # signature); scoped per-peer so follower<->follower traffic
        # (the coming election) stays fast
        for d in (450, 900, 1350, 1800, 2250):
            for j in followers:
                merge_net_plan(sup.node_dir(j), {
                    "seed": seed,
                    "to": {str(lead): {"delay_ms": d}},
                })
            await asyncio.sleep(0.55)
        blackbox.mark("net_stale_ramp_done", lead=lead)
        # one-sided partition: ONLY the followers deny (both their
        # sends and their receives); the old leader's side stays open
        # so the in-flight delayed acks land on it
        for j in followers:
            merge_net_plan(sup.node_dir(j), {"deny": [lead]})
        partitions += 1
        blackbox.mark("net_partition_one_sided", lead=lead)
        new_lead = None
        deadline = now() + 8.0
        while now() < deadline and new_lead is None:
            for j in followers:
                st = sup.status(j)
                if st and st.get("role") == "leader":
                    new_lead = j
                    break
            await asyncio.sleep(0.05)
        wk = key_pool[0]
        if new_lead is not None:
            wc2 = await _connect(clients + 1, pin=new_lead)
            await write_one(wc2, clients + 1, wk,
                            b"fresh-after-partition")
            await wc2.close()
        blackbox.mark("net_fresh_write", new_lead=new_lead)
        # hammer reads at the OLD leader while stale in-flight acks
        # keep its broken lease alive
        wc3 = await _connect(clients + 2, pin=lead, retries=2)
        t_end = now() + 2.2
        while now() < t_end:
            rec = history.invoke(clients + 2, READ, wk, None, now())
            try:
                out = await wc3.read(wk, cls="linearizable")
            except _READ_DEAD:
                rec.fail(history.stamp(now()))
            else:
                rec.read_class = out.cls
                rec.serve_index = out.index
                rec.ok(history.stamp(now()), out.value)
            await asyncio.sleep(0.05)
        await wc3.close()
        for wc in wcs:
            await wc.close()
        await asyncio.sleep(0.7)
        evidence = {i: sup.status(i) for i in range(nodes)}
        for i in range(nodes):
            _harvest(i)

    mains = {None: main_healthy, "peer_no_crc": main_peer_no_crc,
             "lease_stale_round": main_lease_stale_round}
    with blackbox.journal_for(f"cluster_net_seed{seed}", bdir):
        blackbox.mark("cluster_net_run", seed=seed, nodes=nodes,
                      broken=broken)
        try:
            sup.start_all()
            asyncio.run(mains[broken]())
        finally:
            sup.stop_all()
        history.close()
        blackbox.mark("check_history", ops=len(history))
        per_class = check_read_classes(history, step_budget=step_budget)
        blackbox.mark("check_done", verdicts={
            c: r.verdict for c, r in per_class.items()
        })

    if broken is None:
        digest_ok, digest_detail = _digest_agreement(evidence)
    elif broken == "lease_stale_round":
        verdicts = [c.verdict for c in per_class.values()]
        caught = VIOLATION in verdicts
        caught_by = "checker" if caught else ""
        digest_detail = "n/a (lease_stale_round)"

    def _sum_stat(key: str) -> int:
        return sum(int((st or {}).get(key, 0))
                   for st in evidence.values())

    def _sum_dialer(key: str) -> int:
        return sum(int(((st or {}).get("dialer") or {}).get(key, 0))
                   for st in evidence.values())

    vstat = evidence.get(victim) or {}
    tier = vstat.get("tier", {})
    flag = {"peer_no_crc": " --broken peer_no_crc",
            "lease_stale_round": " --broken lease_stale_round"}
    return ClusterNetReport(
        seed=seed,
        broken=broken,
        per_class=per_class,
        ops=len(history),
        op_counts=history.counts(),
        nodes=nodes,
        kills=kills,
        restarts=restarts,
        partitions=partitions,
        frames_delayed=int(wire_totals.get("frames_delayed", 0)),
        frames_dup=int(wire_totals.get("frames_dup", 0)),
        frames_reordered=int(wire_totals.get("frames_reordered", 0)),
        frames_replayed=int(wire_totals.get("frames_replayed", 0)),
        conns_torn=int(wire_totals.get("conns_torn", 0)),
        corrupt_injected=int(
            wire_totals.get("frames_corrupt_injected", 0)),
        corrupt_dropped=(_sum_stat("peer_frames_corrupt")
                         + corrupt_dropped_dead),
        stale_round_ignored=(_sum_stat("stale_round_ignored")
                             + stale_ignored_dead),
        demotions=demotions,
        reelected=reelected,
        reelect_s=reelect_s,
        dialer_drops=_sum_dialer("drops") + dialer_dead["drops"],
        redials=_sum_dialer("redials") + dialer_dead["redials"],
        generation=int(vstat.get("generation", 0)),
        segments_adopted=int(tier.get("segments_adopted", 0)),
        rejoined=rejoined,
        digest_ok=digest_ok,
        digest_detail=digest_detail,
        caught=caught,
        caught_by=caught_by,
        statuses=evidence,
        base_dir=base,
        repro=(f"python -m raft_tpu.chaos --cluster-net "
               f"--seed {seed}{flag.get(broken, '')}"),
    )
