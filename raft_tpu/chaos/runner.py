"""The torture runner: workload + nemesis + history + checker, end to end.

``torture_run`` drives a single-group ``RaftEngine`` (with a recorded
``ReplicatedKV`` workload) and ``torture_run_multi`` a key-sharded
``MultiEngine``+``Router`` stack, through a seeded nemesis schedule —
process faults, message faults, and whole-process crash /
checkpoint-restore / restart cycles with storage faults against the
durability files — then quiesces, closes the client history, and hands
it to the linearizability checker. Every random choice (workload and
nemesis alike) derives from the one seed, so a failing run's report
carries a one-line repro: ``python -m raft_tpu.chaos --seed N ...``.

Crash model. The engine is one process simulating R replicas, so a
"crash" is the loss of every replica's VOLATILE state at one instant:
queues, in-flight ops, roles, timers. Durable state is what the
durability stack had on disk — the mirrored checkpoint
(``MirroredStore``) and the vote WAL — which is exactly what
``RaftEngine.restore`` rebuilds from. The runner snapshots the durable
state at the crash instant (the archive IS the simulated disk: every
committed entry was "written" when it committed), lets the nemesis
corrupt it within the keep-one-mirror-healthy rule, restores, and
carries the virtual clock forward so history timestamps stay monotone.
Writes in flight across a crash resolve as ``info`` (they may have
committed just before the crash — the checker explores both worlds);
in-flight reads resolve as ``fail`` (a read that never returned has no
effect to account for).

Client model. Each virtual client runs ONE op at a time (serial — the
§6.3 discipline) against its own rng stream: mostly writes of fresh
values (every written value is unique, which maximizes the checker's
discriminating power: a stale read names its exact culprit), reads via
the batched ReadIndex ticket path (``submit_read``/``read_confirmed``),
and occasional deletes. ``broken="dirty_reads"`` swaps the read path
for one that serves the latest SUBMITTED (possibly uncommitted) value
without leadership confirmation — the deliberately broken variant the
checker must reject, proving the harness has teeth.
"""

from __future__ import annotations

import dataclasses
import random
import tempfile
from typing import Dict, List, Optional

from raft_tpu.chaos.checker import (
    LINEARIZABLE,
    CheckResult,
    check_history,
)
from raft_tpu.chaos.history import DELETE, READ, WRITE, History, OpRecord
from raft_tpu.chaos.nemesis import Nemesis, NemesisAction
from raft_tpu.chaos.storage import MirroredStore
from raft_tpu.chaos.transport import ChaosTransport
from raft_tpu.config import RaftConfig


@dataclasses.dataclass
class TortureReport:
    seed: int
    check: CheckResult
    ops: int
    op_counts: Dict[str, int]
    crashes: int
    msg_stats: Dict[str, int]
    nemesis_log: List[str]
    repro: str

    @property
    def verdict(self) -> str:
        return self.check.verdict

    def summary(self) -> str:
        line = (
            f"seed {self.seed}: {self.verdict} over {self.ops} ops "
            f"({self.op_counts}), {self.crashes} crash cycles, "
            f"msg {self.msg_stats}"
        )
        if self.verdict != LINEARIZABLE:
            line += f"\n  {self.check.detail}\n  REPRO: {self.repro}"
        return line


def _default_cfg(seed: int) -> RaftConfig:
    return RaftConfig(
        n_replicas=3, entry_bytes=32, batch_size=4, log_capacity=128,
        transport="single", seed=seed,
    )


class _Client:
    """One serial client: at most one op outstanding, its own rng."""

    def __init__(self, cid: int, seed: int, keys: List[bytes]):
        self.cid = cid
        self.rng = random.Random(f"client:{seed}:{cid}")
        self.keys = keys
        self.rec: Optional[OpRecord] = None
        self.ticket: Optional[int] = None   # read ticket (single-engine)
        self.seq = None                     # write seq (engine-specific)
        self.counter = 0

    def fresh_value(self) -> bytes:
        self.counter += 1
        return f"c{self.cid}v{self.counter}".encode()

    def pick(self) -> tuple:
        """(op, key, value) for the next invocation."""
        key = self.rng.choice(self.keys)
        roll = self.rng.random()
        if roll < 0.45:
            return WRITE, key, self.fresh_value()
        if roll < 0.52:
            return DELETE, key, None
        return READ, key, None


class _TortureBase:
    """Shared phase loop: invoke / drive / poll / nemesis / quiesce."""

    #: virtual seconds a client waits on one op before giving up. A
    #: write dropped across a leadership change never reads durable, and
    #: a serial client with no give-up would starve the workload for the
    #: rest of the run (seed sweeps showed 3-op histories). Giving up is
    #: recorded honestly: an abandoned write resolves ``info`` (it may
    #: STILL commit later — the unbounded interval covers that), an
    #: abandoned read ``fail`` (a read that served no value has no
    #: effect); the client then moves on.
    OP_TIMEOUT_S = 90.0

    def __init__(self, seed, phases, clients, keys, phase_s):
        self.seed = seed
        self.phases = phases
        self.phase_s = phase_s
        self.history = History()
        self.keys = [f"k{i}".encode() for i in range(keys)]
        self.clients = [_Client(c, seed, self.keys) for c in range(clients)]
        self.crashes = 0

    def _give_up(self, cl: _Client) -> bool:
        """Client-side op timeout (see OP_TIMEOUT_S); True if resolved."""
        rec = cl.rec
        if rec is None or self.now() - rec.invoke_t <= self.OP_TIMEOUT_S:
            return False
        if rec.op == READ:
            rec.fail(self.history.stamp(self.now()))
        else:
            rec.info()
        cl.rec, cl.ticket, cl.seq = None, None, None
        return True

    # engine adapters ----------------------------------------------------
    def now(self) -> float:
        raise NotImplementedError

    def drive(self, seconds: float) -> None:
        raise NotImplementedError

    def invoke(self, cl: _Client) -> None:
        raise NotImplementedError

    def poll(self, cl: _Client) -> None:
        raise NotImplementedError

    def apply_nemesis(self, act: NemesisAction) -> None:
        raise NotImplementedError

    def quiesce(self) -> None:
        raise NotImplementedError

    # the loop -----------------------------------------------------------
    def _poll_all(self) -> None:
        for cl in self.clients:
            if cl.rec is not None:
                self.poll(cl)

    def _invoke_idle(self) -> None:
        for cl in self.clients:
            if cl.rec is None:
                self.invoke(cl)

    def run_phases(self, nemesis: Nemesis) -> None:
        for _ in range(self.phases):
            self._invoke_idle()
            act = nemesis.next_action(
                self.members(), self.alive_map(), self.partitioned,
                self.now(),
            )
            self.apply_nemesis(act)
            # drive in slices so completions are stamped near the event
            # that produced them, not at phase granularity
            for _ in range(4):
                self.drive(self.phase_s / 4)
                self._poll_all()
                self._invoke_idle()
        self.quiesce()
        self.history.close()


def torture_run(
    seed: int,
    phases: int = 12,
    clients: int = 3,
    keys: int = 4,
    phase_s: float = 30.0,
    cfg: Optional[RaftConfig] = None,
    workdir: Optional[str] = None,
    crash: bool = True,
    msg_faults: bool = True,
    storage_faults: bool = True,
    broken: Optional[str] = None,
    step_budget: int = 500_000,
) -> TortureReport:
    """One full single-engine torture run; see module docstring."""
    run = _SingleTorture(
        seed, phases, clients, keys, phase_s,
        cfg or _default_cfg(seed), workdir, broken,
    )
    nemesis = Nemesis(
        seed, run.cfg.rows, allow_crash=crash, allow_msg=msg_faults,
        allow_storage=storage_faults,
    )
    run.run_phases(nemesis)
    check = check_history(run.history, step_budget=step_budget)
    flags = []
    if not crash:
        flags.append("--no-crash")
    if not msg_faults:
        flags.append("--no-msg")
    if not storage_faults:
        flags.append("--no-storage")
    if broken:
        flags.append(f"--broken {broken}")
    repro = (
        f"python -m raft_tpu.chaos --seed {seed} --phases {phases} "
        f"--clients {clients} --keys {keys} --phase-s {phase_s:g}"
        + ("".join(" " + f for f in flags))
    )
    return TortureReport(
        seed=seed, check=check, ops=len(run.history),
        op_counts=run.history.counts(), crashes=run.crashes,
        msg_stats=run.chaos_t.stats, nemesis_log=nemesis.log, repro=repro,
    )


class _SingleTorture(_TortureBase):
    def __init__(self, seed, phases, clients, keys, phase_s, cfg,
                 workdir, broken):
        super().__init__(seed, phases, clients, keys, phase_s)
        from raft_tpu.transport.device import SingleDeviceTransport

        self.cfg = cfg
        self.broken = broken
        self._tmp = None
        if workdir is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="raft_torture_")
            workdir = self._tmp.name
        self.store = MirroredStore(workdir, mirrors=2)
        self.storage_rng = random.Random(f"storage:{seed}")
        self.chaos_t = ChaosTransport(SingleDeviceTransport(cfg), seed)
        self._msg_params = None
        self.partitioned = False
        self._boot_fresh()
        # dirty-read oracle for the broken variant: key -> last value
        # SUBMITTED (not committed) — exactly the cache a naive server
        # would serve reads from without waiting for consensus
        self._dirty: Dict[bytes, Optional[bytes]] = {}

    # -------------------------------------------------------------- boot
    def _boot_fresh(self) -> None:
        from raft_tpu.examples.kv import ReplicatedKV
        from raft_tpu.raft.engine import RaftEngine

        self.engine = RaftEngine(
            self.cfg, self.chaos_t, vote_log=self.store.votelog_path
        )
        self.kv = ReplicatedKV(self.engine)
        self.engine.run_until_leader()

    def _restart(self) -> None:
        from raft_tpu.examples.kv import ReplicatedKV
        from raft_tpu.raft.engine import RaftEngine

        t0 = self.now()
        path, _, _rejected = self.store.load_best()
        old_stats = self.chaos_t.stats
        self.chaos_t = ChaosTransport(
            self._fresh_base(), self.seed * 1000 + self.crashes
        )
        for k, v in old_stats.items():   # stats survive the restart
            self.chaos_t.stats[k] += v
        self.engine = RaftEngine.restore(
            self.cfg, path, self.chaos_t,
            vote_log=self.store.votelog_path,
        )
        # carry virtual time forward: a restart must not rewind the
        # history clock (heap entries armed below t0 simply fire "now")
        self.engine.clock.now = t0
        self.kv = ReplicatedKV(self.engine, replay=True)
        if self._msg_params is not None:
            self.chaos_t.set_message_faults(*self._msg_params)
        self.partitioned = False
        self.engine.run_until_leader()

    def _fresh_base(self):
        from raft_tpu.transport.device import SingleDeviceTransport

        return SingleDeviceTransport(self.cfg)

    # ----------------------------------------------------------- adapters
    def members(self) -> List[int]:
        return [r for r in range(self.cfg.rows) if self.engine.member[r]]

    def alive_map(self) -> Dict[int, bool]:
        return {r: bool(self.engine.alive[r]) for r in range(self.cfg.rows)}

    def now(self) -> float:
        return self.engine.clock.now

    def drive(self, seconds: float) -> None:
        self.engine.run_for(seconds)

    def invoke(self, cl: _Client) -> None:
        from raft_tpu.raft.engine import LinearizableReadRefused

        op, key, value = cl.pick()
        if op == READ:
            cl.rec = self.history.invoke(cl.cid, READ, key, None, self.now())
            if self.broken == "dirty_reads":
                # deliberately broken: no leadership confirmation, no
                # apply wait — half the reads serve the latest SUBMITTED
                # (possibly uncommitted) value, half the applied state.
                # A dirty read of an in-flight write followed by an
                # applied read of the same key before it commits (or a
                # crash that loses it) is the unjustifiable pair the
                # checker must reject.
                if cl.rng.random() < 0.5 and key in self._dirty:
                    value = self._dirty[key]
                else:
                    value = self.kv.get(key)
                cl.rec.ok(self.history.stamp(self.now()), value)
                cl.rec = None
                return
            try:
                cl.ticket = self.engine.submit_read()
            except LinearizableReadRefused:
                cl.rec.fail(self.history.stamp(self.now()))   # refused before any effect
                cl.rec, cl.ticket = None, None
            return
        cl.rec = self.history.invoke(cl.cid, op, key, value, self.now())
        cl.seq = (
            self.kv.set(key, value) if op == WRITE else self.kv.delete(key)
        )
        self._dirty[key] = value if op == WRITE else None

    def poll(self, cl: _Client) -> None:
        from raft_tpu.raft.engine import LinearizableReadRefused

        if self._give_up(cl):
            return
        rec = cl.rec
        if rec.op == READ:
            if isinstance(cl.ticket, tuple):
                idx = cl.ticket[1]     # confirmed, waiting on the apply
            else:
                try:
                    idx = self.engine.read_confirmed(cl.ticket)
                except LinearizableReadRefused:
                    rec.fail(self.history.stamp(self.now()))
                    cl.rec, cl.ticket = None, None
                    return
                if idx is None:
                    return
                # confirmed; tickets are poll-once, so note the bound —
                # the value may only serve once applied state covers it
                cl.ticket = ("applied", idx)
            if self.kv.last_applied < idx:
                return
            rec.ok(self.history.stamp(self.now()), self.kv.get(rec.key))
            cl.rec, cl.ticket = None, None
            return
        if self.engine.is_durable(cl.seq):
            rec.ok(self.history.stamp(self.now()))
            cl.rec, cl.seq = None, None

    def apply_nemesis(self, act: NemesisAction) -> None:
        e = self.engine
        if act.kind == "kill":
            e.fail(act.replica)
        elif act.kind == "recover":
            e.recover(act.replica)
        elif act.kind == "slow":
            e.set_slow(act.replica, True)
        elif act.kind == "unslow":
            e.set_slow(act.replica, False)
        elif act.kind == "campaign":
            e.force_campaign(act.replica)
        elif act.kind == "partition":
            e.partition(act.groups)
            self.partitioned = True
        elif act.kind == "heal":
            e.heal_partition()
            self.partitioned = False
        elif act.kind == "plan":
            e.schedule_faults(act.plan)
        elif act.kind == "msg_on":
            self._msg_params = (act.drop, act.dup, act.delay)
            self.chaos_t.set_message_faults(*self._msg_params)
        elif act.kind == "msg_off":
            self._msg_params = None
            self.chaos_t.clear_message_faults()
        elif act.kind == "crash_restart":
            self._crash_restart(act.storage)

    def _crash_restart(self, storage: str) -> None:
        # resolve in-flight ops against the dying engine: writes may
        # have committed unobserved (info — both worlds stay open);
        # reads never returned (fail — no effect to account for)
        for cl in self.clients:
            if cl.rec is None:
                continue
            if cl.rec.op == READ:
                cl.rec.fail(self.history.stamp(self.now()))
            else:
                cl.rec.info()
            cl.rec, cl.ticket, cl.seq = None, None, None
        self.store.save(self.engine)
        if storage == "tear_votelog":
            self.store.tear_votelog(self.storage_rng)
        elif storage == "flip_bit":
            self.store.flip_bit(
                self.storage_rng.randrange(self.store.mirrors),
                self.storage_rng,
            )
        elif storage == "rollback":
            self.store.rollback(
                self.storage_rng.randrange(self.store.mirrors)
            )
        self.crashes += 1
        self._restart()

    def quiesce(self) -> None:
        """Heal every fault plane, then resolve all outstanding ops."""
        e = self.engine
        self._msg_params = None
        self.chaos_t.clear_message_faults()
        e.heal_partition()
        self.partitioned = False
        for r in range(self.cfg.rows):
            if e.member[r] and not e.alive[r]:
                e.recover(r)
            e.set_slow(r, False)
        probe = e.submit(bytes(self.cfg.entry_bytes))
        e.run_until_committed(probe, limit=3000.0)
        for _ in range(40):
            self._poll_all()
            if all(cl.rec is None for cl in self.clients):
                break
            e.run_for(4 * self.cfg.heartbeat_period)
        # anything still unresolved closes as info/fail via History.close
        for cl in self.clients:
            if cl.rec is not None and cl.rec.op == READ:
                cl.rec.fail(self.history.stamp(self.now()))
                cl.rec, cl.ticket = None, None


def torture_run_multi(
    seed: int,
    n_groups: int = 4,
    phases: int = 10,
    clients: int = 3,
    keys: int = 6,
    phase_s: float = 30.0,
    cfg: Optional[RaftConfig] = None,
    step_budget: int = 500_000,
) -> TortureReport:
    """Multi-Raft torture: the sharded Router/ShardedKV client surface
    under per-group process faults. No crash cycles or message faults —
    ``MultiEngine`` has no checkpoint/restore or pluggable transport yet
    (its module docstring scopes both); per-key histories across groups
    are the point: the Router must keep every key's subhistory
    linearizable while sibling groups fail independently."""
    run = _MultiTorture(
        seed, phases, clients, keys, phase_s, cfg, n_groups
    )
    nemesis = Nemesis(
        seed, run.cfg.n_replicas, allow_crash=False, allow_msg=False,
        allow_storage=False,
    )
    run.run_phases(nemesis)
    check = check_history(run.history, step_budget=step_budget)
    repro = (
        f"python -m raft_tpu.chaos --seed {seed} --multi "
        f"--groups {n_groups} --phases {phases} --clients {clients} "
        f"--keys {keys} --phase-s {phase_s:g}"
    )
    return TortureReport(
        seed=seed, check=check, ops=len(run.history),
        op_counts=run.history.counts(), crashes=0,
        msg_stats={}, nemesis_log=nemesis.log, repro=repro,
    )


class _MultiTorture(_TortureBase):
    def __init__(self, seed, phases, clients, keys, phase_s, cfg, n_groups):
        super().__init__(seed, phases, clients, keys, phase_s)
        from raft_tpu.examples.kv_sharded import ShardedKV
        from raft_tpu.multi.engine import MultiEngine
        from raft_tpu.multi.router import Router

        self.cfg = cfg or RaftConfig(
            n_replicas=3, entry_bytes=32, batch_size=4, log_capacity=128,
            transport="single", seed=seed,
        )
        self.engine = MultiEngine(self.cfg, n_groups)
        self.engine.seed_leaders()
        self.router = Router(self.engine)
        self.kv = ShardedKV(self.engine, self.router)
        self.partitioned = False
        self._part_group: Optional[int] = None
        self.nem_rng = random.Random(f"multi-nemesis:{seed}")

    def members(self) -> List[int]:
        return list(range(self.cfg.n_replicas))

    def alive_map(self) -> Dict[int, bool]:
        # a replica counts as dead for the kill gate if ANY group lost
        # it (faults below are applied per-group or globally)
        return {
            r: bool(self.engine.alive[:, r].all())
            for r in range(self.cfg.n_replicas)
        }

    def now(self) -> float:
        return self.engine.clock.now

    def drive(self, seconds: float) -> None:
        self.engine.run_for(seconds)

    def invoke(self, cl: _Client) -> None:
        from raft_tpu.multi.engine import NotLeader

        op, key, value = cl.pick()
        cl.rec = self.history.invoke(cl.cid, op, key, value, self.now())
        try:
            if op == READ:
                g, idx = self.router.read_index(key)
                if self.kv.last_applied[g] < idx:
                    self.drive(2 * self.cfg.heartbeat_period)
                if self.kv.last_applied[g] < idx:
                    cl.rec.fail(self.history.stamp(self.now()))   # apply lag: no value served
                else:
                    cl.rec.ok(self.history.stamp(self.now()), self.kv.get(key))
                cl.rec = None
                return
            cl.seq = (
                self.kv.set(key, value) if op == WRITE
                else self.kv.delete(key)
            )
        except NotLeader:
            # nothing was queued (submit_to_leader refuses before
            # queueing; read_index confirms nothing): provably no effect
            cl.rec.fail(self.history.stamp(self.now()))
            cl.rec, cl.seq = None, None

    def poll(self, cl: _Client) -> None:
        if cl.rec is None or cl.rec.op == READ:
            return
        if self._give_up(cl):
            return
        g, seq = cl.seq
        if self.engine.is_durable(g, seq):
            cl.rec.ok(self.history.stamp(self.now()))
            cl.rec, cl.seq = None, None

    def apply_nemesis(self, act: NemesisAction) -> None:
        e = self.engine
        rng = self.nem_rng
        g = rng.randrange(e.G)
        if act.kind == "kill":
            e.fail(g, act.replica)
        elif act.kind == "recover":
            for gg in range(e.G):
                if not e.alive[gg, act.replica]:
                    e.recover(gg, act.replica)
        elif act.kind == "slow":
            e.set_slow(g, act.replica, True)
        elif act.kind == "unslow":
            for gg in range(e.G):
                e.set_slow(gg, act.replica, False)
        elif act.kind == "campaign":
            e.force_campaign(g, act.replica)
        elif act.kind == "partition":
            self._part_group = g
            e.partition(g, act.groups)
            self.partitioned = True
        elif act.kind == "heal":
            if self._part_group is not None:
                e.heal_partition(self._part_group)
            self._part_group = None
            self.partitioned = False
        elif act.kind == "plan":
            # scope the classic fragment to one group (the multi-Raft
            # FaultEvent.group field)
            from raft_tpu.faults.plan import FaultPlan

            e.schedule_faults(FaultPlan([
                dataclasses.replace(ev, group=g) for ev in act.plan.events
            ]))

    def quiesce(self) -> None:
        e = self.engine
        for g in range(e.G):
            e.heal_partition(g)
            for r in range(self.cfg.n_replicas):
                if not e.alive[g, r]:
                    e.recover(g, r)
                e.set_slow(g, r, False)
        self.partitioned = False
        for g in range(e.G):
            e.run_until_leader(g, limit=3000.0)
        for _ in range(40):
            self._poll_all()
            if all(cl.rec is None for cl in self.clients):
                break
            e.run_for(4 * self.cfg.heartbeat_period)
