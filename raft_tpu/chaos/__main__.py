"""One-command torture repro: ``python -m raft_tpu.chaos --seed N``.

Runs one torture run (or a ``--sweep K`` batch) with the given seed and
knobs, prints each run's summary plus a JSON result line, and exits
non-zero unless every history checked LINEARIZABLE — the exact
invocation a failing run's report names as its repro.
"""

from __future__ import annotations

import argparse
import json
import sys

from raft_tpu.chaos.runner import (
    cluster_net_run,
    cluster_run,
    cluster_storage_run,
    migration_run,
    overload_run,
    reads_run,
    reconfig_run,
    segment_storage_run,
    torture_run,
    torture_run_multi,
    txn_run,
    wire_run,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.chaos",
        description="Jepsen-style torture run with linearizability check",
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sweep", type=int, default=1,
                    help="run seeds [seed, seed+sweep)")
    ap.add_argument("--phases", type=int, default=12)
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--keys", type=int, default=4)
    ap.add_argument("--phase-s", type=float, default=30.0)
    ap.add_argument("--step-budget", type=int, default=500_000)
    ap.add_argument("--multi", action="store_true",
                    help="multi-Raft Router/ShardedKV torture instead")
    ap.add_argument("--groups", type=int, default=4, help="--multi groups")
    ap.add_argument("--no-crash", action="store_true")
    ap.add_argument("--no-msg", action="store_true")
    ap.add_argument("--no-storage", action="store_true")
    ap.add_argument("--overload", action="store_true",
                    help="arm admission and let the nemesis open "
                         "open-loop arrival storms at 2-10x capacity, "
                         "composed with the other fault planes "
                         "(docs/OVERLOAD.md)")
    ap.add_argument("--membership", action="store_true",
                    help="arm the membership plane: nemesis grow/shrink/"
                         "remove-the-leader/wipe-replace cycles over a "
                         "headroom cluster, composed with the other "
                         "fault planes (docs/CHAOS.md round 9)")
    ap.add_argument("--reconfig", action="store_true",
                    help="run the deterministic reconfiguration drill "
                         "(grow via learner, shrink, leader removal, "
                         "wipe-replace) instead of a torture run; "
                         "succeeds only if the history checks "
                         "linearizable AND commit progress resumes "
                         "within the documented window after every "
                         "configuration commit")
    ap.add_argument("--migration", action="store_true",
                    help="run the deterministic group-migration drill "
                         "(a mesh-sharded MultiEngine moves groups "
                         "between shards mid-traffic, Rebalancer-"
                         "planned) instead of a torture run; succeeds "
                         "only if the history checks linearizable AND "
                         "commit progress resumes on every moved group "
                         "within the documented window; needs a multi-"
                         "device backend (virtual CPU devices work)")
    ap.add_argument("--segments", action="store_true",
                    help="run the deterministic sealed-segment storage "
                         "nemesis drill (tiered log: torn spill, bit "
                         "flip, dropped shard against RS-coded cold-"
                         "tier segments) instead of a torture run; "
                         "succeeds only if the history checks "
                         "linearizable, the lapped follower rejoins, "
                         "AND recovery rode the RS reconstruct path "
                         "(no segment lost)")
    ap.add_argument("--reads", action="store_true",
                    help="run the deterministic read scale-out drill "
                         "(leader leases under traffic, clock-skew "
                         "churn across the drift band, leader kill "
                         "with lease resumption, session reads, and "
                         "the scripted stale-probe scenario) instead "
                         "of a torture run; succeeds only if EVERY "
                         "read class passes its own consistency model "
                         "(per-class verdicts, docs/READS.md) and the "
                         "stale probe was refused; with --broken "
                         "lease_skew, succeeds only if the stale "
                         "serve was CAUGHT")
    ap.add_argument("--wire", action="store_true",
                    help="run the wire-plane drill (docs/NETWORK.md): "
                         "torture traffic driven through a REAL "
                         "loopback asyncio TCP server instead of "
                         "in-process calls, with the leader-kill and "
                         "overload nemeses composed; succeeds only if "
                         "every read class holds its contract, the "
                         "admission gate's typed refusals surfaced as "
                         "wire backpressure (shed >= 1), and clients "
                         "rode NOT_LEADER frames through the election")
    ap.add_argument("--cluster", action="store_true",
                    help="run the multi-process cluster drill "
                         "(docs/CLUSTER.md): 3 REAL OS processes, one "
                         "replica each, speaking peer frames over "
                         "loopback TCP, with kill -9 composed with a "
                         "userspace partition, an open-loop burst, "
                         "SIGSTOP/SIGCONT, and restart-with-handoff; "
                         "succeeds only if every read class holds its "
                         "contract AND the killed-and-restarted "
                         "process adopted its prior generation's "
                         "sealed segments (segments_resealed == 0) "
                         "and rejoined via the resumable snapshot "
                         "stream")
    ap.add_argument("--cluster-nodes", type=int, default=3,
                    help="--cluster process count (>= 3)")
    ap.add_argument("--cluster-storage", action="store_true",
                    help="run the storage-fault nemesis over the "
                         "multi-process cluster (docs/CLUSTER.md "
                         "storage-fault model): every durable write "
                         "rides the FaultyIO VFS seam, and torn "
                         "writes, fsync stalls, a disk-full window, "
                         "post-kill media rot (mid-file WAL bit flip, "
                         "torn manifest, flipped sealed shard), and a "
                         "mid-run fsync-EIO fail-stop compose with "
                         "partition / kill -9 / restart-with-handoff; "
                         "succeeds only if every read class holds its "
                         "contract AND every recovery receipt is "
                         "present (WAL truncated at the first bad "
                         "CRC, manifest.json.prev fallback, RS shard "
                         "reconstruct, typed disk-full sheds, death "
                         "certificate + exit 97 with ZERO post-EIO "
                         "fsyncs, commit digests agreeing at shared "
                         "checkpoints); with --broken fsync_lies or "
                         "wal_skip_corrupt, succeeds only if the lie "
                         "was CAUGHT")
    ap.add_argument("--cluster-net", action="store_true",
                    help="run the network-fault nemesis over the "
                         "multi-process cluster (docs/CLUSTER.md "
                         "network-fault model): every peer byte rides "
                         "the netfault seam, and latency + jitter, a "
                         "bandwidth trickle, torn frames, duplicate / "
                         "reordered / cross-redial-replayed delivery, "
                         "and post-header bit corruption compose with "
                         "an ASYMMETRIC partition of the leader and "
                         "kill -9 / restart-with-handoff; succeeds "
                         "only if every read class holds its contract "
                         "AND every wire receipt is present (injected "
                         "corruption all dropped at the CRC check "
                         "with commit digests agreeing, dup/reordered "
                         "replies credited as zero lease evidence, "
                         "CheckQuorum demotion then re-election "
                         "within the liveness window, torn "
                         "connections redialed, the killed ex-leader "
                         "rejoined); with --broken peer_no_crc or "
                         "lease_stale_round, succeeds only if the lie "
                         "was CAUGHT")
    ap.add_argument("--txn", action="store_true",
                    help="run the cross-group transaction drill "
                         "(docs/TXN.md): a replicated 2PC coordinator "
                         "drives validated transfers across a mesh-"
                         "sharded MultiEngine while the nemesis kills "
                         "leaders, partitions groups, and migrates a "
                         "participant mid-transaction; succeeds only "
                         "if the commit-order witness checks "
                         "SERIALIZABLE, money is conserved, AND the "
                         "single-key side-traffic checks linearizable; "
                         "with --broken txn_partial_commit or "
                         "txn_dirty_read, succeeds only if the "
                         "serializability checker CAUGHT the bug")
    ap.add_argument("--txn-extra", action="store_true",
                    help="compose the round-16 remainder nemeses into "
                         "the --txn drill (phase 4b): a mem_replace "
                         "window (participant follower out, "
                         "replacement catches up on the same row), an "
                         "induced-slow-follower wire fault, and an "
                         "open-loop overload burst through the "
                         "admission gate")
    ap.add_argument("--txn-lease-reads", action="store_true",
                    help="arm the read-plane lease path for the --txn "
                         "drill's basis reads: every transfer's "
                         "expects anchor to a leader-certified read "
                         "index (zero quorum rounds while the "
                         "participant leader holds a valid lease)")
    ap.add_argument("--read-plane", action="store_true",
                    help="arm the read scale-out plane on a torture "
                         "run: leader leases (prevote implied) plus "
                         "the clock-skew nemesis drawing rates inside "
                         "the configured drift band, composed with "
                         "the other fault planes")
    ap.add_argument("--overload-recovery", type=float, default=None,
                    metavar="MULT",
                    help="run the deterministic overload-and-recover "
                         "scenario at MULT x capacity instead of a "
                         "torture run; succeeds only if the history "
                         "checks linearizable, the queue bound held, "
                         "AND goodput recovered inside the documented "
                         "window")
    ap.add_argument("--broken",
                    choices=["dirty_reads", "commit_rewind",
                             "lease_skew", "txn_partial_commit",
                             "txn_dirty_read", "fsync_lies",
                             "wal_skip_corrupt", "peer_no_crc",
                             "lease_stale_round"],
                    default=None,
                    help="deliberately broken variant; the run SUCCEEDS "
                         "(exit 0) only if the harness catches it — "
                         "dirty_reads must be REJECTED by the offline "
                         "checker, commit_rewind (acked commits lost by "
                         "a lying storage layer; usually invisible to "
                         "the checker) must trip the ONLINE safety "
                         "auditor during the run (--audit is implied), "
                         "lease_skew (leader leases that ignore the "
                         "clock-drift bound; needs --reads) must serve "
                         "a stale read the per-class checker and/or "
                         "auditor catch, txn_partial_commit (a 2PC "
                         "coordinator that commits a transaction whose "
                         "prewrite lost its locks; needs --txn) and "
                         "txn_dirty_read (a store that serves staged "
                         "intents before the decision; needs --txn) "
                         "must both be CAUGHT by the serializability "
                         "checker, fsync_lies (a disk whose fsync "
                         "returns before durability; needs "
                         "--cluster-storage) must lose acked writes "
                         "the checker sees after a cluster-wide "
                         "kill -9, and wal_skip_corrupt (a WAL replay "
                         "that SKIPS a corrupt record instead of "
                         "truncating; needs --cluster-storage) must "
                         "trip the cross-node commit-digest plane, "
                         "peer_no_crc (frame-CRC negotiation disabled; "
                         "needs --cluster-net) must let injected wire "
                         "corruption into the log where the digest "
                         "plane catches it, and lease_stale_round (a "
                         "lease clock that credits append replies at "
                         "arrival time regardless of round; needs "
                         "--cluster-net) must serve a stale lease "
                         "read the per-class checker flags. "
                         "A passing broken run means the harness "
                         "lost its teeth")
    ap.add_argument("--audit", action="store_true",
                    help="attach the ONLINE safety plane: the "
                         "obs.audit.SafetyAuditor invariant checks "
                         "(one leader per term, monotone commit/terms, "
                         "committed-prefix CRC, per-client monotone "
                         "reads) plus the obs.slo.SloTracker burn-rate "
                         "plane — determinism-neutral; violations are "
                         "reported in the JSON result line")
    ap.add_argument("--observe", action="store_true",
                    help="attach the observability plane (flight "
                         "recorder, per-op spans, metrics registry) — "
                         "determinism-neutral; makes forensics bundles "
                         "carry the full event ring + span table")
    ap.add_argument("--observe-device", action="store_true",
                    help="additionally attach the DEVICE observability "
                         "plane (obs.device in-kernel event rings + "
                         "on-device counters; implies --observe on the "
                         "torture runners) — determinism-neutral, and "
                         "bundles gain a device_ring section")
    ap.add_argument("--observe-compile", action="store_true",
                    help="additionally attach the XLA compile-and-"
                         "memory plane (obs.compile CompileWatch + "
                         "RetraceSentinel, obs.memory census): every "
                         "trace/compile is recorded per program label, "
                         "the sentinel freezes after the warmup phase "
                         "(later hot-path compiles are typed "
                         "violations), and the device-memory census "
                         "baselines there — determinism-neutral; also "
                         "armed by env RAFT_TPU_COMPILE_SENTINEL=1")
    ap.add_argument("--bundle-dir", default=None, metavar="DIR",
                    help="write a repro bundle to DIR whenever a run "
                         "ends in anything but its expected verdict "
                         "(also honored via RAFT_TPU_BUNDLE_DIR); "
                         "inspect with python -m raft_tpu.obs --explain")
    ap.add_argument("--blackbox-dir", default=None, metavar="DIR",
                    help="write a black-box progress journal (one "
                         "append-only line-flushed .jsonl per run: "
                         "nemesis phases, crash-restore cycles, checker "
                         "milestones) to DIR — it survives an external "
                         "kill of the harness itself (also honored via "
                         "RAFT_TPU_BLACKBOX_DIR); inspect with "
                         "python -m raft_tpu.obs --explain")
    args = ap.parse_args(argv)
    if args.multi and args.broken:
        ap.error("--broken applies to the single-engine runner only")
    if args.overload_recovery is not None and (args.multi or args.broken):
        ap.error("--overload-recovery is a standalone single-engine run")
    if args.membership and args.multi:
        ap.error("--membership applies to the single-engine runner only "
                 "(MultiEngine is fixed-membership by design)")
    if args.read_plane and args.multi:
        ap.error("--read-plane applies to the single-engine runner "
                 "only (the multi engine has no PreVote yet — its "
                 "lease plane is exercised by the Router tests and "
                 "bench, not the torture nemesis)")
    if args.reconfig and (args.multi or args.broken or args.overload
                          or args.overload_recovery is not None):
        ap.error("--reconfig is a standalone single-engine drill")
    if args.migration and (args.multi or args.broken or args.overload
                           or args.reconfig
                           or args.overload_recovery is not None):
        ap.error("--migration is a standalone sharded-multi drill")
    if args.segments and (args.multi or args.broken or args.overload
                          or args.reconfig or args.migration
                          or args.overload_recovery is not None):
        ap.error("--segments is a standalone single-engine drill")
    if args.broken == "lease_skew" and not args.reads:
        ap.error("--broken lease_skew applies to the --reads drill")
    if (args.broken in ("txn_partial_commit", "txn_dirty_read")
            and not args.txn):
        ap.error("--broken %s applies to the --txn drill" % args.broken)
    if args.txn and (args.multi or args.overload or args.reconfig
                     or args.migration or args.segments
                     or args.membership or args.reads or args.wire
                     or args.broken not in (None, "txn_partial_commit",
                                            "txn_dirty_read")
                     or args.overload_recovery is not None):
        ap.error("--txn is a standalone sharded-multi drill (--broken "
                 "txn_partial_commit / txn_dirty_read are its only "
                 "compositions)")
    if (args.txn_extra or args.txn_lease_reads) and not args.txn:
        ap.error("--txn-extra / --txn-lease-reads apply to the --txn "
                 "drill")
    if args.reads and (args.multi or args.overload or args.reconfig
                       or args.migration or args.segments
                       or args.membership
                       or args.broken not in (None, "lease_skew")
                       or args.overload_recovery is not None):
        ap.error("--reads is a standalone single-engine drill "
                 "(--broken lease_skew is its one composition)")
    if args.wire and (args.multi or args.broken or args.overload
                      or args.reconfig or args.migration
                      or args.segments or args.membership or args.reads
                      or args.overload_recovery is not None):
        ap.error("--wire is a standalone drill (its leader-kill and "
                 "overload nemeses are built in)")
    if args.cluster and (args.multi or args.broken or args.overload
                         or args.reconfig or args.migration
                         or args.segments or args.membership
                         or args.reads or args.wire or args.txn
                         or args.cluster_storage or args.cluster_net
                         or args.overload_recovery is not None):
        ap.error("--cluster is a standalone multi-process drill (its "
                 "kill -9 / partition / pause / overload / restart "
                 "nemeses are built in)")
    if (args.broken in ("fsync_lies", "wal_skip_corrupt")
            and not args.cluster_storage):
        ap.error("--broken %s applies to the --cluster-storage drill"
                 % args.broken)
    if args.cluster_storage and (
            args.multi or args.overload or args.reconfig
            or args.migration or args.segments or args.membership
            or args.reads or args.wire or args.txn or args.cluster_net
            or args.broken not in (None, "fsync_lies",
                                   "wal_skip_corrupt")
            or args.overload_recovery is not None):
        ap.error("--cluster-storage is a standalone multi-process "
                 "drill (--broken fsync_lies / wal_skip_corrupt are "
                 "its only compositions)")
    if (args.broken in ("peer_no_crc", "lease_stale_round")
            and not args.cluster_net):
        ap.error("--broken %s applies to the --cluster-net drill"
                 % args.broken)
    if args.cluster_net and (
            args.multi or args.overload or args.reconfig
            or args.migration or args.segments or args.membership
            or args.reads or args.wire or args.txn
            or args.broken not in (None, "peer_no_crc",
                                   "lease_stale_round")
            or args.overload_recovery is not None):
        ap.error("--cluster-net is a standalone multi-process drill "
                 "(--broken peer_no_crc / lease_stale_round are its "
                 "only compositions)")

    ok = True
    if args.cluster_net:
        from raft_tpu.cluster import ClusterBroken

        for seed in range(args.seed, args.seed + args.sweep):
            try:
                rep = cluster_net_run(
                    seed, nodes=args.cluster_nodes,
                    clients=args.clients, keys=args.keys,
                    step_budget=args.step_budget,
                    blackbox_dir=args.blackbox_dir,
                    broken=args.broken,
                )
            except ClusterBroken as ex:
                print(json.dumps({
                    "seed": seed, "verdict": "BROKEN_ENV",
                    "error": str(ex).splitlines()[0],
                }), flush=True)
                return 1
            print(rep.summary())
            print(json.dumps({
                "seed": seed,
                "verdict": rep.verdict,
                "per_class": {c: r.verdict
                              for c, r in rep.per_class.items()},
                "ops": rep.ops,
                "op_counts": rep.op_counts,
                "kills": rep.kills,
                "restarts": rep.restarts,
                "partitions": rep.partitions,
                "frames_delayed": rep.frames_delayed,
                "frames_dup": rep.frames_dup,
                "frames_reordered": rep.frames_reordered,
                "frames_replayed": rep.frames_replayed,
                "conns_torn": rep.conns_torn,
                "corrupt_injected": rep.corrupt_injected,
                "corrupt_dropped": rep.corrupt_dropped,
                "stale_round_ignored": rep.stale_round_ignored,
                "demotions": rep.demotions,
                "reelected": rep.reelected,
                "reelect_s": rep.reelect_s,
                "dialer_drops": rep.dialer_drops,
                "redials": rep.redials,
                "generation": rep.generation,
                "segments_adopted": rep.segments_adopted,
                "rejoined": rep.rejoined,
                "digest_ok": rep.digest_ok,
                "digest_detail": rep.digest_detail,
                "broken": rep.broken,
                "caught": rep.caught,
                "caught_by": rep.caught_by,
                "base_dir": rep.base_dir,
            }), flush=True)
            if args.broken:
                # the flag's contract: a CAUGHT lie IS success
                ok = ok and bool(rep.caught)
            else:
                ok = ok and (
                    rep.verdict == "LINEARIZABLE"
                    and rep.handoff_ok
                    and rep.net_ok
                )
        return 0 if ok else 1
    if args.cluster_storage:
        from raft_tpu.cluster import ClusterBroken

        for seed in range(args.seed, args.seed + args.sweep):
            try:
                rep = cluster_storage_run(
                    seed, nodes=args.cluster_nodes,
                    clients=args.clients, keys=args.keys,
                    step_budget=args.step_budget,
                    blackbox_dir=args.blackbox_dir,
                    broken=args.broken,
                )
            except ClusterBroken as ex:
                print(json.dumps({
                    "seed": seed, "verdict": "BROKEN_ENV",
                    "error": str(ex).splitlines()[0],
                }), flush=True)
                return 1
            print(rep.summary())
            print(json.dumps({
                "seed": seed,
                "verdict": rep.verdict,
                "per_class": {c: r.verdict
                              for c, r in rep.per_class.items()},
                "ops": rep.ops,
                "op_counts": rep.op_counts,
                "kills": rep.kills,
                "restarts": rep.restarts,
                "partitions": rep.partitions,
                "generation": rep.generation,
                "segments_adopted": rep.segments_adopted,
                "segments_resealed": rep.segments_resealed,
                "rejoined": rep.rejoined,
                "wal_truncated": rep.wal_truncated,
                "manifest_fallbacks": rep.manifest_fallbacks,
                "segment_reconstructs": rep.segment_reconstructs,
                "disk_full_sheds": rep.disk_full_sheds,
                "stalls": rep.stalls,
                "eio_exit": rep.eio_exit,
                "eio_cert": rep.eio_cert,
                "fsync_after_eio": rep.fsync_after_eio,
                "digest_ok": rep.digest_ok,
                "digest_detail": rep.digest_detail,
                "broken": rep.broken,
                "caught": rep.caught,
                "caught_by": rep.caught_by,
                "base_dir": rep.base_dir,
            }), flush=True)
            if args.broken:
                # the flag's contract: a CAUGHT lie IS success
                ok = ok and bool(rep.caught)
            else:
                ok = ok and (
                    rep.verdict == "LINEARIZABLE"
                    and rep.handoff_ok
                    and rep.storage_ok
                )
        return 0 if ok else 1
    if args.cluster:
        from raft_tpu.cluster import ClusterBroken

        for seed in range(args.seed, args.seed + args.sweep):
            try:
                rep = cluster_run(
                    seed, nodes=args.cluster_nodes,
                    clients=args.clients, keys=args.keys,
                    step_budget=args.step_budget,
                    blackbox_dir=args.blackbox_dir,
                )
            except ClusterBroken as ex:
                # fast-fail: the environment cannot spawn children at
                # all — say so in the result line and stop burning time
                print(json.dumps({
                    "seed": seed, "verdict": "BROKEN_ENV",
                    "error": str(ex).splitlines()[0],
                }), flush=True)
                return 1
            print(rep.summary())
            print(json.dumps({
                "seed": seed,
                "verdict": rep.verdict,
                "per_class": {c: r.verdict
                              for c, r in rep.per_class.items()},
                "ops": rep.ops,
                "op_counts": rep.op_counts,
                "nodes": rep.nodes,
                "kills": rep.kills,
                "restarts": rep.restarts,
                "partitions": rep.partitions,
                "pauses": rep.pauses,
                "flood_ops": rep.flood_ops,
                "generation": rep.generation,
                "segments_adopted": rep.segments_adopted,
                "segments_resealed": rep.segments_resealed,
                "snap_chunks_in": rep.snap_chunks_in,
                "rejoined": rep.rejoined,
                "incarnations": rep.incarnations,
                "failovers": rep.failovers,
                "base_dir": rep.base_dir,
            }), flush=True)
            ok = ok and (
                rep.verdict == "LINEARIZABLE"
                and rep.handoff_ok
                and rep.kills >= 1
                and rep.snap_chunks_in >= 1
            )
        return 0 if ok else 1
    if args.txn:
        for seed in range(args.seed, args.seed + args.sweep):
            rep = txn_run(
                seed, n_groups=args.groups, broken=args.broken,
                step_budget=args.step_budget,
                bundle_dir=args.bundle_dir,
                blackbox_dir=args.blackbox_dir,
                extra_nemeses=args.txn_extra,
                lease_reads=args.txn_lease_reads,
            )
            print(rep.summary())
            print(json.dumps({
                "seed": seed,
                "verdict": rep.verdict,
                "singles": rep.singles.verdict,
                "txns": rep.txns,
                "committed": rep.committed,
                "aborted": rep.aborted,
                "unresolved": rep.unresolved,
                "conflicts": rep.conflicts,
                "single_ops": rep.single_ops,
                "conserved_ok": rep.conserved_ok,
                "moves": rep.moves,
                "nemeses": rep.nemeses,
                "broken": rep.broken,
                "commit_digest": rep.commit_digest,
                "bundle": rep.bundle_path,
                "read_certs": rep.read_certs,
            }), flush=True)
            if args.broken:
                # the flag's contract: a CAUGHT violation IS success
                ok = ok and rep.caught
            else:
                ok = ok and (
                    rep.verdict == "SERIALIZABLE"
                    and rep.conserved_ok
                    and rep.singles.verdict == "LINEARIZABLE"
                    and rep.committed >= 1
                )
        return 0 if ok else 1
    if args.wire:
        for seed in range(args.seed, args.seed + args.sweep):
            rep = wire_run(
                seed, clients=args.clients, keys=args.keys,
                step_budget=args.step_budget,
                blackbox_dir=args.blackbox_dir,
                bundle_dir=args.bundle_dir,
            )
            print(rep.summary())
            print(json.dumps({
                "seed": seed,
                "verdict": rep.verdict,
                "per_class": {c: r.verdict
                              for c, r in rep.per_class.items()},
                "ops": rep.ops,
                "op_counts": rep.op_counts,
                "shed_writes": rep.shed_writes,
                "not_leader_frames": rep.not_leader_frames,
                "wire_refusals": rep.wire_refusals,
                "leader_kills": rep.leader_kills,
                "net": rep.net,
                "commit_digest": rep.commit_digest,
                "traced": rep.traced,
                "client_spans": rep.client_spans,
                "server_spans": rep.server_spans,
                "bundle": rep.bundle_path,
            }), flush=True)
            ok = ok and (
                rep.verdict == "LINEARIZABLE"
                and rep.shed_writes >= 1
                and rep.not_leader_frames >= 1
            )
        return 0 if ok else 1
    if args.reads:
        for seed in range(args.seed, args.seed + args.sweep):
            rep = reads_run(
                seed, broken=args.broken,
                step_budget=args.step_budget,
                observe=True, bundle_dir=args.bundle_dir,
                blackbox_dir=args.blackbox_dir,
            )
            print(rep.summary())
            print(json.dumps({
                "seed": seed,
                "verdict": rep.verdict,
                "per_class": {c: r.verdict
                              for c, r in rep.per_class.items()},
                "lease_serves": rep.lease_serves,
                "read_index_serves": rep.read_index_serves,
                "session_serves": rep.session_serves,
                "refused_stale": rep.refused_stale,
                "stale_served": rep.stale_served,
                "audit_violations": rep.audit_violations,
                "ops": rep.ops,
            }), flush=True)
            if args.broken == "lease_skew":
                # the flag's contract: a caught stale serve IS success
                ok = ok and rep.caught
            else:
                ok = ok and (
                    rep.verdict == "LINEARIZABLE"
                    and rep.refused_stale >= 1
                    and rep.lease_serves > 0
                    and rep.session_serves > 0
                )
        return 0 if ok else 1
    if args.segments:
        for seed in range(args.seed, args.seed + args.sweep):
            rep = segment_storage_run(
                seed, step_budget=args.step_budget,
                observe=args.observe, bundle_dir=args.bundle_dir,
                blackbox_dir=args.blackbox_dir,
            )
            print(rep.summary())
            print(json.dumps({
                "seed": seed,
                "verdict": rep.verdict,
                "rejoined": rep.rejoined,
                "recovered_via_rs": rep.recovered_via_rs,
                "faults": rep.faults,
                "tier": rep.tier,
                "chunks_shipped": rep.chunks_shipped,
                "ops": rep.ops,
            }), flush=True)
            ok = ok and (
                rep.verdict == "LINEARIZABLE" and rep.rejoined
                and rep.recovered_via_rs
            )
        return 0 if ok else 1
    if args.migration:
        for seed in range(args.seed, args.seed + args.sweep):
            rep = migration_run(
                seed, n_groups=args.groups,
                clients=args.clients, keys=args.keys,
                step_budget=args.step_budget,
                observe=args.observe, bundle_dir=args.bundle_dir,
                blackbox_dir=args.blackbox_dir,
            )
            print(rep.summary())
            print(json.dumps({
                "seed": seed,
                "verdict": rep.verdict,
                "progress_ok": rep.progress_ok,
                "moves": rep.moves,
                "n_shards": rep.n_shards,
                "ops": rep.ops,
                "op_counts": rep.op_counts,
            }), flush=True)
            ok = ok and (
                rep.verdict == "LINEARIZABLE" and rep.progress_ok
            )
        return 0 if ok else 1
    if args.reconfig:
        for seed in range(args.seed, args.seed + args.sweep):
            rep = reconfig_run(
                seed, step_budget=args.step_budget,
                observe=args.observe, bundle_dir=args.bundle_dir,
                blackbox_dir=args.blackbox_dir,
            )
            print(rep.summary())
            print(json.dumps({
                "seed": seed,
                "verdict": rep.verdict,
                "availability_ok": rep.availability_ok,
                "events": rep.events,
                "promote_s": rep.promote_s,
                "replace_promote_s": rep.replace_promote_s,
                "ops": rep.ops,
                "op_counts": rep.op_counts,
            }), flush=True)
            ok = ok and (
                rep.verdict == "LINEARIZABLE" and rep.availability_ok
            )
        return 0 if ok else 1
    if args.overload_recovery is not None:
        for seed in range(args.seed, args.seed + args.sweep):
            rep = overload_run(
                seed, rate_mult=args.overload_recovery,
                step_budget=args.step_budget,
                observe=args.observe, bundle_dir=args.bundle_dir,
                blackbox_dir=args.blackbox_dir,
            )
            print(rep.summary())
            print(json.dumps({
                "seed": seed,
                "verdict": rep.verdict,
                "rate_mult": rep.rate_mult,
                "baseline_goodput": rep.baseline_goodput,
                "overload_goodput": rep.overload_goodput,
                "recovery_goodput": rep.recovery_goodput,
                "shed": rep.shed,
                "queue_depth_max": rep.queue_depth_max,
                "depth_bound": rep.depth_bound,
                "recovered_in_s": rep.recovered_in_s,
                "recovery_ok": rep.recovery_ok,
            }), flush=True)
            ok = ok and (
                rep.verdict == "LINEARIZABLE" and rep.recovery_ok
                and rep.queue_depth_max <= rep.depth_bound
            )
        return 0 if ok else 1

    audit = args.audit or args.broken == "commit_rewind"
    #   commit_rewind's whole point is a fault the offline checker
    #   usually CANNOT see (no client-visible effect): the success
    #   criterion is the online auditor tripping, so the audit plane is
    #   implied on
    expect = ("VIOLATION" if args.broken == "dirty_reads"
              else "LINEARIZABLE")
    for seed in range(args.seed, args.seed + args.sweep):
        if args.multi:
            rep = torture_run_multi(
                seed, n_groups=args.groups, phases=args.phases,
                clients=args.clients, keys=args.keys,
                phase_s=args.phase_s, overload=args.overload,
                step_budget=args.step_budget,
                observe=args.observe,
                observe_device=args.observe_device,
                audit=audit,
                observe_compile=args.observe_compile,
                bundle_dir=args.bundle_dir,
                blackbox_dir=args.blackbox_dir,
            )
        else:
            rep = torture_run(
                seed, phases=args.phases, clients=args.clients,
                keys=args.keys, phase_s=args.phase_s,
                crash=not args.no_crash, msg_faults=not args.no_msg,
                storage_faults=not args.no_storage, broken=args.broken,
                overload=args.overload, membership=args.membership,
                reads=args.read_plane,
                step_budget=args.step_budget,
                observe=args.observe,
                observe_device=args.observe_device,
                audit=audit,
                observe_compile=args.observe_compile,
                bundle_dir=args.bundle_dir,
                blackbox_dir=args.blackbox_dir,
            )
        violations = (
            rep.obs.audit.total_violations
            if rep.obs is not None and rep.obs.audit is not None else None
        )
        print(rep.summary())
        print(json.dumps({
            "seed": seed,
            "verdict": rep.verdict,
            "expected": expect,
            "ops": rep.ops,
            "op_counts": rep.op_counts,
            "crashes": rep.crashes,
            "msg_stats": rep.msg_stats,
            "shed_ops": rep.shed_ops,
            "open_loop_ops": rep.open_loop_ops,
            "membership_ops": rep.membership_ops,
            "checker_steps": rep.check.steps,
            "audit_violations": violations,
            **(
                {
                    "compiles": rep.obs.compile.total_compiles,
                    "compile_violations":
                        len(rep.obs.compile.sentinel.violations),
                }
                if rep.obs is not None and rep.obs.compile is not None
                else {}
            ),
        }), flush=True)
        if args.broken == "commit_rewind":
            ok = ok and bool(violations)
        elif args.broken:
            ok = ok and rep.verdict == expect
        else:
            ok = ok and rep.verdict == expect and not violations
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
