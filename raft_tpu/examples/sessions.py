"""Exactly-once client sessions over an at-least-once log — the standard
Raft client-session pattern (Raft dissertation §6.3), layered on the
engine's honest durability contract.

``RaftEngine.submit`` documents that entries queued across a leadership
change may be dropped, and that clients resubmit (raft/engine.py). Naive
resubmission gives AT-LEAST-ONCE application: if the ack was lost but the
entry actually committed, the retry applies twice — fine for idempotent
SETs (examples.kv), wrong for counters, appends, or transfers.

``SessionedStateMachine`` closes the loop: every operation carries a
(client id, request id); the state machine remembers the highest request
id applied per client and IGNORES re-applications, so a client can retry
blindly until durable and the operation still applies exactly once.
The dedup table is part of the state machine — rebuilt by the same log
replay that rebuilds the data, so restarts preserve exactly-once too.

``ReplicatedCounter`` is the worked non-idempotent application.
"""

from __future__ import annotations

import struct
from typing import Callable, Dict, Optional, Tuple

from raft_tpu.raft.engine import RaftEngine

_HDR = struct.Struct("<QQq")   # client_id, request_id, operand


class SessionedStateMachine:
    """Apply-stream wrapper delivering each (client, request) at most once.

    ``apply_op(operand)`` runs only for the first committed occurrence of
    a (client_id, request_id) pair; later occurrences (client retries that
    both committed) are dropped. Request ids must be monotonically
    increasing per client — the standard session contract."""

    def __init__(
        self,
        engine: RaftEngine,
        apply_op: Callable[[int], None],
        replay: bool = False,
    ):
        if engine.cfg.entry_bytes < _HDR.size:
            raise ValueError(
                f"session ops need {_HDR.size}-byte entries, "
                f"config has {engine.cfg.entry_bytes}"
            )
        self.engine = engine
        self._apply_op = apply_op
        self._last_req: Dict[int, int] = {}     # client id -> request id
        self.duplicates_dropped = 0
        engine.register_apply(self._apply, replay=replay)

    def encode(self, client_id: int, request_id: int, operand: int) -> bytes:
        if client_id == 0:
            # 0 marks padding/probe entries; an op encoded with it would
            # commit but never apply — reject at the source
            raise ValueError("client id 0 is reserved for padding entries")
        size = self.engine.cfg.entry_bytes
        body = _HDR.pack(client_id, request_id, operand)
        if len(body) > size:
            raise ValueError(f"entries are {size} bytes, op needs {len(body)}")
        return body + bytes(size - len(body))

    def last_request(self, client_id: int) -> int:
        """Highest request id applied for ``client_id`` (0 if none) —
        restart path: clients derive their next id from this."""
        return self._last_req.get(client_id, 0)

    def _apply(self, index: int, payload: bytes) -> None:
        client, req, operand = _HDR.unpack_from(payload)
        if client == 0:
            return                               # padding / probe entries
        if self._last_req.get(client, -1) >= req:
            self.duplicates_dropped += 1         # committed retry: drop
            return
        # apply BEFORE recording: a raising apply_op must not mark the op
        # applied, or every retry/replay would be dropped and the op lost
        self._apply_op(operand)
        self._last_req[client] = req


class ReplicatedCounter:
    """A non-idempotent state machine (sum of increments) with
    exactly-once semantics under blind client retries."""

    def __init__(self, engine: RaftEngine, replay: bool = False):
        self.engine = engine
        self.value = 0
        self._sm = SessionedStateMachine(engine, self._add, replay=replay)
        # replay runs synchronously above: seed the id allocator from the
        # rebuilt dedup table so a post-restart add() never reuses an
        # already-applied request id (which would be silently dropped)
        self._next_req: Dict[int, int] = dict(self._sm._last_req)

    def _add(self, operand: int) -> None:
        self.value += operand

    def add(self, client_id: int, amount: int,
            request_id: Optional[int] = None) -> Tuple[int, int]:
        """Submit an increment; returns (engine seq, request id). Safe to
        call again with the SAME request id if durability was never
        observed — the session layer deduplicates committed retries."""
        if request_id is None:
            request_id = self._next_req.get(client_id, 0) + 1
        # max, not overwrite: retrying an OLD id must not regress the
        # allocator into handing out already-used ids for new ops
        self._next_req[client_id] = max(
            self._next_req.get(client_id, 0), request_id
        )
        seq = self.engine.submit(
            self._sm.encode(client_id, request_id, amount)
        )
        return seq, request_id

    @property
    def duplicates_dropped(self) -> int:
        return self._sm.duplicates_dropped
