"""A replicated key-value store — the canonical Raft application, built
entirely on the public engine API.

The reference replicates bare random ints and never applies them to
anything (SURVEY §2: "there is no state machine"; main.go:92,149). This
example is what the missing layer looks like: operations are encoded into
fixed-size log entries, submitted through the engine, and applied to a
dict **only once committed** — so every replica of the state machine
(here, every process that replays the same log) converges to the same
map, and a read served from the applied state never shows an
un-durable write.

Usage:

    eng = RaftEngine(cfg)
    kv = ReplicatedKV(eng)
    eng.run_until_leader()
    seq = kv.set(b"color", b"green")
    eng.run_until_committed(seq)
    kv.get(b"color")                      # b"green"

Restart: build the engine with ``RaftEngine.restore`` and pass
``replay=True`` — the store rebuilds from the archived committed tail.

Entry encoding (fits one fixed-size log entry, entry_bytes >= 6):
``[op u8][klen u16][vlen u16][key][value]`` zero-padded; op 1 = SET,
op 2 = DELETE. Zero padding is self-delimiting because op 0 is invalid
(an all-zero heartbeat entry is ignored).

Ops 3-6 are CLAIMED by the transaction plane (``raft_tpu.txn.ops``:
LOCK=3, COMMIT=4, ABORT=5, DECIDE=6 — docs/TXN.md); a new plain-KV op
must start at 7. This store ignores them (unknown op = no-op on apply),
which is what lets ``txn.store.TxnShardedKV`` layer the typed entries
over the same log without forking the wire format.
"""

from __future__ import annotations

import struct
from typing import Dict, Optional

from raft_tpu.raft.engine import RaftEngine

_SET, _DELETE = 1, 2
_HDR = struct.Struct("<BHH")


def encode_op(entry_bytes: int, op: int, key: bytes, value: bytes) -> bytes:
    """One KV operation as a fixed-size log entry (module docstring
    format). Shared by ``ReplicatedKV`` and the sharded store
    (``examples.kv_sharded.ShardedKV``) so both speak one wire format."""
    body = _HDR.pack(op, len(key), len(value)) + key + value
    if len(body) > entry_bytes:
        raise ValueError(f"op needs {len(body)} bytes, entries are {entry_bytes}")
    return body + bytes(entry_bytes - len(body))


def decode_op(payload: bytes):
    """Decode one log entry back into ``(op, key, value)`` — ``(0, b"",
    None)`` for padding/heartbeat entries, ``value=None`` for deletes.
    The read-audit feed (``obs.audit``) uses this to map applied entries
    to per-key values without re-implementing the wire format."""
    op, klen, vlen = _HDR.unpack_from(payload)
    if op not in (_SET, _DELETE):
        return 0, b"", None
    key = payload[_HDR.size:_HDR.size + klen]
    if op == _DELETE:
        return op, key, None
    return op, key, payload[_HDR.size + klen:_HDR.size + klen + vlen]


def apply_op(data: Dict[bytes, bytes], payload: bytes) -> None:
    """Apply one committed entry to a dict state machine (op 0 =
    padding/heartbeat: ignore)."""
    op, klen, vlen = _HDR.unpack_from(payload)
    if op == _SET:
        k = payload[_HDR.size:_HDR.size + klen]
        data[k] = payload[_HDR.size + klen:_HDR.size + klen + vlen]
    elif op == _DELETE:
        data.pop(payload[_HDR.size:_HDR.size + klen], None)


class ReplicatedKV:
    """Dict-shaped state machine over the replicated log."""

    def __init__(self, engine: RaftEngine, replay: bool = False):
        self.engine = engine
        self._data: Dict[bytes, bytes] = {}
        self.last_applied = 0
        engine.register_apply(self._apply, replay=replay)

    # ------------------------------------------------------------ client
    def _encode(self, op: int, key: bytes, value: bytes) -> bytes:
        return encode_op(self.engine.cfg.entry_bytes, op, key, value)

    def set(self, key: bytes, value: bytes, client=None) -> int:
        """Queue a SET; returns the engine seq. Durable (and visible to
        ``get``) once the engine commits it — check
        ``engine.is_durable(seq)`` or run until committed. ``client``
        is the opaque id the admission gate's fair-share accounting
        keys on (``raft_tpu.admission``); with admission configured the
        submit may raise ``Overloaded`` before anything is queued."""
        return self.engine.submit(self._encode(_SET, key, value),
                                  client=client)

    def delete(self, key: bytes, client=None) -> int:
        return self.engine.submit(self._encode(_DELETE, key, b""),
                                  client=client)

    def get(self, key: bytes) -> Optional[bytes]:
        """Read from LOCAL applied (committed) state.

        Weaker contract than ``linearizable_get``: it never shows a
        write that could still be lost to a leadership change, but it
        can be arbitrarily STALE — on a partitioned/minority-side engine
        mirror nothing proves a fresher write hasn't committed on the
        majority side. Use ``linearizable_get`` when the read must
        reflect every write acknowledged before it was issued."""
        return self._data.get(key)

    def linearizable_get(self, key: bytes) -> Optional[bytes]:
        """Linearizable read (ReadIndex, dissertation §6.4): the engine
        confirms leadership with a quorum round and returns a read index;
        the value is served only from state applied to at least that
        index. Raises ``raft_tpu.raft.engine.LinearizableReadRefused``
        when leadership cannot be confirmed (no leader, deposed, or a
        quorum is unreachable — e.g. from the minority side of a
        partition), and ``RuntimeError`` if the apply stream is paused
        behind an archive gap below the read index."""
        idx = self.engine.read_linearizable()
        if self.last_applied < idx:
            raise RuntimeError(
                f"apply stream at {self.last_applied} has not reached "
                f"read index {idx} (archive gap)"
            )
        return self._data.get(key)

    def __len__(self) -> int:
        return len(self._data)

    # ------------------------------------------------------ state machine
    def _apply(self, index: int, payload: bytes) -> None:
        apply_op(self._data, payload)
        self.last_applied = index
