"""Worked examples built on the public raft_tpu API."""

from raft_tpu.examples.kv import ReplicatedKV

__all__ = ["ReplicatedKV"]
