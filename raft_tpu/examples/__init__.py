"""Worked examples built on the public raft_tpu API."""

from raft_tpu.examples.kv import ReplicatedKV
from raft_tpu.examples.kv_sharded import ShardedKV
from raft_tpu.examples.sessions import (
    ReplicatedCounter,
    SessionedStateMachine,
)

__all__ = [
    "ReplicatedKV", "ShardedKV", "ReplicatedCounter", "SessionedStateMachine",
]
