"""A key-sharded replicated KV store over G Raft groups — the
production-store shape (TiKV/CockroachDB style) on the multi-Raft
subsystem.

One ``ReplicatedKV`` tops out at its single group's commit stream;
``ShardedKV`` hashes every key onto one of G independent groups
(``multi.Router``), so G commit streams run concurrently — and on this
engine, *in the same batched device launches* (``multi.MultiEngine``).
The wire format and dict state machine are ``examples.kv``'s exactly
(``encode_op`` / ``apply_op``): a per-group shard of this store is
bitwise the single-group store over that group's log.

Usage:

    eng = MultiEngine(cfg, n_groups=4)
    eng.seed_leaders()                    # round-robin leader placement
    kv = ShardedKV(eng)
    g, seq = kv.set(b"color", b"green")
    eng.run_until_committed(g, seq)
    kv.get(b"color")                      # b"green"

Consistency contract per key (same as ``ReplicatedKV``, scoped to the
key's group): ``get`` serves LOCAL applied state — never an un-durable
write, but possibly stale; ``linearizable_get`` confirms the group's
leadership first (per-group ReadIndex) and reflects every write
acknowledged before it was issued. Cross-key (cross-group) reads carry
NO ordering relation — exactly the per-shard consistency a sharded
store offers.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from raft_tpu.examples.kv import _DELETE, _SET, apply_op, encode_op
from raft_tpu.multi.engine import MultiEngine
from raft_tpu.multi.router import Router


class ShardedKV:
    """Dict-shaped state machine sharded over G replicated logs."""

    def __init__(self, engine: MultiEngine, router: Optional[Router] = None,
                 replay: bool = False):
        self.engine = engine
        self.router = router if router is not None else Router(engine)
        self._data: List[Dict[bytes, bytes]] = [
            {} for _ in range(engine.G)
        ]
        self.last_applied = [0] * engine.G
        for g in range(engine.G):
            engine.register_apply(g, self._make_apply(g), replay=replay)

    def _make_apply(self, g: int):
        def _apply(index: int, payload: bytes) -> None:
            apply_op(self._data[g], payload)
            self.last_applied[g] = index
        return _apply

    # ------------------------------------------------------------ client
    def set(self, key: bytes, value: bytes) -> Tuple[int, int]:
        """Queue a SET on the key's group; returns ``(group, seq)``.
        Durable (and visible to ``get``) once
        ``engine.is_durable(group, seq)``."""
        return self.router.submit(
            key, encode_op(self.engine.cfg.entry_bytes, _SET, key, value)
        )

    def delete(self, key: bytes) -> Tuple[int, int]:
        return self.router.submit(
            key, encode_op(self.engine.cfg.entry_bytes, _DELETE, key, b"")
        )

    def set_many(
        self, items: Sequence[Tuple[bytes, bytes]]
    ) -> List[Tuple[int, int]]:
        """Batched SETs: group-bucketed through ``Router.submit_many``
        (one leadership check per group; same-tick replication batches
        across groups on device). Returns ``(group, seq)`` per item in
        input order."""
        eb = self.engine.cfg.entry_bytes
        return self.router.submit_many(
            [(k, encode_op(eb, _SET, k, v)) for k, v in items]
        )

    def get(self, key: bytes) -> Optional[bytes]:
        """Read the key's group-LOCAL applied state: never an un-durable
        write, but possibly stale (see module docstring)."""
        return self._data[self.router.group_of(key)].get(key)

    def linearizable_get(self, key: bytes) -> Optional[bytes]:
        """Linearizable read of one key: the key's group confirms
        leadership (per-group ReadIndex) and the value serves only from
        state applied to at least the read index. Raises
        ``multi.NotLeader`` (after the router's retries) when the group
        cannot confirm, ``RuntimeError`` if the apply stream lags the
        read index."""
        g, idx = self.router.read_index(key)
        if self.last_applied[g] < idx:
            raise RuntimeError(
                f"group {g} apply stream at {self.last_applied[g]} has "
                f"not reached read index {idx}"
            )
        return self._data[g].get(key)

    def get_many(self, keys: Sequence[bytes]) -> List[Optional[bytes]]:
        """Batched local reads, aligned with ``keys``."""
        return [self.get(k) for k in keys]

    def __len__(self) -> int:
        return sum(len(d) for d in self._data)
