"""Transaction log-entry encodings: the participant plane's wire format.

Cross-group transactions (docs/TXN.md) ride the groups' OWN replicated
logs as typed entries, extending ``examples.kv``'s op space (op 1 = SET,
op 2 = DELETE) with four transactional ops:

- ``OP_LOCK`` (3): prewrite — lock one key and stage its intent (the
  new value, or a delete, or nothing for a read-only lock) under a
  transaction id and a TTL deadline. First LOCK to APPLY wins the key:
  apply order is log order, so every replica resolves a prewrite race
  identically, and a coordinator learns it lost by finding someone
  else's lock where its own should be.
- ``OP_COMMIT`` (4) / ``OP_ABORT`` (5): release — roll the txn's locks
  in THIS group forward (apply staged intents) or back (discard them).
  Idempotent: releasing a txn that holds no locks is a no-op, so a
  resolver and a slow coordinator can both release safely.
- ``OP_DECIDE`` (6): the commit/abort decision record, replicated in
  the designated decision group only. First decision to apply wins
  (``TxnShardedKV`` ignores later ones), which is what makes
  coordinator crash-restore replay to the SAME verdict: the decision
  group's log is the single serialization point.

All four ops are invisible to the plain stores: ``kv.decode_op``
returns padding for op codes it does not speak and ``kv.apply_op``
no-ops them, so a log carrying txn entries replays byte-identically
through a plain ``ShardedKV`` / the read-audit feed (the txn-off
byte-identity pin in tests/test_txn.py).

Encodings (fixed-size entries, zero-padded like ``kv.encode_op``):

- LOCK:    ``[op u8][txn_id u32][deadline f64][flags u8][klen u8]
  [vlen u8][key][value]`` (16-byte header)
- COMMIT/ABORT: ``[op u8][txn_id u32]`` (5 bytes)
- DECIDE:  ``[op u8][txn_id u32][verdict u8][group_mask u32]``
  (10 bytes; the mask names the participant groups a resolver must
  release — G <= 32)
"""

from __future__ import annotations

import struct
from typing import NamedTuple, Optional

from raft_tpu.admission.gate import Overloaded

OP_LOCK = 3
OP_COMMIT = 4
OP_ABORT = 5
OP_DECIDE = 6

TXN_OPS = (OP_LOCK, OP_COMMIT, OP_ABORT, OP_DECIDE)

#: LOCK flag bits: the staged intent writes (else the lock is
#: read-only), and the write is a delete.
FLAG_WRITE = 0x01
FLAG_DELETE = 0x02

VERDICT_COMMIT = 1
VERDICT_ABORT = 2

_LOCK_HDR = struct.Struct("<BIdBBB")     # 16 bytes
_REL_HDR = struct.Struct("<BI")          # 5 bytes
_DEC_HDR = struct.Struct("<BIBI")        # 10 bytes


class LockConflict(Overloaded):
    """A transactional submit refused because a LIVE lock held by
    another transaction covers one of its keys. Raised BEFORE anything
    is queued — the admission gate's provably-no-effect contract, which
    is exactly what lets the serializability checker grade a refused
    transaction as a no-op. ``retry_after_s`` hints the remaining lock
    TTL (the earliest the conflict can possibly clear without a
    decision)."""

    def __init__(self, key: bytes, holder: int, retry_after_s: float,
                 group: Optional[int] = None):
        super().__init__(
            "txn_lock", retry_after_s,
            detail=f"key {key!r} locked by txn {holder}", group=group,
        )
        self.key = key
        self.holder = holder


class LockRecord(NamedTuple):
    """One decoded LOCK entry."""

    txn_id: int
    deadline: float
    flags: int
    key: bytes
    value: bytes


class DecisionRecord(NamedTuple):
    """One decoded DECIDE entry."""

    txn_id: int
    commit: bool
    group_mask: int


def _pad(entry_bytes: int, body: bytes) -> bytes:
    if len(body) > entry_bytes:
        raise ValueError(
            f"txn op needs {len(body)} bytes, entries are {entry_bytes}"
        )
    return body + bytes(entry_bytes - len(body))


def encode_lock(entry_bytes: int, txn_id: int, key: bytes,
                value: Optional[bytes], deadline: float,
                delete: bool = False) -> bytes:
    """One prewrite entry. ``value=None`` stages no write (a read-only
    lock) unless ``delete`` is set."""
    flags = 0
    staged = b""
    if delete:
        flags = FLAG_WRITE | FLAG_DELETE
    elif value is not None:
        flags = FLAG_WRITE
        staged = value
    if len(key) > 0xFF or len(staged) > 0xFF:
        raise ValueError("txn keys/values are limited to 255 bytes")
    body = _LOCK_HDR.pack(OP_LOCK, txn_id, deadline, flags,
                          len(key), len(staged)) + key + staged
    return _pad(entry_bytes, body)


def encode_release(entry_bytes: int, commit: bool, txn_id: int) -> bytes:
    """One release entry: roll the txn's locks in the receiving group
    forward (``commit=True``) or back."""
    return _pad(entry_bytes, _REL_HDR.pack(
        OP_COMMIT if commit else OP_ABORT, txn_id
    ))


def encode_decision(entry_bytes: int, txn_id: int, commit: bool,
                    group_mask: int) -> bytes:
    """The replicated decision record (decision group only)."""
    return _pad(entry_bytes, _DEC_HDR.pack(
        OP_DECIDE, txn_id,
        VERDICT_COMMIT if commit else VERDICT_ABORT, group_mask,
    ))


def decode_lock(payload: bytes) -> LockRecord:
    op, txn_id, deadline, flags, klen, vlen = _LOCK_HDR.unpack_from(
        payload
    )
    off = _LOCK_HDR.size
    return LockRecord(txn_id, deadline, flags,
                      payload[off:off + klen],
                      payload[off + klen:off + klen + vlen])


def decode_release(payload: bytes):
    """``(commit, txn_id)``."""
    op, txn_id = _REL_HDR.unpack_from(payload)
    return op == OP_COMMIT, txn_id


def decode_decision(payload: bytes) -> DecisionRecord:
    op, txn_id, verdict, mask = _DEC_HDR.unpack_from(payload)
    return DecisionRecord(txn_id, verdict == VERDICT_COMMIT, mask)
