"""The participant plane: a lock-aware sharded KV state machine.

``TxnShardedKV`` extends ``examples.kv_sharded.ShardedKV`` with the
four transactional ops of ``txn.ops`` — locks and staged intents are
REPLICATED state (they live in the groups' own logs and rebuild on
replay exactly like the data), so participant crash recovery falls out
of machinery that already exists rather than a side-channel.

Per-group lock table semantics (all pure functions of log order, so
every replica converges):

- ``OP_LOCK``: first lock to apply wins the key. A later LOCK by a
  DIFFERENT txn applies as nothing — the losing coordinator discovers
  the loss at validation (``lock_owned``) and must abort. A re-applied
  LOCK by the same txn refreshes the staged intent (idempotent).
- ``OP_COMMIT``: every lock held by the txn in this group rolls
  forward — staged writes/deletes land in the data map — and releases.
- ``OP_ABORT``: the txn's locks release, intents discarded.
- ``OP_DECIDE`` (decision group only): first decision for a txn id
  wins; later ones are ignored. The decision's APPLY POSITION is the
  transaction's serialization point — ``decision()`` returns it, and
  the serializability checker replays committed transactions in
  exactly this order (the commit-order witness).

Plain ops (SET/DELETE) apply unchanged. ``set``/``delete`` on a key
under a LIVE foreign lock refuse with :class:`txn.ops.LockConflict`
before anything queues — a best-effort gate against applied state (a
lock that lands between the check and the apply is the usual admission
race; mixed workloads that need strict exclusion route writes through
transactions, docs/TXN.md).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from raft_tpu.examples.kv import apply_op
from raft_tpu.examples.kv_sharded import ShardedKV
from raft_tpu.txn import ops as T


class Lock:
    """One held lock: owner txn, TTL deadline, staged intent."""

    __slots__ = ("txn_id", "deadline", "flags", "value")

    def __init__(self, txn_id: int, deadline: float, flags: int,
                 value: bytes):
        self.txn_id = txn_id
        self.deadline = deadline
        self.flags = flags
        self.value = value

    def expired(self, now: float) -> bool:
        return now >= self.deadline


class TxnShardedKV(ShardedKV):
    """Sharded KV + replicated per-group lock tables + the decision
    map (module docstring). ``decision_group`` names the Raft group
    that carries ``OP_DECIDE`` records; everything else about the
    store is ``ShardedKV``."""

    def __init__(self, engine, router=None, replay: bool = False,
                 decision_group: int = 0, broken: Optional[str] = None):
        # state the apply closures touch must exist BEFORE the base
        # constructor registers them (replay=True applies immediately)
        self.locks: List[Dict[bytes, Lock]] = [
            {} for _ in range(engine.G)
        ]
        self._decisions: Dict[int, Tuple[bool, int, int]] = {}
        self._decision_pos = 0
        self.decision_group = decision_group
        self.locks_acquired = 0
        self.locks_lost = 0
        self.broken = broken
        #   "txn_dirty_read": reads serve STAGED lock intents — the
        #   read-uncommitted fault the serializability checker must
        #   catch (chaos --broken txn_dirty_read)
        self._replaying = replay
        super().__init__(engine, router, replay)
        self._replaying = False

    # ------------------------------------------------------ state machine
    def _make_apply(self, g: int):
        def _apply(index: int, payload: bytes) -> None:
            op = payload[0] if payload else 0
            if op in T.TXN_OPS:
                self._apply_txn(g, payload)
            else:
                apply_op(self._data[g], payload)
            self.last_applied[g] = index
        return _apply

    def _apply_txn(self, g: int, payload: bytes) -> None:
        op = payload[0]
        if op == T.OP_LOCK:
            rec = T.decode_lock(payload)
            cur = self.locks[g].get(rec.key)
            if cur is None or cur.txn_id == rec.txn_id:
                self.locks[g][rec.key] = Lock(
                    rec.txn_id, rec.deadline, rec.flags, rec.value
                )
                if cur is None:
                    self.locks_acquired += 1
                    if not self._replaying:
                        self.engine._metric_inc(
                            g, "raft_txn_locks_total",
                            "txn locks acquired (replicated apply)",
                        )
            else:
                self.locks_lost += 1       # first lock won; this one
                return                     # applies as nothing
        elif op in (T.OP_COMMIT, T.OP_ABORT):
            commit, txn_id = T.decode_release(payload)
            held = [k for k, lk in self.locks[g].items()
                    if lk.txn_id == txn_id]
            for k in held:
                lk = self.locks[g].pop(k)
                if commit and lk.flags & T.FLAG_WRITE:
                    if lk.flags & T.FLAG_DELETE:
                        self._data[g].pop(k, None)
                    else:
                        self._data[g][k] = lk.value
        elif op == T.OP_DECIDE:
            rec = T.decode_decision(payload)
            if rec.txn_id not in self._decisions:
                # first decision wins — a replay, a duplicate submit or
                # a racing resolver all converge to the same verdict
                self._decisions[rec.txn_id] = (
                    rec.commit, rec.group_mask, self._decision_pos
                )
                self._decision_pos += 1

    # ------------------------------------------------------------- queries
    def decision(self, txn_id: int):
        """``(commit, group_mask, position)`` for a decided txn, else
        None. ``position`` is the decision's apply order in the
        decision group — the commit-order witness the checker replays."""
        return self._decisions.get(txn_id)

    def lock_of(self, key: bytes) -> Tuple[int, Optional[Lock]]:
        g = self.router.group_of(key)
        return g, self.locks[g].get(key)

    def lock_owned(self, txn_id: int, key: bytes) -> bool:
        _, lk = self.lock_of(key)
        return lk is not None and lk.txn_id == txn_id

    def blocking_lock(self, key: bytes, txn_id: int, now: float):
        """The LIVE foreign lock covering ``key``, else None. Expired
        locks do not block (the TTL path resolves them); own locks do
        not block."""
        g, lk = self.lock_of(key)
        if (lk is None or lk.txn_id == txn_id or lk.expired(now)):
            return None
        return lk

    def lock_stats(self) -> dict:
        return {
            "held": sum(len(t) for t in self.locks),
            "acquired": self.locks_acquired,
            "lost": self.locks_lost,
            "decisions": len(self._decisions),
        }

    # ------------------------------------------------------------- client
    def get(self, key: bytes) -> Optional[bytes]:
        if self.broken == "txn_dirty_read":
            g, lk = self.lock_of(key)
            if lk is not None and lk.flags & T.FLAG_WRITE:
                # BROKEN: serve the staged, UNCOMMITTED intent
                return (None if lk.flags & T.FLAG_DELETE else lk.value)
        return super().get(key)

    def set(self, key: bytes, value: bytes) -> Tuple[int, int]:
        self._refuse_if_locked(key)
        return super().set(key, value)

    def delete(self, key: bytes) -> Tuple[int, int]:
        self._refuse_if_locked(key)
        return super().delete(key)

    def _refuse_if_locked(self, key: bytes) -> None:
        now = self.engine.clock.now
        lk = self.blocking_lock(key, -1, now)
        if lk is not None:
            g = self.router.group_of(key)
            raise T.LockConflict(
                key, lk.txn_id,
                max(lk.deadline - now, self.engine.cfg.heartbeat_period),
                group=g,
            )
