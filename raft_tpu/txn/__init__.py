"""Cross-group transactions: a replicated 2PC coordinator plane over
multi-Raft (docs/TXN.md).

- ``txn.ops`` — transactional log-entry encodings (LOCK / COMMIT /
  ABORT / DECIDE) extending ``examples.kv``'s op space, plus the typed
  :class:`LockConflict` refusal.
- ``txn.store`` — :class:`TxnShardedKV`: the participant plane (per-
  group replicated lock tables, staged intents, the decision map).
- ``txn.coordinator`` — :class:`TxnCoordinator`: pollable BEGIN →
  prewrite fan-out → replicated decision → release, with the TTL /
  status-check resolver for dead coordinators.
"""

from raft_tpu.txn.coordinator import TxnCoordinator, TxnHandle, TxnItem
from raft_tpu.txn.ops import LockConflict
from raft_tpu.txn.store import TxnShardedKV

__all__ = [
    "LockConflict",
    "TxnCoordinator",
    "TxnHandle",
    "TxnItem",
    "TxnShardedKV",
]
